"""Memory observatory: object-plane lifecycle, arena introspection, and
leak attribution.

The five observability planes (chaos/profiling/metrics/logs/steptrace)
watch the control plane and the training loop; this one lights up the
OBJECT plane — the repo's strongest perf axis since the slab arena —
answering "what objects exist, who owns them, where do their bytes
live, and why is the store full". Per process it keeps

- a **creation table**: every ``put()`` stamps the creating user-code
  callsite (one bounded frame walk), size, kind, and timestamp, so a
  driver-side leak groups by the line that made it;
- a **flow ring**: bounded spill/restore/push/fetch events with bytes,
  latency, and the transfer path — ``arena`` (bytes never left slab
  memory) vs ``heap`` (chunk assembly through heap buffers, the copy
  the ROADMAP's receive-side slab assembly exists to remove) vs
  ``file`` (one-file ``.obj`` interop).

Metrics-core discipline applies: ``record_*`` is a flag load + a dict/
list store, and the whole plane is gated by ``RAY_TPU_MEMVIEW_ENABLED=0``
/ cfg ``memview_enabled`` so it costs nothing when off. The bench lane
(BENCH_MEMVIEW_OVERHEAD=1) gates the tracking share of the put/get hot
path <2% and asserts zero records when disabled.

The owner-side store ledger (object_store.LocalObjectStore) is the
ground truth for resident bytes: ``arena_introspect()`` reports
per-segment occupancy, live/dead entry counts, and **dead byte ranges**
— the literal input to future ``fallocate(PUNCH_HOLE)`` reclamation —
plus recycling-pool and per-client slab charges. The fan-out rides the
proven worker→raylet→GCS snapshot pattern (``memview_snapshot`` /
``memview_node`` / ``memview_cluster``) and ``merge_cluster`` joins
store rows with every process's reference tables into lifecycle rows
and **verdicts**: objects resident yet referenced by nobody (leaks,
grouped by creation callsite), pool segments pinned only by a reader's
SHARED flock (with the pinning pids from /proc/locks), and capacity
overshoot attributed to its cause (register_external fallback writes vs
untracked restores) instead of a raw counter.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "set_enabled", "is_enabled", "record_calls", "reset",
    "callsite_tag", "record_put", "forget_put", "put_info", "puts_table",
    "record_flow", "flow_snapshot", "process_snapshot",
    "coalesce_ranges", "segment_stats", "flock_holders",
    "merge_cluster", "group_objects", "leak_verdicts", "pressure_verdicts",
]

_enabled = os.environ.get("RAY_TPU_MEMVIEW_ENABLED", "1").lower() not in (
    "0", "false", "no")
_explicit = False  # set_enabled() was called: runtime override wins
# instrumentation event count (the bench lane's calibrated-cost x count
# estimator multiplies this, same discipline as steptrace._events)
_events = 0

_TRACK_DEFAULT = 8192
_FLOW_DEFAULT = 2048

_lock = threading.Lock()
# oid bytes -> (callsite, wall ts, nbytes, kind); bounded FIFO — the
# owner table, not the store ledger: it exists for callsite/age/refcount
# attribution, and an evicted entry degrades a row to "callsite unknown"
_puts: "OrderedDict[bytes, tuple]" = OrderedDict()
_puts_max = 0

_flow_ring: List[Any] = []
_flow_size = 0
_flow_idx = 0  # monotonic per-process flow index (slot = idx % size)


def _fold_cfg():
    """Fold cfg ``memview_enabled`` (itself env-overridable as
    ``RAY_TPU_memview_enabled``) into the flag — the documented kill
    switch must gate the record paths, not just the surfaces. An
    explicit set_enabled() always wins."""
    global _enabled
    if _explicit:
        return
    try:
        from ray_tpu._private.config import GLOBAL_CONFIG

        if not GLOBAL_CONFIG.memview_enabled:
            _enabled = False
    except Exception:
        pass


_fold_cfg()


def set_enabled(flag: bool):
    global _enabled, _explicit
    _explicit = True
    _enabled = bool(flag)


def is_enabled() -> bool:
    _fold_cfg()
    return _enabled


def record_calls() -> int:
    """Total record_* calls in this process since import (the overhead
    lane's event count)."""
    return _events


def reset():
    """Drop all records and counters (tests / bench phases)."""
    global _flow_ring, _flow_size, _flow_idx, _puts_max, _events
    with _lock:
        _puts.clear()
        _puts_max = 0
        _ext_pins.clear()
    _flow_ring = []
    _flow_size = 0
    _flow_idx = 0
    _events = 0


# ---------------------------------------------------------------------------
# external pins: store-resident bytes a process holds OUTSIDE the
# ObjectRef world (e.g. a serving replica's arena-backed KV pages).
# Pinned oids join the process's ``referenced`` snapshot set, so the
# cluster merge sees the holder — an unpinned-yet-undeleted page then
# ages into a leak verdict exactly like an unreferenced object.
# ---------------------------------------------------------------------------

_ext_pins: set = set()


def pin_external(oid: bytes):
    with _lock:
        _ext_pins.add(bytes(oid))


def unpin_external(oid: bytes):
    with _lock:
        _ext_pins.discard(bytes(oid))


def external_pins() -> list:
    with _lock:
        return list(_ext_pins)


def _limits():
    global _puts_max, _flow_ring, _flow_size
    if _puts_max == 0:
        _fold_cfg()  # late system_config overrides land before any write
        track, flow = _TRACK_DEFAULT, _FLOW_DEFAULT
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG

            track = int(GLOBAL_CONFIG.memview_track_max)
            flow = int(GLOBAL_CONFIG.memview_flow_ring_size)
        except Exception:
            pass
        _puts_max = max(16, track)
        _flow_ring = [None] * max(16, flow)
        _flow_size = len(_flow_ring)


# ---------------------------------------------------------------------------
# creation-site table (worker-side; stamped at put())
# ---------------------------------------------------------------------------

def callsite_tag(skip: int = 2) -> Optional[str]:
    """First stack frame OUTSIDE ray_tpu internals, as
    ``dir/file.py:line in fn`` — the user line that created the object.
    Bounded walk (puts are ~100µs+; this is ~1µs for typical depths)."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return None
    depth = 0
    while f is not None and depth < 32:
        fn = f.f_code.co_filename
        if "ray_tpu" not in fn:
            parts = fn.replace("\\", "/").rsplit("/", 2)
            short = "/".join(parts[-2:]) if len(parts) > 1 else fn
            return f"{short}:{f.f_lineno} in {f.f_code.co_name}"
        f = f.f_back
        depth += 1
    return None


def record_put(oid: bytes, nbytes: int, kind: str = "put",
               callsite: Optional[str] = None):
    """Stamp an object's creation: callsite + wall time + size. Hot path
    of every ``put()`` — flag load, frame walk, one dict store."""
    global _events
    if not _enabled:
        return
    _limits()
    if not _enabled:  # late config override folded in by _limits
        return
    _events += 1
    # start at our caller: the ray_tpu-frame skip inside callsite_tag
    # walks the rest of the way out of runtime internals
    site = callsite_tag(2) if callsite is None else callsite
    with _lock:
        _puts[oid] = (site, time.time(), int(nbytes), kind)
        while len(_puts) > _puts_max:
            _puts.popitem(last=False)  # bounded FIFO


def forget_put(oid: bytes):
    """The owner freed the object: drop its creation record (an entry
    surviving its object would read as a leak candidate forever)."""
    if not _puts:
        return
    with _lock:
        _puts.pop(oid, None)


def put_info(oid: bytes) -> Optional[tuple]:
    """(callsite, ts, nbytes, kind) or None."""
    return _puts.get(oid)


def puts_table() -> Dict[bytes, tuple]:
    with _lock:
        return dict(_puts)


# ---------------------------------------------------------------------------
# flow ring (spill/restore/push/fetch events)
# ---------------------------------------------------------------------------

def record_flow(kind: str, nbytes: int, dur_s: float, path: str,
                oid_hex: Optional[str] = None):
    """One object-plane transfer event. ``kind`` is spill/restore/
    fetch/push/push_rx/punch; ``path`` is where the bytes travelled:
    "arena" (bytes never left slab memory — zero-copy sends, receive-
    side slab assembly, hole punches), "heap" (chunk assembly through
    heap buffers: the legacy/native-fallback receive path), "file"
    (one-file .obj interop)."""
    global _events, _flow_idx
    if not _enabled:
        return
    _limits()
    if not _enabled:
        return
    _events += 1
    _flow_ring[_flow_idx % _flow_size] = (
        kind, _flow_idx, time.time(), int(nbytes), float(dur_s), path,
        oid_hex)
    _flow_idx += 1


def flow_snapshot() -> List[dict]:
    """Ring contents as dicts, oldest first."""
    if _flow_idx == 0:
        return []
    ring, size, idx = _flow_ring, _flow_size, _flow_idx
    raw = ring[:idx] if idx <= size else \
        ring[idx % size:] + ring[: idx % size]
    out = []
    for rec in raw:
        if rec is None:  # torn slot mid-wrap: skip, never corrupt
            continue
        out.append({"kind": rec[0], "idx": rec[1], "ts": rec[2],
                    "bytes": rec[3], "dur_s": rec[4], "path": rec[5],
                    "object_id": rec[6]})
    return out


def process_snapshot(extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """The ``memview_snapshot`` RPC payload skeleton: flow ring + event
    count + identity. Callers (worker/raylet) add their ``owned`` /
    ``referenced`` / ``store`` tables via ``extra``."""
    out: Dict[str, Any] = {
        "pid": os.getpid(),
        "flows": flow_snapshot(),
        "flow_dropped": max(0, _flow_idx - _flow_size) if _flow_size else 0,
        "record_calls": _events,
    }
    if extra:
        out.update(extra)
    return out


# ---------------------------------------------------------------------------
# pure helpers: dead-range math, scan-based segment stats, flock holders
# ---------------------------------------------------------------------------

def coalesce_ranges(ranges: Iterable[Tuple[int, int]]
                    ) -> List[Tuple[int, int]]:
    """Merge (offset, length) ranges into sorted, maximal runs — the
    shape the hole-punch pass (``object_store.punch_holes``) punches.
    Adjacent and overlapping ranges fuse; order of the input doesn't
    matter."""
    out: List[List[int]] = []
    for off, length in sorted(ranges):
        if length <= 0:
            continue
        if out and off <= out[-1][0] + out[-1][1]:
            out[-1][1] = max(out[-1][1], off + length - out[-1][0])
        else:
            out.append([off, length])
    return [(o, n) for o, n in out]


def segment_stats(path: str) -> Dict[str, Any]:
    """Scan-based ground truth for one slab segment file (the arena is
    authoritative over any ledger): live/dead entry counts and bytes,
    coalesced dead ranges, and the bump-allocation end offset."""
    from ray_tpu._private import slab_arena

    live = dead = live_bytes = dead_bytes = end = 0
    dead_ranges: List[Tuple[int, int]] = []
    for _oid, off, _ml, _dl, total, is_dead in slab_arena.scan_segment(path):
        end = off + total
        if is_dead:
            dead += 1
            dead_bytes += total
            dead_ranges.append((off, total))
        else:
            live += 1
            live_bytes += total
    return {
        "live_entries": live, "dead_entries": dead,
        "live_bytes": live_bytes, "dead_bytes": dead_bytes,
        "dead_ranges": coalesce_ranges(dead_ranges), "end": end,
        "fragmentation": dead_bytes / (live_bytes + dead_bytes)
        if (live_bytes + dead_bytes) else 0.0,
    }


def flock_holders(path: str) -> List[int]:
    """Pids holding a flock on ``path``, from /proc/locks (Linux; best
    effort elsewhere). This is how a recycling-pool segment stuck behind
    a reader's SHARED flock names its pinner."""
    try:
        st = os.stat(path)
    except OSError:
        return []
    want = f"{os.major(st.st_dev):02x}:{os.minor(st.st_dev):02x}:" \
           f"{st.st_ino}"
    pids: List[int] = []
    try:
        with open("/proc/locks") as f:
            for line in f:
                # "1: FLOCK ADVISORY WRITE 4242 08:01:123456 0 EOF"
                parts = line.split()
                if len(parts) >= 6 and parts[1] == "FLOCK" \
                        and parts[5] == want:
                    try:
                        pids.append(int(parts[4]))
                    except ValueError:
                        continue
    except OSError:
        return []
    return sorted(set(pids))


# ---------------------------------------------------------------------------
# cluster merge + verdicts (GCS-side; pure functions, unit-testable)
# ---------------------------------------------------------------------------

# a store-resident object younger than this is never called a leak: its
# owner's reference may simply not have reached the scrape yet (put
# report in flight, snapshot raced)
LEAK_MIN_AGE_S = 30.0


def merge_cluster(processes: Sequence[dict],
                  locations: Optional[Dict[str, list]] = None,
                  flow_limit: int = 500) -> Dict[str, Any]:
    """Fold per-process memview snapshots into one cluster view.

    Store rows (from each raylet's ledger) are joined with every
    process's owner tables: an object row gains its owner's refcount,
    pins, creation callsite, and age; objects living only inline in an
    owner's memory store appear as ``state="inlined"`` rows. The union
    of every process's reference tables gives the reachability set the
    leak verdicts test against.
    """
    objects: Dict[str, dict] = {}
    arenas: List[dict] = []
    flows: List[dict] = []
    referenced: set = set()
    owners: Dict[str, dict] = {}
    scrape_errors = 0
    for proc in processes:
        if proc.get("error"):
            scrape_errors += 1
            continue
        node = proc.get("node_id")
        owner_id = proc.get("client_id") or str(node)
        for oid_hex in proc.get("referenced", ()):
            referenced.add(oid_hex)
        for row in proc.get("owned", ()):
            owners[row["object_id"]] = dict(row, owner=owner_id)
        for fl in proc.get("flows", ()):
            flows.append(dict(fl, node_id=node, pid=proc.get("pid")))
        store = proc.get("store")
        if store:
            # arena=None means the node's store has no introspection
            # surface (native C++ store): no row — a phantom all-zero
            # arena would read as "healthy and empty" in triage output
            if store.get("arena") is not None:
                arenas.append(dict(store["arena"], node_id=node))
            for row in store.get("objects", ()):
                r = objects.get(row["object_id"])
                if r is None:
                    r = objects[row["object_id"]] = dict(row)
                    r["nodes"] = []
                r["nodes"].append(node)
                # a spilled copy elsewhere must not mask a live one
                if row.get("state") == "arena":
                    r["state"] = "arena"
                r["size"] = max(r.get("size") or 0, row.get("size") or 0)
    for oid_hex, own in owners.items():
        if oid_hex not in objects and own.get("inlined"):
            objects[oid_hex] = {
                "object_id": oid_hex, "state": "inlined",
                "size": own.get("size") or 0, "nodes": [],
            }
    rows: List[dict] = []
    for oid_hex, r in objects.items():
        own = owners.get(oid_hex)
        if own is not None:
            r["owner"] = own.get("owner")
            r["refs"] = own.get("refs", 0)
            r["pins"] = max(r.get("pins") or 0, own.get("pins") or 0)
            if own.get("callsite"):
                r["callsite"] = own["callsite"]
            if r.get("age_s") is None and own.get("age_s") is not None:
                r["age_s"] = own["age_s"]
        r["referenced"] = oid_hex in referenced
        if locations is not None and oid_hex in locations:
            r["locations"] = locations[oid_hex]
        rows.append(r)
    rows.sort(key=lambda r: -(r.get("size") or 0))
    totals: Dict[str, dict] = {}
    for r in rows:
        t = totals.setdefault(r.get("state") or "?",
                              {"count": 0, "bytes": 0})
        t["count"] += 1
        t["bytes"] += r.get("size") or 0
    flows.sort(key=lambda f: f.get("ts") or 0)
    verdicts = leak_verdicts(rows, complete=(scrape_errors == 0)) \
        + pressure_verdicts(arenas)
    return {
        "objects": rows,
        "arenas": arenas,
        "flows": flows[-flow_limit:],
        "totals": totals,
        "verdicts": verdicts,
        "referenced_count": len(referenced),
        "scrape_errors": scrape_errors,
    }


def leak_verdicts(rows: Sequence[dict], complete: bool = True,
                  min_age_s: float = LEAK_MIN_AGE_S) -> List[dict]:
    """Objects resident in a store yet referenced by NO process in the
    scrape: unreachable-yet-undeleted. Age-gated (a fresh put's report
    may still be in flight) and downgraded to suspected when part of the
    cluster didn't answer (an unreachable owner is not a dead owner)."""
    out = []
    for r in rows:
        if r.get("state") == "inlined" or r.get("referenced"):
            continue
        age = r.get("age_s")
        if age is not None and age < min_age_s:
            continue
        out.append({
            "kind": "leak",
            "confidence": "likely" if complete else "suspected",
            "object_id": r["object_id"],
            "bytes": r.get("size") or 0,
            "state": r.get("state"),
            "nodes": r.get("nodes") or [],
            "callsite": r.get("callsite"),
            "age_s": age,
            "detail": "resident but referenced by no live process"
                      + ("" if complete
                         else " (scrape incomplete: owner may be"
                              " unreachable, not gone)"),
        })
    return out


def pressure_verdicts(arenas: Sequence[dict]) -> List[dict]:
    """Per-node store-pressure attribution: capacity overshoot named by
    cause, pool segments pinned only by reader flocks (with pids), and
    heavy fragmentation (dead ranges are hole-punch candidates)."""
    out: List[dict] = []
    for a in arenas:
        node = a.get("node_id")
        spilled = a.get("spilled") or {}
        by_cause = spilled.get("overshoot_by_cause") or {}
        for cause, nbytes in sorted(by_cause.items()):
            if nbytes:
                out.append({
                    "kind": "overshoot", "node_id": node, "bytes": nbytes,
                    "cause": cause,
                    "detail": {
                        "register_external":
                            "one-file fallback writes landed past "
                            "capacity (lease denied or legacy path)",
                        "untracked_restore":
                            "a predecessor raylet's spilled objects "
                            "restored into an already-full store",
                    }.get(cause, cause),
                })
        for ent in a.get("pool_pinned") or ():
            out.append({
                "kind": "pinned_segment", "node_id": node,
                "bytes": ent.get("charged") or ent.get("file_size") or 0,
                "file": ent.get("file"),
                "holder_pids": ent.get("holder_pids") or [],
                "detail": "recycling-pool segment kept alive only by a "
                          "reader's SHARED flock — a stuck zero-copy "
                          "view pins its pages",
            })
        dead = a.get("dead_bytes") or 0
        live = a.get("live_bytes") or 0
        if dead and dead >= max(live, 1):
            out.append({
                "kind": "fragmentation", "node_id": node, "bytes": dead,
                "fragmentation": dead / (dead + live) if dead + live else 0.0,
                "detail": "over half the resident slab bytes are dead "
                          "entries inside live segments — hole-punch "
                          "reclamation candidates (see dead_ranges)",
            })
    return out


def group_objects(rows: Sequence[dict], by: str) -> List[dict]:
    """Aggregate object rows by callsite / node / owner / state:
    ``[{key, count, bytes}]`` sorted biggest first."""
    if by not in ("callsite", "node", "owner", "state"):
        raise ValueError(f"group_by must be callsite|node|owner|state, "
                         f"got {by!r}")

    def key_of(r: dict) -> str:
        if by == "node":
            nodes = r.get("nodes") or []
            return str(nodes[0])[:12] if nodes else "(no node)"
        v = r.get(by)
        return str(v) if v else f"(unknown {by})"

    groups: Dict[str, dict] = {}
    for r in rows:
        g = groups.setdefault(key_of(r), {"count": 0, "bytes": 0})
        g["count"] += 1
        g["bytes"] += r.get("size") or 0
    return sorted(
        ({"key": k, **v} for k, v in groups.items()),
        key=lambda g: (-g["bytes"], g["key"]),
    )
