"""Pluggable GCS persistence.

Analog of the reference's StoreClient family
(ray: src/ray/gcs/store_client/in_memory_store_client.h,
redis_store_client.h; typed tables gcs_table_storage.h:50,248). The
reference persists GCS tables to Redis so a restarted GCS replays state
(`gcs_init_data.h`) and clients resubscribe. TPU-native we use an
append-only log file on the head node (Redis isn't a baked-in dependency);
the interface is small enough that a Redis/etcd client drops in.

Records are length-prefixed pickles of ``(table, key, value)`` where
``value=None`` tombstones the key. ``load()`` replays the log into
``{table: {key: value}}`` and compacts it (rewrites live records only), so
the log stays proportional to live state, not mutation count.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Dict, Optional

_LEN = struct.Struct("<I")


class NullStore:
    """In-memory GCS: nothing survives restart (the default)."""

    def load(self) -> Dict[str, dict]:
        return {}

    def put(self, table: str, key, value) -> None:
        pass

    def close(self) -> None:
        pass


class FileLogStore:
    """Append-only log with replay + compaction on load."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = None

    def load(self) -> Dict[str, dict]:
        tables: Dict[str, dict] = {}
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                while True:
                    header = f.read(_LEN.size)
                    if len(header) < _LEN.size:
                        break
                    (n,) = _LEN.unpack(header)
                    blob = f.read(n)
                    if len(blob) < n:  # torn tail write: stop replay here
                        break
                    try:
                        table, key, value = pickle.loads(blob)
                    except Exception:
                        break
                    if value is None:
                        tables.get(table, {}).pop(key, None)
                    else:
                        tables.setdefault(table, {})[key] = value
        self._compact(tables)
        return tables

    def _compact(self, tables: Dict[str, dict]) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for table, entries in tables.items():
                for key, value in entries.items():
                    blob = pickle.dumps((table, key, value), protocol=5)
                    f.write(_LEN.pack(len(blob)))
                    f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def put(self, table: str, key, value) -> None:
        if self._f is None:
            self._f = open(self.path, "ab")
        blob = pickle.dumps((table, key, value), protocol=5)
        with self._lock:
            self._f.write(_LEN.pack(len(blob)))
            self._f.write(blob)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def make_store(persist_path: Optional[str]):
    return FileLogStore(persist_path) if persist_path else NullStore()
