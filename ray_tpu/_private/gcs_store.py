"""Pluggable GCS persistence.

Analog of the reference's StoreClient family
(ray: src/ray/gcs/store_client/in_memory_store_client.h,
redis_store_client.h; typed tables gcs_table_storage.h:50,248). The
reference persists GCS tables to Redis so a restarted GCS replays state
(`gcs_init_data.h`) and clients resubscribe. TPU-native we use an
append-only log file on the head node (Redis isn't a baked-in dependency);
the interface is small enough that a Redis/etcd client drops in.

Records are length-prefixed pickles of ``(table, key, value)`` where
``value=None`` tombstones the key. ``load()`` replays the log into
``{table: {key: value}}`` and compacts it (rewrites live records only), so
the log stays proportional to live state, not mutation count.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Dict, Optional

_LEN = struct.Struct("<I")


class NullStore:
    """In-memory GCS: nothing survives restart (the default)."""

    def load(self) -> Dict[str, dict]:
        return {}

    def put(self, table: str, key, value) -> None:
        pass

    def close(self) -> None:
        pass


class FileLogStore:
    """Append-only log with replay + compaction on load."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = None

    # first bytes of a native-store (src/log_store.cpp) file — this store
    # must refuse it rather than compact it down to nothing
    NATIVE_MAGIC = b"RTPULG02"

    def load(self) -> Dict[str, dict]:
        tables: Dict[str, dict] = {}
        if os.path.exists(self.path):
            with open(self.path, "rb") as probe:
                if probe.read(8) == self.NATIVE_MAGIC:
                    raise RuntimeError(
                        f"{self.path} was written by the native log store "
                        "but the native library is unavailable; rebuild "
                        "src/ (make -C src) or move the file aside"
                    )
            with open(self.path, "rb") as f:
                while True:
                    header = f.read(_LEN.size)
                    if len(header) < _LEN.size:
                        break
                    (n,) = _LEN.unpack(header)
                    blob = f.read(n)
                    if len(blob) < n:  # torn tail write: stop replay here
                        break
                    try:
                        table, key, value = pickle.loads(blob)
                    except Exception:
                        break
                    if value is None:
                        tables.get(table, {}).pop(key, None)
                    else:
                        tables.setdefault(table, {})[key] = value
        self._compact(tables)
        return tables

    def _compact(self, tables: Dict[str, dict]) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for table, entries in tables.items():
                for key, value in entries.items():
                    blob = pickle.dumps((table, key, value), protocol=5)
                    f.write(_LEN.pack(len(blob)))
                    f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def put(self, table: str, key, value) -> None:
        if self._f is None:
            self._f = open(self.path, "ab")
        blob = pickle.dumps((table, key, value), protocol=5)
        with self._lock:
            self._f.write(_LEN.pack(len(blob)))
            self._f.write(blob)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class NativeLogStore:
    """C++ append-log store (src/log_store.cpp) behind the same interface:
    native framing, torn-tail truncation, and compaction; keys/values stay
    pickled by this layer (opaque bytes to C++). Reference analog: the
    RedisStoreClient persistence role, collapsed to a local log."""

    def __init__(self, path: str, fsync: bool = False):
        import ctypes

        from ray_tpu._private import native_store

        lib = native_store.load_library()
        if lib is None or not getattr(lib, "_has_log_store", False):
            raise OSError("native library lacks the log store")
        self._lib = lib
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._h = ctypes.c_void_p(
            lib.rtpu_log_open(path.encode(), 1 if fsync else 0)
        )
        if not self._h:
            raise OSError(f"native log store failed to open {path}")

    def load(self) -> Dict[str, dict]:
        import ctypes

        if not self._h:
            raise OSError("native log store is closed")
        tables: Dict[str, dict] = {}
        lib = self._lib
        lib.rtpu_log_iter_start(self._h)
        t = ctypes.POINTER(ctypes.c_uint8)()
        k = ctypes.POINTER(ctypes.c_uint8)()
        v = ctypes.POINTER(ctypes.c_uint8)()
        tl = ctypes.c_uint64()
        kl = ctypes.c_uint64()
        vl = ctypes.c_uint64()
        while lib.rtpu_log_iter_next(
            self._h, ctypes.byref(t), ctypes.byref(tl), ctypes.byref(k),
            ctypes.byref(kl), ctypes.byref(v), ctypes.byref(vl),
        ):
            table = ctypes.string_at(t, tl.value).decode()
            key = pickle.loads(ctypes.string_at(k, kl.value))
            value = pickle.loads(ctypes.string_at(v, vl.value))
            tables.setdefault(table, {})[key] = value
        return tables

    def put(self, table: str, key, value) -> None:
        if not self._h:
            raise OSError("native log store is closed")
        tb = table.encode()
        kb = pickle.dumps(key, protocol=5)
        if value is None:
            rc = self._lib.rtpu_log_put(self._h, tb, len(tb), kb, len(kb),
                                        None, 0)
        else:
            vb = pickle.dumps(value, protocol=5)
            rc = self._lib.rtpu_log_put(self._h, tb, len(tb), kb, len(kb),
                                        vb, len(vb))
        if rc != 0:
            raise OSError(
                f"native log store write failed (disk full?): {table!r}"
            )

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_log_close(self._h)
            self._h = None


def make_store(persist_path: Optional[str]):
    """Native C++ log store when the library loads, Python fallback
    otherwise (both replay + compact; formats are store-private)."""
    if not persist_path:
        return NullStore()
    try:
        from ray_tpu._private import native_store

        if native_store.available():
            # Open refuses foreign formats (returns null -> OSError), so a
            # log written by the Python store falls through to it intact.
            return NativeLogStore(persist_path)
    except Exception:
        pass
    return FileLogStore(persist_path)
