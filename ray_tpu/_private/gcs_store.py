"""Pluggable GCS persistence.

Analog of the reference's StoreClient family
(ray: src/ray/gcs/store_client/in_memory_store_client.h,
redis_store_client.h; typed tables gcs_table_storage.h:50,248). The
reference persists GCS tables to Redis so a restarted GCS replays state
(`gcs_init_data.h`) and clients resubscribe. TPU-native we use an
append-only log file on the head node (Redis isn't a baked-in dependency);
the interface is small enough that a Redis/etcd client drops in.

Records are length-prefixed pickles of ``(table, key, value)`` where
``value=None`` tombstones the key. ``load()`` replays the log into
``{table: {key: value}}`` and compacts it (rewrites live records only), so
the log stays proportional to live state, not mutation count.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Dict, Optional

_LEN = struct.Struct("<I")


class NullStore:
    """In-memory GCS: nothing survives restart (the default)."""

    def load(self) -> Dict[str, dict]:
        return {}

    def put(self, table: str, key, value) -> None:
        pass

    def close(self) -> None:
        pass


class FileLogStore:
    """Append-only log with replay + compaction on load."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = None

    # first bytes of a native-store (src/log_store.cpp) file — this store
    # must refuse it rather than compact it down to nothing
    NATIVE_MAGIC = b"RTPULG02"

    def load(self) -> Dict[str, dict]:
        tables: Dict[str, dict] = {}
        if os.path.exists(self.path):
            with open(self.path, "rb") as probe:
                if probe.read(8) == self.NATIVE_MAGIC:
                    raise RuntimeError(
                        f"{self.path} was written by the native log store "
                        "but the native library is unavailable; rebuild "
                        "src/ (make -C src) or move the file aside"
                    )
            with open(self.path, "rb") as f:
                while True:
                    header = f.read(_LEN.size)
                    if len(header) < _LEN.size:
                        break
                    (n,) = _LEN.unpack(header)
                    blob = f.read(n)
                    if len(blob) < n:  # torn tail write: stop replay here
                        break
                    try:
                        table, key, value = pickle.loads(blob)
                    except Exception:
                        break
                    if value is None:
                        tables.get(table, {}).pop(key, None)
                    else:
                        tables.setdefault(table, {})[key] = value
        self._compact(tables)
        return tables

    def _compact(self, tables: Dict[str, dict]) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for table, entries in tables.items():
                for key, value in entries.items():
                    blob = pickle.dumps((table, key, value), protocol=5)
                    f.write(_LEN.pack(len(blob)))
                    f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def put(self, table: str, key, value) -> None:
        if self._f is None:
            self._f = open(self.path, "ab")
        blob = pickle.dumps((table, key, value), protocol=5)
        with self._lock:
            self._f.write(_LEN.pack(len(blob)))
            self._f.write(blob)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class NativeLogStore:
    """C++ append-log store (src/log_store.cpp) behind the same interface:
    native framing, torn-tail truncation, and compaction; keys/values stay
    pickled by this layer (opaque bytes to C++). Reference analog: the
    RedisStoreClient persistence role, collapsed to a local log."""

    def __init__(self, path: str, fsync: bool = False):
        import ctypes

        from ray_tpu._private import native_store

        lib = native_store.load_library()
        if lib is None or not getattr(lib, "_has_log_store", False):
            raise OSError("native library lacks the log store")
        self._lib = lib
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._h = ctypes.c_void_p(
            lib.rtpu_log_open(path.encode(), 1 if fsync else 0)
        )
        if not self._h:
            raise OSError(f"native log store failed to open {path}")

    def load(self) -> Dict[str, dict]:
        import ctypes

        if not self._h:
            raise OSError("native log store is closed")
        tables: Dict[str, dict] = {}
        lib = self._lib
        lib.rtpu_log_iter_start(self._h)
        t = ctypes.POINTER(ctypes.c_uint8)()
        k = ctypes.POINTER(ctypes.c_uint8)()
        v = ctypes.POINTER(ctypes.c_uint8)()
        tl = ctypes.c_uint64()
        kl = ctypes.c_uint64()
        vl = ctypes.c_uint64()
        while lib.rtpu_log_iter_next(
            self._h, ctypes.byref(t), ctypes.byref(tl), ctypes.byref(k),
            ctypes.byref(kl), ctypes.byref(v), ctypes.byref(vl),
        ):
            table = ctypes.string_at(t, tl.value).decode()
            key = pickle.loads(ctypes.string_at(k, kl.value))
            value = pickle.loads(ctypes.string_at(v, vl.value))
            tables.setdefault(table, {})[key] = value
        return tables

    def put(self, table: str, key, value) -> None:
        if not self._h:
            raise OSError("native log store is closed")
        tb = table.encode()
        kb = pickle.dumps(key, protocol=5)
        if value is None:
            rc = self._lib.rtpu_log_put(self._h, tb, len(tb), kb, len(kb),
                                        None, 0)
        else:
            vb = pickle.dumps(value, protocol=5)
            rc = self._lib.rtpu_log_put(self._h, tb, len(tb), kb, len(kb),
                                        vb, len(vb))
        if rc != 0:
            raise OSError(
                f"native log store write failed (disk full?): {table!r}"
            )

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_log_close(self._h)
            self._h = None


class SqliteStore:
    """Durable external storage backend (reference analog: the
    RedisStoreClient role, src/ray/gcs/store_client/redis_store_client.h
    — GCS tables live in a store that outlives the GCS process). Point
    it at LOCAL persistent disk outside the session dir and head-node
    session loss no longer loses cluster metadata. Do NOT put the file
    on NFS or similar network filesystems: SQLite's WAL mode needs
    shared memory and network-FS locking is unreliable — for
    network-attached durability, drop a Redis/etcd client behind the
    same load/put/close interface instead.

    Selected with a ``sqlite://<path>`` persist path (see make_store).
    WAL mode with synchronous=FULL: every commit is fsync'd — this
    store exists for the machine-loss case, not just process loss.

    ``cluster_id`` scopes ownership: reopening the DB from a DIFFERENT
    cluster wipes the previous cluster's state instead of resurrecting
    its actors/jobs into the new one (a restarted GCS of the SAME
    cluster replays normally).
    """

    def __init__(self, path: str, cluster_id: Optional[str] = None):
        import sqlite3

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=FULL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs_kv ("
            " tbl TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs_meta ("
            " key TEXT PRIMARY KEY, value TEXT)"
        )
        self._db.commit()
        if cluster_id:
            row = self._db.execute(
                "SELECT value FROM gcs_meta WHERE key='cluster_id'"
            ).fetchone()
            if row is not None and row[0] != cluster_id:
                import logging

                logging.getLogger(__name__).warning(
                    "sqlite GCS store %s belonged to cluster %s; wiping "
                    "its state for new cluster %s", path, row[0], cluster_id,
                )
                self._db.execute("DELETE FROM gcs_kv")
            self._db.execute(
                "INSERT OR REPLACE INTO gcs_meta (key, value) "
                "VALUES ('cluster_id', ?)", (cluster_id,)
            )
            self._db.commit()

    def load(self) -> Dict[str, dict]:
        tables: Dict[str, dict] = {}
        with self._lock:
            rows = self._db.execute(
                "SELECT tbl, key, value FROM gcs_kv"
            ).fetchall()
        for tbl, key, value in rows:
            tables.setdefault(tbl, {})[pickle.loads(key)] = \
                pickle.loads(value)
        return tables

    def put(self, table: str, key, value) -> None:
        kb = pickle.dumps(key, protocol=5)
        with self._lock:
            if value is None:
                self._db.execute(
                    "DELETE FROM gcs_kv WHERE tbl=? AND key=?", (table, kb)
                )
            else:
                self._db.execute(
                    "INSERT OR REPLACE INTO gcs_kv (tbl, key, value) "
                    "VALUES (?, ?, ?)",
                    (table, kb, pickle.dumps(value, protocol=5)),
                )
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            try:
                self._db.close()
            except Exception:
                pass


class RemoteKvStore:
    """GCS persistence against a REMOTE KV server (``kv://host:port`` —
    see kv_server.py). Reference parity: ray's Redis store client
    (src/ray/gcs/store_client/redis_store_client.h): cluster metadata
    lives OFF the head node, so losing the head's disk loses nothing —
    a restarted GCS loads the full snapshot back over the wire.

    Puts are ACKNOWLEDGED requests: the mutation is on the server before
    put() returns, so a kill -9 of the GCS immediately after a client-
    observed write cannot lose it — the same posture as the sqlite
    backend's synchronous commit (and ray's Redis store client, which
    completes GCS mutations in the Redis write callback).
    """

    def __init__(self, address: str, cluster_id: Optional[str] = None):
        from ray_tpu._private.rpcio import EventLoopThread, connect

        self.cluster_id = cluster_id or ""
        # kv://[:token@]host:port — the KV server is cluster-EXTERNAL, so
        # it authenticates with its own secret (redis requirepass shape),
        # not the per-cluster generated token
        token = None
        if "@" in address:
            userinfo, address = address.rsplit("@", 1)
            token = userinfo.lstrip(":")
        host, port = address.rsplit(":", 1)
        self._io = EventLoopThread("gcs-kv-store")
        self._conn = self._io.run(connect(host, int(port),
                                          name="gcs-kv-store",
                                          token=token))
        # fail fast on a wrong address instead of at first load
        self._io.run(self._conn.request("kv_ping", {}), timeout=10)

    def load(self) -> Dict[str, dict]:
        out = self._io.run(
            self._conn.request("kv_load", {"cluster_id": self.cluster_id}),
            timeout=60,
        )
        return out.get("tables", {})

    def put(self, table: str, key, value) -> None:
        if not self._io.loop.is_running():
            # shutdown race: a stopped-but-open loop would queue the
            # coroutine forever and block this caller the full timeout
            return
        try:
            self._io.run(
                self._conn.request("kv_put", {
                    "cluster_id": self.cluster_id,
                    "entries": [(table, key, value)],
                }),
                timeout=30,
            )
        except RuntimeError:
            pass  # shutdown race: the loop is gone
        except Exception:
            # a dropped KV server degrades persistence, not the cluster
            # (same failure posture as a full disk under the log store)
            pass

    def close(self) -> None:
        self._io.stop()


def make_store(persist_path: Optional[str],
               cluster_id: Optional[str] = None):
    """Backend selection by scheme:

    - ``None``/empty        -> NullStore (in-memory, nothing survives)
    - ``sqlite://<path>``   -> SqliteStore (durable external store)
    - ``kv://host:port``    -> RemoteKvStore (external KV server; head
      disk loss loses no metadata — kv_server.py, redis-analog)
    - plain path            -> native C++ log store when the library
      loads, Python append-log fallback otherwise

    ``RAY_TPU_GCS_STORAGE`` overrides the configured path wholesale, so
    an operator can point an existing deployment at durable storage
    without touching startup scripts. ``cluster_id`` (the session name)
    keeps an external store from resurrecting a previous cluster's
    state — session-dir log files are per-cluster by construction."""
    persist_path = os.environ.get("RAY_TPU_GCS_STORAGE") or persist_path
    if not persist_path:
        return NullStore()
    if persist_path.startswith("sqlite://"):
        return SqliteStore(persist_path[len("sqlite://"):],
                           cluster_id=cluster_id)
    if persist_path.startswith("kv://"):
        return RemoteKvStore(persist_path[len("kv://"):],
                             cluster_id=cluster_id)
    try:
        from ray_tpu._private import native_store

        if native_store.available():
            # Open refuses foreign formats (returns null -> OSError), so a
            # log written by the Python store falls through to it intact.
            return NativeLogStore(persist_path)
    except Exception:
        pass
    return FileLogStore(persist_path)
