"""Pluggable GCS persistence.

Analog of the reference's StoreClient family
(ray: src/ray/gcs/store_client/in_memory_store_client.h,
redis_store_client.h; typed tables gcs_table_storage.h:50,248). The
reference persists GCS tables to Redis so a restarted GCS replays state
(`gcs_init_data.h`) and clients resubscribe. TPU-native we use an
append-only log file on the head node (Redis isn't a baked-in dependency);
the interface is small enough that a Redis/etcd client drops in.

Records are length-prefixed pickles of ``(table, key, value)`` where
``value=None`` tombstones the key. ``load()`` replays the log into
``{table: {key: value}}`` and compacts it (rewrites live records only), so
the log stays proportional to live state, not mutation count.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Dict, Optional

_LEN = struct.Struct("<I")


class NullStore:
    """In-memory GCS: nothing survives restart (the default)."""

    def load(self) -> Dict[str, dict]:
        return {}

    def put(self, table: str, key, value) -> None:
        pass

    def close(self) -> None:
        pass


class FileLogStore:
    """Append-only log with replay + compaction on load."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = None

    # first bytes of a native-store (src/log_store.cpp) file — this store
    # must refuse it rather than compact it down to nothing
    NATIVE_MAGIC = b"RTPULG02"

    def load(self) -> Dict[str, dict]:
        tables: Dict[str, dict] = {}
        if os.path.exists(self.path):
            with open(self.path, "rb") as probe:
                if probe.read(8) == self.NATIVE_MAGIC:
                    raise RuntimeError(
                        f"{self.path} was written by the native log store "
                        "but the native library is unavailable; rebuild "
                        "src/ (make -C src) or move the file aside"
                    )
            with open(self.path, "rb") as f:
                while True:
                    header = f.read(_LEN.size)
                    if len(header) < _LEN.size:
                        break
                    (n,) = _LEN.unpack(header)
                    blob = f.read(n)
                    if len(blob) < n:  # torn tail write: stop replay here
                        break
                    try:
                        table, key, value = pickle.loads(blob)
                    except Exception:
                        break
                    if value is None:
                        tables.get(table, {}).pop(key, None)
                    else:
                        tables.setdefault(table, {})[key] = value
        self._compact(tables)
        return tables

    def _compact(self, tables: Dict[str, dict]) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for table, entries in tables.items():
                for key, value in entries.items():
                    blob = pickle.dumps((table, key, value), protocol=5)
                    f.write(_LEN.pack(len(blob)))
                    f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def put(self, table: str, key, value) -> None:
        if self._f is None:
            self._f = open(self.path, "ab")
        blob = pickle.dumps((table, key, value), protocol=5)
        with self._lock:
            self._f.write(_LEN.pack(len(blob)))
            self._f.write(blob)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class NativeLogStore:
    """C++ append-log store (src/log_store.cpp) behind the same interface:
    native framing, torn-tail truncation, and compaction; keys/values stay
    pickled by this layer (opaque bytes to C++). Reference analog: the
    RedisStoreClient persistence role, collapsed to a local log."""

    def __init__(self, path: str, fsync: bool = False):
        import ctypes

        from ray_tpu._private import native_store

        lib = native_store.load_library()
        if lib is None or not getattr(lib, "_has_log_store", False):
            raise OSError("native library lacks the log store")
        self._lib = lib
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._h = ctypes.c_void_p(
            lib.rtpu_log_open(path.encode(), 1 if fsync else 0)
        )
        if not self._h:
            raise OSError(f"native log store failed to open {path}")

    def load(self) -> Dict[str, dict]:
        import ctypes

        if not self._h:
            raise OSError("native log store is closed")
        tables: Dict[str, dict] = {}
        lib = self._lib
        lib.rtpu_log_iter_start(self._h)
        t = ctypes.POINTER(ctypes.c_uint8)()
        k = ctypes.POINTER(ctypes.c_uint8)()
        v = ctypes.POINTER(ctypes.c_uint8)()
        tl = ctypes.c_uint64()
        kl = ctypes.c_uint64()
        vl = ctypes.c_uint64()
        while lib.rtpu_log_iter_next(
            self._h, ctypes.byref(t), ctypes.byref(tl), ctypes.byref(k),
            ctypes.byref(kl), ctypes.byref(v), ctypes.byref(vl),
        ):
            table = ctypes.string_at(t, tl.value).decode()
            key = pickle.loads(ctypes.string_at(k, kl.value))
            value = pickle.loads(ctypes.string_at(v, vl.value))
            tables.setdefault(table, {})[key] = value
        return tables

    def put(self, table: str, key, value) -> None:
        if not self._h:
            raise OSError("native log store is closed")
        tb = table.encode()
        kb = pickle.dumps(key, protocol=5)
        if value is None:
            rc = self._lib.rtpu_log_put(self._h, tb, len(tb), kb, len(kb),
                                        None, 0)
        else:
            vb = pickle.dumps(value, protocol=5)
            rc = self._lib.rtpu_log_put(self._h, tb, len(tb), kb, len(kb),
                                        vb, len(vb))
        if rc != 0:
            raise OSError(
                f"native log store write failed (disk full?): {table!r}"
            )

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_log_close(self._h)
            self._h = None


class SqliteStore:
    """Durable external storage backend (reference analog: the
    RedisStoreClient role, src/ray/gcs/store_client/redis_store_client.h
    — GCS tables live in a store that outlives the GCS process). Point
    it at LOCAL persistent disk outside the session dir and head-node
    session loss no longer loses cluster metadata. Do NOT put the file
    on NFS or similar network filesystems: SQLite's WAL mode needs
    shared memory and network-FS locking is unreliable — for
    network-attached durability, drop a Redis/etcd client behind the
    same load/put/close interface instead.

    Selected with a ``sqlite://<path>`` persist path (see make_store).
    WAL mode with synchronous=FULL: every commit is fsync'd — this
    store exists for the machine-loss case, not just process loss.

    ``cluster_id`` scopes ownership: reopening the DB from a DIFFERENT
    cluster wipes the previous cluster's state instead of resurrecting
    its actors/jobs into the new one (a restarted GCS of the SAME
    cluster replays normally).
    """

    def __init__(self, path: str, cluster_id: Optional[str] = None):
        import sqlite3

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=FULL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs_kv ("
            " tbl TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs_meta ("
            " key TEXT PRIMARY KEY, value TEXT)"
        )
        self._db.commit()
        if cluster_id:
            row = self._db.execute(
                "SELECT value FROM gcs_meta WHERE key='cluster_id'"
            ).fetchone()
            if row is not None and row[0] != cluster_id:
                import logging

                logging.getLogger(__name__).warning(
                    "sqlite GCS store %s belonged to cluster %s; wiping "
                    "its state for new cluster %s", path, row[0], cluster_id,
                )
                self._db.execute("DELETE FROM gcs_kv")
            self._db.execute(
                "INSERT OR REPLACE INTO gcs_meta (key, value) "
                "VALUES ('cluster_id', ?)", (cluster_id,)
            )
            self._db.commit()

    def load(self) -> Dict[str, dict]:
        tables: Dict[str, dict] = {}
        with self._lock:
            rows = self._db.execute(
                "SELECT tbl, key, value FROM gcs_kv"
            ).fetchall()
        for tbl, key, value in rows:
            tables.setdefault(tbl, {})[pickle.loads(key)] = \
                pickle.loads(value)
        return tables

    def put(self, table: str, key, value) -> None:
        kb = pickle.dumps(key, protocol=5)
        with self._lock:
            if value is None:
                self._db.execute(
                    "DELETE FROM gcs_kv WHERE tbl=? AND key=?", (table, kb)
                )
            else:
                self._db.execute(
                    "INSERT OR REPLACE INTO gcs_kv (tbl, key, value) "
                    "VALUES (?, ?, ?)",
                    (table, kb, pickle.dumps(value, protocol=5)),
                )
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            try:
                self._db.close()
            except Exception:
                pass


class RemoteKvStore:
    """GCS persistence against a REMOTE KV server (``kv://host:port`` —
    see kv_server.py). Reference parity: ray's Redis store client
    (src/ray/gcs/store_client/redis_store_client.h): cluster metadata
    lives OFF the head node, so losing the head's disk loses nothing —
    a restarted GCS loads the full snapshot back over the wire.

    ``put()`` never blocks the caller: it is called from GCS RPC
    handlers ON the GCS event loop (gcs.py _persist_actor/_persist_pg),
    where one synchronous KV round trip per mutation would stall the
    entire control plane — and a HUNG server would stall it longer than
    node_death_timeout_s, declaring healthy nodes dead. Mutations are
    queued FIFO and drained by one writer task on the kv io thread,
    pipelined in batches; the wire order equals the put order, so a
    tombstone after a write lands as a tombstone. ``aput()`` is the
    awaitable variant for client-observed writes (the GCS kv_put handler
    awaits the flush before acking, restoring the redis-store durability
    contract without blocking its loop). A failed flush trips a
    circuit breaker into the degraded no-persist posture (same posture
    as a full disk under the log store) for
    ``gcs_kv_breaker_cooldown_s``, then retries. ``close()`` drains the
    queue (bounded) so a clean shutdown loses nothing.
    """

    def __init__(self, address: str, cluster_id: Optional[str] = None):
        from ray_tpu._private.rpcio import EventLoopThread, connect

        self.cluster_id = cluster_id or ""
        # kv://[:token@]host:port — the KV server is cluster-EXTERNAL, so
        # it authenticates with its own secret (redis requirepass shape),
        # not the per-cluster generated token
        token = None
        if "@" in address:
            userinfo, address = address.rsplit("@", 1)
            token = userinfo.lstrip(":")
        host, port = address.rsplit(":", 1)
        self._io = EventLoopThread("gcs-kv-store")
        self._conn = self._io.run(connect(host, int(port),
                                          name="gcs-kv-store",
                                          token=token))
        # fail fast on a wrong address instead of at first load
        self._io.run(self._conn.request("kv_ping", {}), timeout=10)
        from collections import deque

        self._q: deque = deque()  # of ((table, key, value), ack_fut|None)
        self._lock = threading.Lock()
        self._flushing = False
        self._degraded_until = 0.0
        self._dropped = 0
        self._setup_metrics()

    def _setup_metrics(self):
        """Snapshot-time gauges over the put pipeline: queue depth,
        breaker posture, drops (metrics_core.py — zero hot-path cost)."""
        try:
            import time as _time

            from ray_tpu._private import metrics_core as mc

            reg = mc.registry()
            reg.gauge("gcs_kv_put_queue_depth",
                      "Remote-KV puts queued for the io thread"
                      ).set_fn(lambda: len(self._q))
            reg.gauge("gcs_kv_breaker_open",
                      "1 while the remote-KV circuit breaker holds the "
                      "degraded no-persist posture").set_fn(
                lambda: 1.0 if _time.monotonic() < self._degraded_until
                else 0.0)
            reg.counter("gcs_kv_puts_dropped_total",
                        "Puts dropped by overload/breaker"
                        ).default.set_fn(lambda: self._dropped)
        except Exception:  # metrics must never break persistence setup
            pass

    def _cfg(self):
        from ray_tpu._private.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG

    def load(self) -> Dict[str, dict]:
        out = self._io.run(
            self._conn.request("kv_load", {"cluster_id": self.cluster_id}),
            timeout=60,
        )
        return out.get("tables", {})

    def put(self, table: str, key, value) -> None:
        self._enqueue((table, key, value), None)

    async def aput(self, table: str, key, value) -> bool:
        """Awaitable put for callers on SOME event loop (the GCS kv_put
        handler): resolves once the mutation is flushed to the server, so
        a client-observed ack is durable — without ever blocking the
        caller's loop. Bounded: a degraded server resolves False after
        the put timeout (well under node_death_timeout_s) instead of
        stalling the control plane."""
        import asyncio
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._enqueue((table, key, value), fut)
        try:
            return bool(await asyncio.wait_for(
                asyncio.wrap_future(fut),
                self._cfg().gcs_kv_put_timeout_s + 1.0,
            ))
        except Exception:
            return False

    @staticmethod
    def _ack(fut, ok: bool):
        if fut is not None and not fut.done():
            fut.set_result(ok)

    def _enqueue(self, entry, fut) -> None:
        if not self._io.loop.is_running():
            # shutdown race: the drain task can never run
            self._ack(fut, False)
            return
        cfg = self._cfg()
        with self._lock:
            if len(self._q) >= cfg.gcs_kv_queue_max:
                # overload: drop the OLDEST entry — for a same-key churn
                # the newest write is the one that must win, and the
                # breaker below is what normally bounds the queue anyway
                _, old_fut = self._q.popleft()
                self._ack(old_fut, False)
                self._dropped += 1
            self._q.append((entry, fut))
            if self._flushing:
                return
            self._flushing = True
        try:
            self._io.loop.call_soon_threadsafe(self._start_drain)
        except RuntimeError:
            with self._lock:
                self._flushing = False
            self._ack(fut, False)

    def _start_drain(self):
        # on the kv io loop; keep a strong ref so the task can't be GC'd
        task = self._io.loop.create_task(self._drain())
        self._drain_task = task

    async def _drain(self):
        import asyncio
        import logging
        import time as _time

        cfg = self._cfg()
        log = logging.getLogger(__name__)
        try:
            while True:
                with self._lock:
                    if not self._q:
                        self._flushing = False
                        return
                    batch = []
                    while self._q and len(batch) < 256:
                        batch.append(self._q.popleft())
                entries = [entry for entry, _ in batch]
                futs = [fut for _, fut in batch]
                if _time.monotonic() < self._degraded_until:
                    # breaker open: degraded no-persist — drop and count
                    self._dropped += len(batch)
                    for fut in futs:
                        self._ack(fut, False)
                    continue
                try:
                    await self._conn.request(
                        "kv_put",
                        {"cluster_id": self.cluster_id, "entries": entries},
                        timeout=cfg.gcs_kv_put_timeout_s,
                    )
                    for fut in futs:
                        self._ack(fut, True)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    self._dropped += len(batch)
                    for fut in futs:
                        self._ack(fut, False)
                    self._degraded_until = (
                        _time.monotonic() + cfg.gcs_kv_breaker_cooldown_s
                    )
                    log.warning(
                        "remote KV put failed (%s); persistence degraded "
                        "for %.0fs (%d mutations dropped so far)",
                        e, cfg.gcs_kv_breaker_cooldown_s, self._dropped,
                    )
        except BaseException:
            with self._lock:
                self._flushing = False
            raise

    def close(self) -> None:
        # bounded drain: a clean shutdown persists everything queued; a
        # degraded/hung server gives up after the put timeout instead of
        # wedging GCS teardown
        import time as _time

        deadline = _time.monotonic() + self._cfg().gcs_kv_put_timeout_s
        while _time.monotonic() < deadline:
            with self._lock:
                idle = not self._q and not self._flushing
            if idle or _time.monotonic() < self._degraded_until:
                break
            _time.sleep(0.01)
        self._io.stop()


def make_store(persist_path: Optional[str],
               cluster_id: Optional[str] = None):
    """Backend selection by scheme:

    - ``None``/empty        -> NullStore (in-memory, nothing survives)
    - ``sqlite://<path>``   -> SqliteStore (durable external store)
    - ``kv://host:port``    -> RemoteKvStore (external KV server; head
      disk loss loses no metadata — kv_server.py, redis-analog)
    - plain path            -> native C++ log store when the library
      loads, Python append-log fallback otherwise

    ``RAY_TPU_GCS_STORAGE`` overrides the configured path wholesale, so
    an operator can point an existing deployment at durable storage
    without touching startup scripts. ``cluster_id`` (the session name)
    keeps an external store from resurrecting a previous cluster's
    state — session-dir log files are per-cluster by construction."""
    persist_path = os.environ.get("RAY_TPU_GCS_STORAGE") or persist_path
    if not persist_path:
        return NullStore()
    if persist_path.startswith("sqlite://"):
        return SqliteStore(persist_path[len("sqlite://"):],
                           cluster_id=cluster_id)
    if persist_path.startswith("kv://"):
        return RemoteKvStore(persist_path[len("kv://"):],
                             cluster_id=cluster_id)
    try:
        from ray_tpu._private import native_store

        if native_store.available():
            # Open refuses foreign formats (returns null -> OSError), so a
            # log written by the Python store falls through to it intact.
            return NativeLogStore(persist_path)
    except Exception:
        pass
    return FileLogStore(persist_path)
