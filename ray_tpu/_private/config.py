"""Typed runtime flag table, env-overridable.

Analog of the reference's RAY_CONFIG table (ray: src/ray/common/ray_config_def.h,
205 flags overridable via RAY_* env vars). Each flag is declared once with a
type and default; ``RAY_TPU_<NAME>`` environment variables override, and an
explicit ``system_config`` dict (passed to ``init``) overrides both.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

_FLAG_DEFS: Dict[str, tuple] = {}


def _flag(name: str, typ, default):
    _FLAG_DEFS[name] = (typ, default)
    return default


class _Config:
    """Singleton flag table. Access flags as attributes."""

    def __init__(self):
        self._values: Dict[str, Any] = {}
        self.reset()

    def reset(self, system_config: Dict[str, Any] | None = None):
        self._values = {}
        for name, (typ, default) in _FLAG_DEFS.items():
            value = default
            env = os.environ.get(f"RAY_TPU_{name}")
            if env is not None:
                value = self._parse(typ, env)
            self._values[name] = value
        if system_config:
            self.update(system_config)

    def update(self, overrides: Dict[str, Any]):
        for k, v in overrides.items():
            if k not in _FLAG_DEFS:
                raise ValueError(f"Unknown system config flag: {k}")
            typ, _ = _FLAG_DEFS[k]
            self._values[k] = self._parse(typ, v) if isinstance(v, str) else typ(v)

    @staticmethod
    def _parse(typ, raw: str):
        if typ is bool:
            return raw.lower() in ("1", "true", "yes")
        if typ in (dict, list):
            return json.loads(raw)
        return typ(raw)

    def __getattr__(self, name):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)


# --- flag declarations -------------------------------------------------------
# Scheduling
_flag("max_pending_lease_requests_per_scheduling_category", int, 10)
_flag("scheduler_spread_threshold", float, 0.5)
_flag("scheduler_top_k_fraction", float, 0.2)
_flag("max_spillback_depth", int, 10)
_flag("worker_lease_timeout_ms", int, 30_000)
# Topology-aware gang scheduling (topology.py): nodes advertise torus
# coordinates via labels (torus-coord="0x1[x2]", torus-dims="4x4[x8]" —
# TPU-style "x" separators keep the labels wire-safe for the native
# scheduler), synthesized here from per-node env/config the way the
# reference synthesizes TPU slice topology. Placement-group scheduling
# then scores candidate placements by ring-allreduce link overlap
# against committed gangs and prefers torus-aligned contiguous slices;
# clusters with no coords advertised take the resource-fit path
# untouched.
_flag("sched_topology_enabled", bool, True)
_flag("torus_coord", str, "")  # this node's "0x1[x2]" (per-node env)
_flag("torus_dims", str, "")  # the torus extent "4x4[x8]"
_flag("sched_max_candidates", int, 32)  # slice windows scored per gang
_flag("sched_repack_max_moves", int, 8)  # bundle migrations per repack
# Workers
_flag("num_workers_soft_limit", int, 16)
_flag("worker_register_timeout_s", float, 60.0)
_flag("idle_worker_killing_time_threshold_ms", int, 300_000)
_flag("prestart_worker_first_driver", bool, False)
_flag("worker_niceness", int, 0)
# Objects
_flag("max_direct_call_object_size", int, 100 * 1024)  # inline threshold (ray: 100KB)
_flag("object_store_memory", int, 2 * 1024**3)
_flag("object_store_eviction_fraction", float, 0.8)
# Slab-arena object plane (slab_arena.py): leased write slabs + shared
# index instead of one file per object. RAY_TPU_slab_arena=0 restores the
# legacy per-object-file data path (and with it the native C++ writer).
_flag("slab_arena", bool, True)
_flag("slab_size_bytes", int, 16 * 1024 * 1024)  # default lease ceiling
_flag("slab_min_lease_bytes", int, 1024 * 1024)  # first lease of a worker
_flag("slab_index_slots", int, 1 << 16)  # shared index capacity (~4MB)
_flag("object_transfer_chunk_bytes", int, 8 * 1024 * 1024)
# concurrent chunk requests per pull (raylet._fetch_from): one request at
# a time is latency-bound (the reason push outran pull); the window's
# chunks land out of order at their offsets in the reserved slab entry
_flag("fetch_pipeline_depth", int, 4)
# the FIRST fetch request (which discovers total size + metadata) asks
# for at most this much: a full-size head chunk is a serial prefix the
# pipeline can't overlap, while a small head reveals the size after a
# fraction of a chunk and the concurrent window covers the rest
_flag("fetch_head_chunk_bytes", int, 1 << 20)
_flag("object_pull_timeout_s", float, 60.0)
_flag("fetch_warn_timeout_s", float, 10.0)
# Hole-punch reclamation (object_store.punch_holes): a periodic raylet
# pass fallocate(PUNCH_HOLE|KEEP_SIZE)s the page-aligned interior of
# dead entry ranges in sealed segments above the fragmentation
# threshold, returning tmpfs pages without waiting for whole-segment
# emptiness. KEEP_SIZE preserves the mapping, so live zero-copy readers
# keep their views; flock-pinned and pooled segments are skipped.
_flag("slab_punch_enabled", bool, True)
_flag("slab_punch_interval_s", float, 30.0)
_flag("slab_punch_min_fragmentation", float, 0.25)
_flag("slab_punch_min_bytes", int, 1 << 20)
# Pull admission + spilling (ray: pull_manager.h:56, local_object_manager.h:40)
_flag("max_concurrent_pulls", int, 8)
_flag("pull_manager_memory_fraction", float, 0.5)
_flag("object_spill_dir", str, "")  # path or storage URI (file://, s3://, ...)
# staging root for mid-spill .obj copies; "" = the spill destination's
# own filesystem when local, else the system temp dir (often tmpfs —
# point this at real disk for non-local backends under memory pressure)
_flag("spill_staging_dir", str, "")
# module imported by the raylet before building its store — the hook for
# register_external_storage_scheme plugins (custom spill backends)
_flag("external_storage_setup_module", str, "")
# engine for runtime_env={"container": ...} worker wrapping (a name on
# PATH or an absolute path; tests point this at a fake engine)
_flag("container_runtime", str, "podman")
# Health / fault tolerance
_flag("heartbeat_interval_s", float, 0.5)
_flag("node_death_timeout_s", float, 10.0)
_flag("gcs_rpc_timeout_s", float, 30.0)
_flag("task_retry_delay_ms", int, 100)
_flag("actor_restart_delay_ms", int, 100)
# Reference counting / lineage (ray: reference_count.h, object_recovery_manager.h)
_flag("borrower_poll_timeout_s", float, 600.0)
_flag("borrower_poll_retries", int, 6)
_flag("max_lineage_cache_entries", int, 4096)
_flag("max_object_reconstructions", int, 3)
# GCS fault tolerance (ray: gcs_server.h:101-107 StorageType,
# gcs_failover_worker_reconnect_timeout ray_config_def.h:62)
_flag("gcs_failover_reconnect_timeout_s", float, 10.0)
_flag("gcs_client_reconnect_timeout_s", float, 60.0)
_flag("gcs_store_fsync", bool, False)
# Memory monitor (ray: common/memory_monitor.h:52, worker_killing_policy.h)
_flag("memory_usage_threshold", float, 0.95)
_flag("memory_monitor_refresh_ms", int, 250)
_flag("memory_monitor_test_path", str, "")  # test injection: file with a float
# On-demand profiling (profiler.py: sampled CPU flamegraphs + mem diffs)
_flag("profiler_default_hz", float, 100.0)
_flag("profiler_max_hz", float, 1000.0)
# sampling self-throttles when (time spent sampling / wall time) would
# exceed this fraction — attaching to a loaded worker stays <5% overhead
_flag("profiler_max_overhead_fraction", float, 0.05)
_flag("profiler_max_duration_s", float, 600.0)
_flag("profiler_mem_top_n", int, 30)
_flag("profiler_mem_frames", int, 8)
# GCS remote-KV persistence put pipeline (gcs_store.RemoteKvStore): puts
# are queued onto the kv io thread (ordered, batched) so a slow KV server
# never blocks the GCS event loop; a failed flush trips a circuit breaker
# into the degraded no-persist posture for the cooldown.
_flag("gcs_kv_put_timeout_s", float, 5.0)
_flag("gcs_kv_queue_max", int, 10_000)
_flag("gcs_kv_breaker_cooldown_s", float, 30.0)
# Metrics / events (metrics_core.py: per-process counters/gauges/log2
# histograms behind the metrics_snapshot fan-out + /metrics scrape)
_flag("metrics_enabled", bool, True)  # master switch (overhead A/B lane)
# dashboard head: cadence + depth of the in-head snapshot ring buffer the
# SPA Metrics tab draws its sparkline time-series from
_flag("metrics_history_interval_s", float, 5.0)
_flag("metrics_history_len", int, 120)
# cluster scrape budget: per-node fan-out timeout inside metrics_cluster
_flag("metrics_scrape_timeout_s", float, 10.0)
_flag("metrics_report_interval_s", float, 2.0)
_flag("task_events_buffer_size", int, 10_000)
_flag("event_stats", bool, True)
# Worker-log streaming to drivers (ray: log_monitor.py tail cadence +
# worker.py print_logs). log_to_driver is the master gate for the driver
# subscription (RAY_TPU_LOG_TO_DRIVER=0 kills it cluster-wide); raylets
# additionally skip tailing entirely while the GCS reports zero "logs"
# subscribers, so an unwatched cluster pays nothing for the log plane.
_flag("log_tail_interval_s", float, 0.3)
_flag("log_to_driver", bool, True)
# driver-side dedup: identical lines fanning in from many workers within
# this window collapse to one line + "[repeated Nx]" summary
_flag("log_dedup_window_s", float, 1.0)
# length caps on published records: lines longer than this are truncated
# (counted in raylet_log_lines_truncated_total), and one publish batch
# never carries more than log_publish_max_bytes of line payload per tick
# (excess lines defer to the next tick via the tail offset)
_flag("log_max_line_bytes", int, 4096)
_flag("log_publish_max_bytes", int, 2 * 1024 * 1024)
# closed per-task byte-range attribution spans kept per worker for the
# tailer's line -> task-name resolution (bounded ring)
_flag("log_span_history", int, 128)
# Push plane (ray: push_manager.h max_chunks_in_flight per push)
_flag("push_max_chunks_in_flight", int, 8)
_flag("push_rx_expiry_s", float, 60.0)  # abandoned inbound push sessions
# Idle workers spawned at raylet boot (ray: prestart_worker_first_driver)
_flag("worker_prestart", int, 2)
# Direct task push over worker leases (ray: direct_task_transport.cc)
_flag("direct_task_leases", bool, True)
# blocked get() diagnostics: after this many seconds waiting on one ref, log
# a WARNING with the direct-push transport state (and append it to
# RAY_TPU_STALL_DUMP_FILE if set). 0 disables.
_flag("get_stall_dump_s", float, 30.0)
_flag("direct_lease_pipeline_depth", int, 4)  # in-flight tasks per lease
_flag("direct_lease_max", int, 16)  # leases per scheduling class per driver
_flag("direct_lease_linger_s", float, 0.5)  # idle hold before lease return
# grace-period return: after the class queue drains (and the feeders'
# linger expires) the pump HOLDS its leases this long before returning
# them, so the next burst rides the already-open lease conns with zero
# raylet round trips. 0 restores return-on-drain (A/B lever).
_flag("direct_lease_grace_s", float, 0.5)
_flag("direct_push_batch_max", int, 64)  # specs per execute_task_batch frame
# idle hold before a per-actor direct sender exits: a sync call loop
# reuses the standing sender (and its pipelined conn) instead of paying
# a task spawn + warm-up tick per call. 0 restores exit-on-drain.
_flag("actor_sender_linger_s", float, 0.5)
# submit_batch ack mode: "batch" = the raylet acks frame ACCEPTANCE and
# schedules in the background (fire-and-forget lane; per-task failures
# surface via the owner's task_result stream + task events), "spec" =
# legacy ack-after-scheduling (A/B lever)
_flag("submit_ack_mode", str, "batch")
# control-plane stage timing (BENCH_CONTROL_PLANE): per-stage histograms
# (envelope build, id mint, result return, submit->run) on the submit
# path; off = one attr check per call
_flag("control_plane_stage_timing", bool, False)
# observability/GC debounce windows. A sync submit->get loop otherwise
# generates one task_events notify (worker->raylet) and one free_objects
# chain (driver->raylet->GCS) PER CALL — on a small box that background
# traffic competes with the call's own round trip for CPU. Events/frees
# buffer for the window and ship as one frame. 0 restores flush-per-tick
# (A/B lever); exit paths still drain synchronously.
_flag("task_events_flush_interval_s", float, 0.02)
_flag("free_flush_interval_s", float, 0.005)
# batch frames in flight per actor sender: >1 keeps the pipe full while the
# next burst accumulates behind it (unbounded pipelining would drain the
# queue one spec at a time and never form a batch)
_flag("actor_direct_max_inflight", int, 2)
_flag("direct_actor_calls", bool, True)  # push actor calls to the worker
# Dispatch / scheduling cadence (raylet loops)
_flag("dispatch_retry_interval_s", float, 0.01)
_flag("infeasible_retry_interval_s", float, 0.5)
_flag("pull_location_poll_interval_s", float, 0.1)
_flag("actor_route_wait_alive_timeout_s", float, 30.0)
# Driver-side get/wait cadence
_flag("wait_poll_interval_s", float, 0.05)
_flag("deferred_release_wait_s", float, 0.5)
_flag("worker_dump_stacks_timeout_s", float, 10.0)
# GCS scheduling retry cadence (actor placement / PG)
_flag("gcs_schedule_retry_interval_s", float, 0.2)
# Per-node dashboard agent (ray: dashboard/agent.py)
_flag("enable_node_agent", bool, True)
# Step observatory (steptrace.py): per-step trainer/collective telemetry.
# steptrace_enabled gates every record path (zero-cost off, same posture
# as metrics_enabled); the ring holds the newest steptrace_ring_size
# records per process (a dropped-old-records counter rides the snapshot).
_flag("steptrace_enabled", bool, True)
_flag("steptrace_ring_size", int, 8192)
# per-node fan-out timeout inside steptrace_cluster
_flag("steptrace_scrape_timeout_s", float, 10.0)
# Memory observatory (memview.py): object lifecycle + arena
# introspection + leak attribution. memview_enabled gates every record
# path (creation-callsite stamping at put(), the spill/restore/transfer
# flow ring) — zero-cost off, same posture as metrics/steptrace.
_flag("memview_enabled", bool, True)
_flag("memview_track_max", int, 8192)  # creation records kept per process
_flag("memview_flow_ring_size", int, 2048)  # flow events kept per process
# per-node fan-out timeout inside memview_cluster
_flag("memview_scrape_timeout_s", float, 10.0)
# Collective / device plane
_flag("collective_timeout_s", float, 120.0)
# Chunked pipeline transport for large store-path allreduces: tensors
# bigger than this are reduce-scattered + allgathered in fixed-size
# chunks (each chunk its own rendezvous sub-key under the op's seq),
# with reduction of chunk N overlapping transport of chunk N+1.
# 0 disables chunking (monolithic single-payload _phase, today's path).
_flag("collective_chunk_bytes", int, 1 << 20)
# in-flight chunk fetches per fetch kind (contribution fetches and
# reduced-chunk fetches each get their own window of this depth, so
# waits on unfinalized reduced chunks can never starve the contribution
# fetches finalization depends on) — the pipeline depth that buys
# transport/reduce overlap
_flag("collective_pipeline_depth", int, 4)
# EQuARX-style block-wise quantization for SUM/MEAN allreduce: "" (off)
# or "int8" (per-chunk symmetric scale + int8 wire). Group-level opt-in
# via create_collective_group(..., quant=) overrides this default.
_flag("collective_quant", str, "")
# straggler-tolerant chunk scheduling: when a peer's EWMA arrival lag
# (seconds behind the fastest peer, measured from receiver-local chunk
# wait times — never cross-host timestamps) exceeds this, its chunks
# are fetched LAST so the bounded pipeline windows stay busy on ranks
# that have already published. 0 (the default) disables reordering
# (FIFO rank order); set well above the transport's RPC round-trip
# floor when enabling.
_flag("collective_straggler_threshold", float, 0.0)
_flag("tpu_autodetect", bool, False)
# RPC substrate (ray: grpc_server.h / client channel args)
_flag("rpc_max_message_bytes", int, 1 << 31)
# wire frame format: 3 = out-of-band buffer table + CRC32 head trailer,
# 2 = out-of-band buffer table (zero-copy payload buffers), 1 = legacy
# in-band pickle frames. Clients dialing high fall back one version per
# redial when the server doesn't ack it. The v3 CRC covers the frame head
# (count byte + buffer table + envelope): corrupted control data is
# detected and the connection reset instead of unpickling garbage;
# out-of-band payload buffers stay CRC-free (checksumming multi-MB tensors
# would re-scan the memory the zero-copy path exists to avoid).
_flag("rpc_frame_version", int, 3)
# payload buffers at least this big ride v2 frames out-of-band; smaller
# ones stay in the pickle envelope (a table entry + unjoined write costs
# more than a tiny memcpy)
_flag("rpc_oob_min_bytes", int, 512)
_flag("rpc_auth_timeout_s", float, 10.0)
_flag("rpc_connect_retries", int, 30)
# connect() retry backoff: delay starts at rpc_connect_retry_delay_s,
# doubles per attempt, caps at rpc_connect_backoff_max_s (with jitter).
# Budget check: 30 retries = ~3s of doubling + 27 capped waits ≈ 57s
# worst-case, inside gcs_client_reconnect_timeout_s (60s).
_flag("rpc_connect_retry_delay_s", float, 0.1)
_flag("rpc_connect_backoff_max_s", float, 2.0)
# default deadline for Connection.request() when the caller passes no
# timeout — no control-plane RPC may hang forever on a silent peer.
# Long-poll methods (borrower polls, waits) pass explicit timeouts.
_flag("rpc_request_timeout_s", float, 120.0)
# call_with_retries backoff envelope (idempotent control-plane calls and
# token-carrying side-effectful ones)
_flag("rpc_retry_attempts", int, 5)
_flag("rpc_retry_base_delay_s", float, 0.1)
_flag("rpc_retry_max_delay_s", float, 2.0)
# keepalive: ping idle connections every interval; a peer silent for the
# timeout is declared dead (black-holed peers surface in O(timeout)
# instead of hanging a request forever). 0 disables. v3+ sessions only.
_flag("rpc_keepalive_interval_s", float, 2.0)
_flag("rpc_keepalive_timeout_s", float, 20.0)
# Serve (ray: serve/_private defaults)
_flag("serve_control_loop_period_s", float, 0.25)
_flag("serve_default_graceful_shutdown_timeout_s", float, 5.0)
# Handle-side routing staleness guard: replica-reported queue lengths
# older than this are IGNORED by power-of-two-choices scoring (local
# inflight counts only) — a wedged controller's stale snapshot must not
# keep steering traffic at a replica that has since filled up.
_flag("serve_replica_report_max_age_s", float, 5.0)
# LLM serving engine (serve/llm): continuous batching over an arena-
# paged KV cache with prefix-affinity routing. serve_llm_enabled=0
# disables every LLM-specific code path (handle-side prefix biasing,
# LLMServer construction); plain deployments never touch these either
# way. Page geometry: page_tokens tokens per page, kv_dim float32s per
# token; kv_pages is the per-replica page budget admission control
# guards. prefix_digest_max caps the chain hashes a replica reports in
# the controller load probe (wire-size bound on the affinity signal).
_flag("serve_llm_enabled", bool, True)
_flag("serve_llm_page_tokens", int, 16)
_flag("serve_llm_kv_dim", int, 64)
_flag("serve_llm_kv_pages", int, 512)
_flag("serve_llm_max_running", int, 8)
_flag("serve_llm_max_queued", int, 32)
_flag("serve_llm_prefix_cache_pages", int, 128)
_flag("serve_llm_prefix_digest_max", int, 256)
_flag("serve_llm_real_model", bool, False)
# Request observatory (reqtrace.py): per-request serve phase tracing.
# reqtrace_enabled gates every record path (zero-cost off, same posture
# as metrics/steptrace/memview); the ring holds the newest
# reqtrace_ring_size records per process (drop accounting rides the
# snapshot).
_flag("reqtrace_enabled", bool, True)
_flag("reqtrace_ring_size", int, 8192)
# per-node fan-out timeout inside reqtrace_cluster
_flag("reqtrace_scrape_timeout_s", float, 10.0)
# Tune (ray: tune/execution/experiment_state.py checkpoint period)
_flag("tune_experiment_snapshot_period_s", float, 10.0)
# Train (ray: train/_internal/backend_executor timeouts)
_flag("train_worker_start_timeout_s", float, 300.0)
_flag("train_result_poll_timeout_s", float, 900.0)
# Train fault tolerance (gang supervision + checkpointed recovery)
# interval between liveness pings / health polls of the worker gang
_flag("train_health_check_interval_s", float, 1.0)
# a rank that reports no step progress for this long is declared wedged
# (0 disables the progress watchdog; only liveness pings run)
_flag("train_progress_timeout_s", float, 0.0)
# master switch: tear down + re-place + restore-from-checkpoint on failure
# (off -> legacy behavior: surface the error to the trainer retry loop)
_flag("train_recovery_enabled", bool, True)
# SIGTERM drain: how long a worker may run past the signal to reach the
# next step boundary and checkpoint before it hard-exits
_flag("train_drain_grace_s", float, 30.0)
# In-graph gradient collective mode for build_train_step: "" lets the
# XLA partitioner insert the reduction from shardings (default,
# byte-identical to the pre-flag path); "chunked" splits the psum into
# train_ingraph_psum_chunks collectives for latency hiding; "quantized"
# rides the int8 wire format (parallel/collectives.py twins). Usually
# set per-run via JaxConfig(ingraph_psum=...), which fans it out to the
# worker gang.
_flag("train_ingraph_psum", str, "")
_flag("train_ingraph_psum_chunks", int, 4)


GLOBAL_CONFIG = _Config()
