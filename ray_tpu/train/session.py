"""Train session: worker↔driver report plumbing.

ray parity: python/ray/train/_internal/session.py:84 (_TrainSession),
air/session.py (report/get_checkpoint/get_context). Inside a train worker the
user loop calls ``report(metrics, checkpoint=...)``; results flow through a
queue polled by the BackendExecutor on the driver.

Step observatory hooks (_private/steptrace.py): ``init_session`` stamps
the worker's rank/world onto the process steptrace context,
``step_phase("data"|"h2d"|"compute"|"optimizer")`` records intra-step
phase intervals, and every ``report()`` auto-delimits a step boundary —
so a multi-rank trainer gets a merged per-step timeline
(``util.state.train_timeline()``) without any explicit instrumentation
beyond its existing report loop.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu._private import steptrace
from ray_tpu.air.checkpoint import Checkpoint


class TrainContext:
    def __init__(self, rank: int, world_size: int, local_rank: int = 0,
                 local_world_size: int = 1, node_rank: int = 0,
                 experiment_name: str = "", trial_name: str = "",
                 trial_id: str = "", trial_dir: str = ""):
        self._rank = rank
        self._world_size = world_size
        self._local_rank = local_rank
        self._local_world_size = local_world_size
        self._node_rank = node_rank
        self._experiment_name = experiment_name
        self._trial_name = trial_name
        self._trial_id = trial_id
        self._trial_dir = trial_dir

    def get_world_size(self) -> int:
        return self._world_size

    def get_world_rank(self) -> int:
        return self._rank

    def get_local_rank(self) -> int:
        return self._local_rank

    def get_local_world_size(self) -> int:
        return self._local_world_size

    def get_node_rank(self) -> int:
        return self._node_rank

    def get_experiment_name(self) -> str:
        return self._experiment_name

    def get_trial_name(self) -> str:
        return self._trial_name

    def get_trial_id(self) -> str:
        return self._trial_id

    def get_trial_dir(self) -> str:
        return self._trial_dir


class _Session:
    def __init__(self, ctx: TrainContext, loaded_checkpoint: Optional[Checkpoint]):
        self.ctx = ctx
        self.queue: "queue.Queue" = queue.Queue()
        self.loaded_checkpoint = loaded_checkpoint
        self.stop_requested = threading.Event()
        self.dataset_shards: Dict[str, Any] = {}
        # gang-supervision surface: progress heartbeat for the driver-side
        # watchdog (stamped at every report) and the SIGTERM drain latch
        self.drain_requested = threading.Event()
        self.step_count = 0
        self.last_progress = time.monotonic()
        # JaxTrainer(overlap_grads=True): GradSync dispatches gradient
        # allreduces on a background thread so collective chunk spans
        # interleave with the step's compute phase spans
        self.overlap_grads = False


_session: Optional[_Session] = None
_lock = threading.Lock()
# process default for overlap_grads: the backend's on_start runs before the
# worker enters its train loop (and so before init_session), so the trainer
# flag lands here and every subsequent session inherits it
_overlap_default = False


def init_session(ctx: TrainContext, loaded_checkpoint: Optional[Checkpoint]) -> _Session:
    global _session
    with _lock:
        _session = _Session(ctx, loaded_checkpoint)
        _session.overlap_grads = _overlap_default
    # steptrace records (phases, step boundaries, compiles) carry this
    # worker's rank from here on; step 0 starts now. The jax.monitoring
    # listener mirrors backend compile events into the ring so compile
    # storms show up even for jitted fns nobody wrapped in trace_jit.
    steptrace.set_train_context(ctx.get_world_rank(), ctx.get_world_size())
    steptrace.install_compile_listener()
    return _session


def shutdown_session():
    global _session
    with _lock:
        _session = None
    steptrace.clear_train_context()


def get_session() -> Optional[_Session]:
    return _session


def request_drain() -> bool:
    """Ask the active session to drain: checkpoint at the next step boundary
    (the next ``report()``) and exit cleanly. Returns whether a session was
    there to accept — the SIGTERM handler falls back to immediate exit when
    no training is in flight."""
    s = _session
    if s is None:
        return False
    s.drain_requested.set()
    return True


def health() -> Dict[str, Any]:
    """Progress snapshot for the driver-side gang watchdog."""
    s = _session
    if s is None:
        return {"active": False}
    return {
        "active": True,
        "step": s.step_count,
        "since_progress_s": time.monotonic() - s.last_progress,
        "draining": s.drain_requested.is_set(),
    }


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None):
    """ray parity: ray.train.report — ship metrics (+ checkpoint) to the
    driver. Outside a session, a no-op with the metrics returned for
    testability."""
    s = _session
    if s is None:
        return metrics
    # step observatory: a report IS the natural step boundary — close the
    # current step interval and open the next (steptrace no-ops when off)
    steptrace.step_mark()
    s.step_count += 1
    s.last_progress = time.monotonic()
    payload = {"type": "report", "metrics": dict(metrics)}
    if checkpoint is not None:
        # Materialize to a directory so the driver (possibly another node)
        # persists it from shared storage; in-memory dicts ride the queue.
        payload["checkpoint_data"] = (
            checkpoint._data if checkpoint._data is not None else None
        )
        payload["checkpoint_path"] = checkpoint._path
    draining = s.drain_requested.is_set()
    if draining:
        # spot preemption: this report is the step boundary the drain was
        # waiting for — tag it so the executor requeues WITHOUT burning a
        # failure-budget slot, then exit the loop cleanly
        payload["drain"] = True
    s.queue.put(payload)
    if draining:
        raise SystemExit("drain requested (preemption)")
    if s.stop_requested.is_set():
        raise SystemExit("training stop requested")


def step_phase(name: str):
    """Context manager delimiting one phase of the current training step
    — canonical phases are ``"data"`` (host-side batch prep), ``"h2d"``
    (host-to-device transfer), ``"compute"`` (the jitted step), and
    ``"optimizer"`` (update/apply); free-form names render too. Records
    into the step observatory ring (zero-cost when steptrace is
    disabled); the merged multi-rank view comes back through
    ``util.state.train_timeline()`` / ``ray_tpu train timeline``::

        with train.step_phase("data"):
            batch = next(it)
        with train.step_phase("compute"):
            params, opt_state, loss = step(params, opt_state, batch)
        train.report({"loss": float(loss)})   # step boundary
    """
    return steptrace.phase(name)


def set_overlap_grads(enabled: bool) -> bool:
    """Arm (or disarm) gradient/compute overlap — the trainer's
    ``overlap_grads=True`` lands here on each worker (at backend
    ``on_start``, i.e. usually before the session exists, hence the
    sticky process default). Returns whether a live session took it."""
    global _overlap_default
    _overlap_default = bool(enabled)
    s = _session
    if s is None:
        return False
    s.overlap_grads = bool(enabled)
    return True


class GradSync:
    """Per-tensor gradient allreduce with optional compute overlap.

    ``submit(name, grad)`` hands one gradient tensor to the collective
    backend; ``results()`` waits for everything submitted and returns
    ``{name: reduced}`` in submission order. With overlap on (the
    session's ``overlap_grads`` flag, or ``overlap=True`` explicitly),
    submits dispatch on ONE background thread so the store-path chunked
    allreduce runs under the remaining backward/step compute — its
    collective + chunk spans interleave with ``step_phase("compute")``
    spans in the train timeline (T3-style, arxiv 2401.16677). With
    overlap off, submit reduces inline (same results, serial timeline).

    Ordering contract: all ranks must submit the same tensor names in
    the same order (the usual DDP bucket contract) — the single
    dispatch thread preserves submission order, so the group's seq
    numbers stay aligned across ranks. Don't run other collectives on
    the same group concurrently with a live GradSync.
    """

    def __init__(self, group_name: str = "train_dp", op: str = "mean",
                 overlap: Optional[bool] = None,
                 timeout: Optional[float] = None):
        s = _session
        if overlap is None:
            overlap = bool(s and s.overlap_grads)
        self.group_name = group_name
        self.op = op
        self.overlap = overlap
        self.timeout = timeout
        self._pending: list = []  # (name, result | Future)
        self._pool = None
        if overlap:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="gradsync")

    def _reduce(self, tensor):
        from ray_tpu.util import collective as col

        kwargs = {}
        if self.timeout is not None:
            kwargs["timeout"] = self.timeout
        return col.allreduce(tensor, self.group_name, op=self.op, **kwargs)

    def submit(self, name: str, tensor) -> None:
        if self._pool is not None:
            self._pending.append((name, self._pool.submit(self._reduce,
                                                          tensor)))
        else:
            self._pending.append((name, self._reduce(tensor)))

    def results(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        pending, self._pending = self._pending, []
        for name, r in pending:
            out[name] = r.result() if hasattr(r, "result") else r
        return out

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.results()
        self.close()
        return False


def get_checkpoint() -> Optional[Checkpoint]:
    s = _session
    return s.loaded_checkpoint if s else None


def get_dataset_shard(dataset_name: str = "train"):
    """ray parity: ray.train.get_dataset_shard — this worker's streaming
    split of the Dataset passed to the trainer's ``datasets=``."""
    s = _session
    if s is None:
        return None
    return s.dataset_shards.get(dataset_name)


def get_context() -> TrainContext:
    s = _session
    if s is None:
        return TrainContext(rank=0, world_size=1)
    return s.ctx
