"""Trainers: BaseTrainer → DataParallelTrainer → JaxTrainer / TorchTrainer.

ray parity: python/ray/train/base_trainer.py:68 (BaseTrainer.fit:569),
data_parallel_trainer.py:58, torch/torch_trainer.py:16. The flagship is
JaxTrainer — the reference's TorchTrainer NCCL-DDP path re-imagined TPU-first:
each worker is a host owning its chips, the step function is jitted over a
Mesh, gradient reduction is in-graph psum on ICI (not a host-side allreduce),
and multi-host wiring is jax.distributed keyed by the worker gang.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig, JaxConfig, TorchConfig
from ray_tpu.train.backend_executor import BackendExecutor


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap this trainer as a Tune trainable (ray parity:
        base_trainer.py:828) so Tuner(trainer) works."""
        trainer = self

        def _trainable(config):
            import copy

            t = copy.copy(trainer)
            merged = dict(getattr(t, "train_loop_config", None) or {})
            merged.update(config.get("train_loop_config", config) or {})
            t.train_loop_config = merged

            # Relay worker reports up through the Tune session so schedulers
            # see intermediate results (falls through to the Train session
            # when no Tune trial is active).
            from ray_tpu.tune import session as session_mod

            def cb(metrics, checkpoint):
                session_mod.report(metrics, checkpoint=checkpoint)

            result = t._fit_impl(result_callback=cb)
            if result.error:
                raise result.error
            return result.metrics or {}

        _trainable.__name__ = type(self).__name__
        return _trainable


class DataParallelTrainer(BaseTrainer):
    """ray parity: train/data_parallel_trainer.py:58."""

    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            scaling_config=scaling_config, run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint, datasets=datasets,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or self._default_backend_config

    def _runtime_env(self) -> Optional[dict]:
        env_vars = getattr(self.backend_config, "env_vars", None)
        if env_vars:
            return {"env_vars": dict(env_vars)}
        return None

    def _fit_impl(self, result_callback=None) -> Result:
        executor = BackendExecutor(
            self.backend_config, self.scaling_config, self.run_config
        )
        try:
            executor.start(
                runtime_env=self._runtime_env(),
                checkpoint=self.resume_from_checkpoint,
            )
            cfg = dict(self.train_loop_config)
            if self.datasets:
                cfg["__datasets__"] = self._shard_datasets()
            result = executor.run(
                self.train_loop_per_worker, cfg, result_callback=result_callback
            )
            return result
        except Exception as e:
            from ray_tpu.train.backend_executor import TrainingFailedError

            err = e if isinstance(e, TrainingFailedError) else TrainingFailedError(str(e))
            return Result(metrics=None, checkpoint=None, error=err,
                          path=executor.trial_dir)
        finally:
            executor.shutdown()

    def _shard_datasets(self):
        """Attach per-worker dataset shards (streaming_split analog)."""
        out = {}
        for name, ds in self.datasets.items():
            try:
                out[name] = ds.streaming_split(self.scaling_config.num_workers)
            except AttributeError:
                out[name] = [ds] * self.scaling_config.num_workers
        return out

    def fit(self) -> Result:
        from ray_tpu.train.backend_executor import FailureBudgetExhaustedError

        result = self._fit_impl()
        failure_cfg = self.run_config.failure_config
        retries = failure_cfg.max_failures
        # Gang failures (rank death, wedge) are recovered IN-PLACE by the
        # BackendExecutor against the same budget; a budget-exhausted
        # outcome is terminal and must not be retried from scratch here.
        # This outer loop remains the from-scratch fallback for
        # application errors, which the in-place path does not retry.
        while (result.error is not None and retries != 0
               and not isinstance(result.error, FailureBudgetExhaustedError)):
            retries -= 1
            result = self._fit_impl()
        if result.error is not None and self.run_config.failure_config.fail_fast:
            raise result.error
        return result


class JaxTrainer(DataParallelTrainer):
    """The TPU-native data-parallel trainer (flagship).

    Replaces the reference's TorchTrainer+NCCL
    (ray: train/torch/torch_trainer.py:16, torch/config.py:69): worker = host
    owning all its chips, `jax.distributed` across hosts, in-graph psum for
    gradients. `train_loop_per_worker` uses ray_tpu.train.get_context() for
    rank info and builds meshes via ray_tpu.parallel.
    """

    def __init__(self, train_loop_per_worker, *, jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 overlap_grads: bool = False, **kwargs):
        scaling_config = scaling_config or ScalingConfig()
        jc = jax_config or JaxConfig(use_tpu=scaling_config.use_tpu)
        if overlap_grads:
            # arm session.GradSync overlap on every worker: gradient
            # allreduces run chunk-pipelined under the step's compute
            jc.overlap_grads = True
        super().__init__(
            train_loop_per_worker,
            backend_config=jc,
            scaling_config=scaling_config,
            **kwargs,
        )


class TorchTrainer(DataParallelTrainer):
    """ray parity: train/torch/torch_trainer.py:16 — CPU gloo process group
    (the reference's NCCL path has no TPU analog; gloo keeps torch workloads
    runnable for migration)."""

    def __init__(self, train_loop_per_worker, *, torch_config: Optional[TorchConfig] = None,
                 **kwargs):
        super().__init__(
            train_loop_per_worker,
            backend_config=torch_config or TorchConfig(),
            **kwargs,
        )


class TensorflowTrainer(DataParallelTrainer):
    """ray parity: train/tensorflow/tensorflow_trainer.py:108 — workers get
    TF_CONFIG so MultiWorkerMirroredStrategy forms the collective ring.
    (On TPU clusters prefer JaxTrainer; this keeps TF workloads runnable
    for migration, like TorchTrainer does for torch.)"""

    def __init__(self, train_loop_per_worker, *,
                 tensorflow_config: Optional["TensorflowConfig"] = None,
                 **kwargs):
        from ray_tpu.train.backend import TensorflowConfig

        super().__init__(
            train_loop_per_worker,
            backend_config=tensorflow_config or TensorflowConfig(),
            **kwargs,
        )


class SklearnTrainer(DataParallelTrainer):
    """ray parity: train/sklearn/sklearn_trainer.py — fit one sklearn
    estimator on the full dataset on a single worker (sklearn has no
    distributed fit; N workers would each fit a partial model on a shard);
    the fitted model ships back as the checkpoint."""

    def __init__(self, *, estimator, datasets: dict,
                 label_column: str,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 fit_params: Optional[dict] = None, **kwargs):
        import cloudpickle

        if not datasets or "train" not in datasets:
            raise ValueError("SklearnTrainer requires datasets={'train': ...}")
        if not label_column:
            raise ValueError("SklearnTrainer requires label_column")
        scaling_config = scaling_config or ScalingConfig(num_workers=1)
        if scaling_config.num_workers != 1:
            raise ValueError(
                "SklearnTrainer fits one estimator on the full dataset; "
                f"num_workers must be 1, got {scaling_config.num_workers}"
            )
        est_blob = cloudpickle.dumps(estimator)
        label = label_column
        fit_params = fit_params or {}

        def train_loop():
            import cloudpickle as cp
            import numpy as np

            from ray_tpu import train as train_mod
            from ray_tpu.air import Checkpoint

            est = cp.loads(est_blob)
            ds = train_mod.get_dataset_shard("train")
            Xs, ys = [], []
            for batch in ds.iter_batches(batch_size=4096,
                                         batch_format="pandas"):
                ys.append(batch[label].to_numpy())
                Xs.append(batch.drop(columns=[label]).to_numpy())
            X = np.concatenate(Xs)
            y = np.concatenate(ys)
            est.fit(X, y, **fit_params)
            score = float(est.score(X, y))
            train_mod.report(
                {"train_score": score},
                checkpoint=Checkpoint.from_dict({"model": cp.dumps(est)}),
            )

        super().__init__(
            train_loop,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            **kwargs,
        )
