"""HuggingFace Transformers integration for Train.

Reference parity: ray python/ray/train/huggingface/transformers/ —
``prepare_trainer`` + ``RayTrainReportCallback`` bridge a user-built
``transformers.Trainer`` into the Train session (log lines become
``train.report`` calls; HF checkpoint saves travel as Train checkpoints),
and ``TransformersTrainer`` runs the whole thing per worker inside the
torch (gloo) process group — HF's own Trainer picks up RANK/WORLD_SIZE
from the backend's env wiring and wraps the model in DDP itself.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from transformers.trainer_callback import TrainerCallback

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train import session
from ray_tpu.train.backend import TorchConfig
from ray_tpu.train.trainer import DataParallelTrainer


class RayTrainReportCallback(TrainerCallback):
    """transformers.TrainerCallback → ray_tpu.train.report bridge.

    Log events report metrics immediately. HF fires on_log BEFORE
    on_save within the same step, so a saved checkpoint is reported from
    on_save, paired with the metrics that step just logged — checkpoint
    scoring (CheckpointConfig.checkpoint_score_attribute) then ranks each
    checkpoint by its own step's metrics, not the next step's."""

    def __init__(self):
        self._last_logs: dict = {}

    def _metrics(self, state):
        metrics = dict(self._last_logs)
        metrics["step"] = state.global_step
        if state.epoch is not None:
            metrics["epoch"] = state.epoch
        return metrics

    def on_log(self, args, state, control, logs=None, **kwargs):
        self._last_logs = dict(logs or {})
        session.report(self._metrics(state))

    def on_save(self, args, state, control, **kwargs):
        path = os.path.join(args.output_dir,
                            f"checkpoint-{state.global_step}")
        if os.path.isdir(path):
            session.report(self._metrics(state),
                           checkpoint=Checkpoint(path=path))


def prepare_trainer(trainer):
    """Attach the report bridge if absent (ray parity:
    train.huggingface.transformers.prepare_trainer)."""
    has = any(
        isinstance(cb, RayTrainReportCallback)
        for cb in trainer.callback_handler.callbacks
    )
    if not has:
        trainer.add_callback(RayTrainReportCallback())
    return trainer


class TransformersTrainer(DataParallelTrainer):
    """ray parity: train/huggingface/transformers — each worker calls
    ``trainer_init_per_worker(config) -> transformers.Trainer`` inside the
    gloo process group and runs ``.train()``; reports/checkpoints flow
    through RayTrainReportCallback."""

    def __init__(self, trainer_init_per_worker: Callable, *,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        def train_loop(config=None):
            trainer = trainer_init_per_worker(config or {})
            prepare_trainer(trainer)
            trainer.train()

        super().__init__(
            train_loop,
            backend_config=torch_config or TorchConfig(),
            **kwargs,
        )
