"""GBDT trainers: XGBoost + LightGBM.

ray parity: python/ray/train/gbdt_trainer.py:109 (GBDTTrainer) +
train/xgboost/xgboost_trainer.py, train/lightgbm/lightgbm_trainer.py —
boosting over Dataset shards with per-round metric reporting and the
fitted booster as the checkpoint. This image does not bundle xgboost/
lightgbm, so the trainers GATE: constructing one without its library
raises ImportError up front (never silently degrade); with the library
present the full fit/checkpoint/resume surface runs. Boosting itself is
single-process multi-threaded (the libraries' own parallelism) — the
reference's rabit/dask collective ring has no offline analog here.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import DataParallelTrainer


class _GBDTTrainer(DataParallelTrainer):
    """Shared driver: materialize the train/validation shards to matrices,
    boost num_boost_round rounds reporting eval metrics each round, ship
    the booster as the checkpoint (ray parity: gbdt_trainer.py:109)."""

    _module_name: str = ""

    def __init__(self, *, params: dict, datasets: dict, label_column: str,
                 num_boost_round: int = 10,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None, **kwargs):
        self._check_import()
        if not datasets or "train" not in datasets:
            raise ValueError(
                f"{type(self).__name__} requires datasets={{'train': ...}}"
            )
        if not label_column:
            raise ValueError(f"{type(self).__name__} requires label_column")
        scaling_config = scaling_config or ScalingConfig(num_workers=1)
        if scaling_config.num_workers != 1:
            # N workers would each boost an independent model on 1/N of
            # the rows — silently worse, never what the caller meant
            raise ValueError(
                f"{type(self).__name__} boosts one model on the full "
                f"dataset; num_workers must be 1, got "
                f"{scaling_config.num_workers}"
            )
        module_name = self._module_name
        label = label_column
        has_valid = "validation" in datasets

        def train_loop():
            import importlib

            import numpy as np

            from ray_tpu import train as train_mod
            from ray_tpu.air import Checkpoint

            lib = importlib.import_module(module_name)

            def to_xy(name):
                ds = train_mod.get_dataset_shard(name)
                Xs, ys = [], []
                for batch in ds.iter_batches(batch_size=4096,
                                             batch_format="pandas"):
                    ys.append(batch[label].to_numpy())
                    Xs.append(batch.drop(columns=[label]).to_numpy())
                return np.concatenate(Xs), np.concatenate(ys)

            X, y = to_xy("train")
            evals = [("train", X, y)]
            if has_valid:
                Xv, yv = to_xy("validation")
                evals.append(("validation", Xv, yv))
            if module_name == "xgboost":
                dtrain = lib.DMatrix(X, label=y)
                # reuse dtrain in the watch list: a second DMatrix of the
                # same rows would double peak training-data memory
                watch = [(dtrain, "train")] + [
                    (lib.DMatrix(ex, label=ey), name)
                    for name, ex, ey in evals[1:]
                ]
                results: dict = {}
                booster = lib.train(
                    params, dtrain, num_boost_round=num_boost_round,
                    evals=watch, evals_result=results, verbose_eval=False,
                )
                for i in range(num_boost_round):
                    metrics = {
                        f"{split}-{metric}": vals[i]
                        for split, md in results.items()
                        for metric, vals in md.items()
                    }
                    metrics["training_iteration"] = i + 1
                    ckpt = None
                    if i == num_boost_round - 1:
                        ckpt = Checkpoint.from_dict(
                            {"model": booster.save_raw("ubj"),
                             "format": "xgboost-ubj"}
                        )
                    train_mod.report(metrics, checkpoint=ckpt)
            else:  # lightgbm
                dtrain = lib.Dataset(X, label=y)
                valid_sets = [
                    lib.Dataset(ex, label=ey, reference=dtrain)
                    for _, ex, ey in evals
                ]
                record: dict = {}
                booster = lib.train(
                    params, dtrain, num_boost_round=num_boost_round,
                    valid_sets=valid_sets,
                    valid_names=[name for name, _, _ in evals],
                    callbacks=[lib.record_evaluation(record)],
                )
                for i in range(num_boost_round):
                    metrics = {
                        f"{split}-{metric}": vals[i]
                        for split, md in record.items()
                        for metric, vals in md.items()
                    }
                    metrics["training_iteration"] = i + 1
                    ckpt = None
                    if i == num_boost_round - 1:
                        ckpt = Checkpoint.from_dict(
                            {"model": booster.model_to_string(),
                             "format": "lightgbm-str"}
                        )
                    train_mod.report(metrics, checkpoint=ckpt)

        super().__init__(
            train_loop,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            **kwargs,
        )

    def _check_import(self):
        import importlib

        try:
            importlib.import_module(self._module_name)
        except ImportError as e:
            raise ImportError(
                f"{type(self).__name__} requires the '{self._module_name}' "
                f"package, which is not installed in this environment"
            ) from e


class XGBoostTrainer(_GBDTTrainer):
    """ray parity: train/xgboost/xgboost_trainer.py XGBoostTrainer."""

    _module_name = "xgboost"

    @staticmethod
    def get_model(checkpoint):
        import xgboost as xgb

        d = checkpoint.to_dict()
        booster = xgb.Booster()
        booster.load_model(bytearray(d["model"]))
        return booster


class LightGBMTrainer(_GBDTTrainer):
    """ray parity: train/lightgbm/lightgbm_trainer.py LightGBMTrainer."""

    _module_name = "lightgbm"

    @staticmethod
    def get_model(checkpoint):
        import lightgbm as lgb

        d = checkpoint.to_dict()
        return lgb.Booster(model_str=d["model"])
