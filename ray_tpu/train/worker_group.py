"""Train worker gang.

ray parity: python/ray/train/_internal/worker_group.py:100 (WorkerGroup of
RayTrainWorker actors) — a gang of actors, one per host-worker, created
inside a placement group, each running the user train loop on a session
thread and draining a result queue.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train import session as session_mod


@ray_tpu.remote
class TrainWorker:
    """ray parity: worker_group.py:18 RayTrainWorker."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._session = None
        self._final: Optional[dict] = None

    def setup_session(self, rank: int, world_size: int, local_rank: int,
                      node_rank: int, experiment_name: str, trial_id: str,
                      trial_dir: str, checkpoint: Optional[Checkpoint]):
        ctx = session_mod.TrainContext(
            rank=rank, world_size=world_size, local_rank=local_rank,
            node_rank=node_rank, experiment_name=experiment_name,
            trial_id=trial_id, trial_dir=trial_dir,
        )
        self._session = session_mod.init_session(ctx, checkpoint)
        return True

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary callable on the worker (backend setup hooks)."""
        return fn(*args, **kwargs)

    def _rt_init_collective(self, world_size, rank, backend, group_name,
                            epoch=0, quant=""):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend, group_name,
                                  epoch=epoch, quant=quant)
        return rank

    def ping(self):
        """Liveness probe: a dead worker raises ActorDiedError at the
        caller; a live one answers immediately (the gang is created with
        max_concurrency>1 so this never queues behind next_result)."""
        return True

    def health(self):
        """Progress snapshot for the executor's per-step watchdog."""
        return session_mod.health()

    def request_drain(self):
        """Preemption notice: checkpoint at the next step boundary and
        exit cleanly (same path the worker's SIGTERM handler takes)."""
        return session_mod.request_drain()

    def start_training(self, train_fn: Callable, config: dict):
        assert self._session is not None, "setup_session must run first"
        sess = self._session
        shards = config.pop("__datasets__", None)
        if shards:
            rank = sess.ctx.get_world_rank()
            sess.dataset_shards = {
                name: splits[rank] for name, splits in shards.items()
            }

        def _run():
            try:
                import inspect

                sig = inspect.signature(train_fn)
                if len(sig.parameters) >= 1:
                    train_fn(config)
                else:
                    train_fn()
                sess.queue.put({"type": "done"})
            except SystemExit:
                sess.queue.put({"type": "done"})
            except BaseException as e:  # noqa: BLE001
                sess.queue.put({
                    "type": "error",
                    "error": f"{type(e).__name__}: {e}",
                    "traceback": traceback.format_exc(),
                })

        self._thread = threading.Thread(target=_run, name="train-loop", daemon=True)
        self._thread.start()
        return True

    def next_result(self, timeout: float = 300.0):
        """Block for the next report/done/error from the train loop. An
        empty poll piggybacks the session health snapshot so the
        executor's watchdog sees per-rank step progress without a second
        RPC round."""
        import queue as _q

        try:
            return self._session.queue.get(timeout=timeout)
        except _q.Empty:
            return {"type": "timeout", "health": session_mod.health()}

    def request_stop(self):
        if self._session:
            self._session.stop_requested.set()
        return True

    def shutdown_session(self):
        session_mod.shutdown_session()
        return True


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: dict,
                 placement_group=None, runtime_env: Optional[dict] = None,
                 generation: int = 0):
        from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

        self.num_workers = num_workers
        # gang generation: 0 on first placement, bumped by the executor on
        # each recovery re-placement; threaded into the collective group
        # epoch so the re-formed gang's rendezvous keys are fresh
        self.generation = generation
        self.workers: List = []
        for i in range(num_workers):
            # max_concurrency=4: liveness pings and health polls must
            # interleave with the long-blocking next_result call
            opts = dict(resources=dict(resources_per_worker), num_cpus=0,
                        max_concurrency=4)
            if placement_group is not None:
                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group, placement_group_bundle_index=i
                )
            if runtime_env:
                opts["runtime_env"] = runtime_env
            self.workers.append(TrainWorker.options(**opts).remote())

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=600,
        )

    def execute_single(self, index: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(
            self.workers[index].execute.remote(fn, *args, **kwargs), timeout=600
        )

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
