from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.train.backend import (Backend, BackendConfig, JaxConfig,
                                   TensorflowConfig, TorchConfig)
from ray_tpu.train.backend_executor import (BackendExecutor,
                                            FailureBudgetExhaustedError,
                                            TrainingFailedError)
from ray_tpu.train.session import (GradSync, get_checkpoint, get_context,
                                   get_dataset_shard, report,
                                   set_overlap_grads, step_phase)
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    TorchTrainer,
    SklearnTrainer,
    TensorflowTrainer,
)
from ray_tpu.train.gbdt import LightGBMTrainer, XGBoostTrainer
from ray_tpu.train.predictor import (BatchPredictor, JaxPredictor,
                                     Predictor, SklearnPredictor,
                                     XGBoostPredictor)
from ray_tpu.train.worker_group import WorkerGroup

__all__ = [
    "BatchPredictor",
    "JaxPredictor",
    "Predictor",
    "SklearnPredictor",
    "XGBoostPredictor",
    "Backend",
    "BackendConfig",
    "BackendExecutor",
    "BaseTrainer",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureBudgetExhaustedError",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TorchConfig",
    "TorchTrainer",
    "TensorflowConfig",
    "TensorflowTrainer",
    "SklearnTrainer",
    "TrainingFailedError",
    "WorkerGroup",
    "GradSync",
    "get_checkpoint",
    "get_dataset_shard",
    "get_context",
    "report",
    "set_overlap_grads",
    "step_phase",
    "TransformersTrainer",
]


def __getattr__(name):
    # transformers imports are heavy; load the HF integration lazily
    if name == "TransformersTrainer":
        from ray_tpu.train.huggingface import TransformersTrainer

        return TransformersTrainer
    raise AttributeError(name)
