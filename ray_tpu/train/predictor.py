"""Predictors + BatchPredictor: checkpoint -> inference, single-batch or
Dataset-scale.

Reference parity: ray python/ray/train/predictor.py (Predictor ABC),
train/batch_predictor.py (BatchPredictor: checkpoint + predictor class
fanned out over ``Dataset.map_batches`` with an actor pool), and the
per-framework predictors (torch/tensorflow/xgboost/sklearn
``*_predictor.py``). TPU-native: the first-class predictor is
``JaxPredictor`` — a jitted apply function over checkpointed params, so
batch scoring rides the same compiled path as training; sklearn and
XGBoost predictors cover the tabular ecosystem.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint

_PREDICTOR_BLOB = "predictor.pkl"


class Predictor:
    """Single-process inference over numpy batches (dict of arrays or a
    single array)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # -- checkpoint plumbing shared by the framework predictors ---------
    @staticmethod
    def _payload(checkpoint: Checkpoint) -> Dict[str, Any]:
        import cloudpickle

        data = checkpoint.to_dict()
        if _PREDICTOR_BLOB in data:
            return cloudpickle.loads(data[_PREDICTOR_BLOB])
        return data

    @staticmethod
    def pack_checkpoint(**payload) -> Checkpoint:
        """Build a Checkpoint a predictor can restore from (the shape the
        framework trainers' save paths produce)."""
        import cloudpickle

        return Checkpoint.from_dict(
            {_PREDICTOR_BLOB: cloudpickle.dumps(payload)}
        )


def _as_feature_matrix(batch) -> np.ndarray:
    if isinstance(batch, dict):
        cols = [np.asarray(v) for v in batch.values()]
        cols = [c[:, None] if c.ndim == 1 else c for c in cols]
        return np.concatenate(cols, axis=1)
    return np.asarray(batch)


class JaxPredictor(Predictor):
    """Applies a checkpointed (apply_fn, params) pair, jitted once.

    ``apply_fn(params, batch_array) -> array``; construct checkpoints
    with ``JaxPredictor.pack(apply_fn, params)``."""

    def __init__(self, apply_fn: Callable, params):
        import jax

        self._apply = jax.jit(apply_fn)
        self._params = params

    @classmethod
    def pack(cls, apply_fn: Callable, params) -> Checkpoint:
        import jax

        return cls.pack_checkpoint(
            apply_fn=apply_fn, params=jax.device_get(params)
        )

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **_kw) -> "JaxPredictor":
        payload = cls._payload(checkpoint)
        return cls(payload["apply_fn"], payload["params"])

    def predict(self, batch) -> Dict[str, np.ndarray]:
        x = _as_feature_matrix(batch).astype(np.float32)
        out = self._apply(self._params, x)
        return {"predictions": np.asarray(out)}


class SklearnPredictor(Predictor):
    """Wraps a fitted sklearn estimator (ray parity:
    train/sklearn/sklearn_predictor.py)."""

    def __init__(self, estimator):
        self._est = estimator

    @classmethod
    def pack(cls, estimator) -> Checkpoint:
        return cls.pack_checkpoint(estimator=estimator)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **_kw) -> "SklearnPredictor":
        return cls(cls._payload(checkpoint)["estimator"])

    def predict(self, batch) -> Dict[str, np.ndarray]:
        x = _as_feature_matrix(batch)
        return {"predictions": np.asarray(self._est.predict(x))}


class XGBoostPredictor(Predictor):
    """Wraps a trained xgboost Booster (ray parity:
    train/xgboost/xgboost_predictor.py)."""

    def __init__(self, booster):
        self._booster = booster

    @classmethod
    def pack(cls, booster) -> Checkpoint:
        return cls.pack_checkpoint(raw=booster.save_raw())

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **_kw) -> "XGBoostPredictor":
        import xgboost

        payload = cls._payload(checkpoint)
        booster = xgboost.Booster()
        booster.load_model(bytearray(payload["raw"]))
        return cls(booster)

    def predict(self, batch) -> Dict[str, np.ndarray]:
        import xgboost

        x = _as_feature_matrix(batch)
        return {
            "predictions": np.asarray(
                self._booster.predict(xgboost.DMatrix(x))
            )
        }


class _ScoringWorker:
    """Actor-pool callable for map_batches: loads the predictor ONCE per
    worker, scores every batch routed to it."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], kwargs: Dict):
        self._predictor = predictor_cls.from_checkpoint(checkpoint, **kwargs)

    def __call__(self, batch):
        return self._predictor.predict(batch)


class BatchPredictor:
    """Offline batch scoring: a checkpoint + predictor class applied over
    a Dataset with an actor pool (ray parity:
    train/batch_predictor.py BatchPredictor.predict)."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, dataset, *, batch_size: Optional[int] = None,
                concurrency: int = 2, num_cpus: float = 1.0):
        """Returns a Dataset of ``{"predictions": ...}`` blocks; lazy —
        consumption drives the streaming executor."""
        return dataset.map_batches(
            _ScoringWorker,
            batch_size=batch_size,
            batch_format="numpy",
            concurrency=concurrency,
            num_cpus=num_cpus,
            fn_constructor_args=(
                self._checkpoint, self._predictor_cls, self._kwargs
            ),
        )
