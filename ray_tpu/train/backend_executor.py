"""BackendExecutor: worker-gang lifecycle + training loop pump.

ray parity: python/ray/train/_internal/backend_executor.py:46 — create the
placement group (:165), start the WorkerGroup, wire ranks (:273), run the
backend's process-group setup, pump reports/checkpoints (:343-466), restart
on failure (:647). TPU delta: one worker per host (not per chip), STRICT_PACK
maps the gang onto one slice when requested.

Elastic fault tolerance (the recovery loop ray's :647 restart sketch grew
into): the pump doubles as a gang supervisor — short-interval result polls
piggyback per-rank session health, so a dead rank surfaces as a prompt
actor-death error and a wedged-but-alive rank trips the per-step progress
watchdog in seconds instead of at collective-timeout. On a recoverable
failure the executor plants the collective abort marker (unwedging
survivors with CollectiveWorldChangedError), drains steptrace, tears the
gang down, re-requests placement, and restarts the user loop from the
latest reported checkpoint at the next gang generation — decrementing
``FailureConfig.max_failures``. A SIGTERM drain (spot preemption)
checkpoints at the next step boundary and requeues WITHOUT burning a
failure-budget slot. Every transition is measured:
``train_worker_failures_total{cause=}``, ``train_restarts_total``, and a
detection→ready ``train_recovery_seconds`` histogram, plus a restart span
in the merged train timeline.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional

import ray_tpu
from ray_tpu._private import steptrace
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)

TRAIN_GROUP_NAME = "train_dp"


class TrainingFailedError(RuntimeError):
    pass


class FailureBudgetExhaustedError(TrainingFailedError):
    """A recoverable gang failure landed with no ``max_failures`` budget
    left. Terminal: the trainer's outer retry loop must not re-run it."""


class ProgressWatchdog:
    """Per-rank step-progress watchdog (pure; unit-testable).

    A rank ARMS at its first observed progress (first report or first
    health snapshot showing a completed step) — before that it may
    legitimately sit in trace/compile for minutes. Once armed, a rank
    whose progress timestamp goes stale by more than ``timeout_s`` is
    declared wedged. ``timeout_s <= 0`` disables the watchdog entirely.
    """

    def __init__(self, num_workers: int, timeout_s: float):
        self.timeout_s = float(timeout_s)
        self._steps = [0] * num_workers
        self._last: List[Optional[float]] = [None] * num_workers

    def touch(self, rank: int, now: Optional[float] = None):
        """Direct progress evidence (a report arrived from this rank)."""
        self._last[rank] = time.monotonic() if now is None else now

    def observe(self, rank: int, step: int, now: Optional[float] = None):
        """Health-snapshot evidence: arms/refreshes only when the rank's
        completed-step count has advanced past what we last saw."""
        if step > self._steps[rank]:
            self._steps[rank] = step
            self._last[rank] = time.monotonic() if now is None else now

    def disarm(self, rank: int):
        self._last[rank] = None

    def wedged(self, now: Optional[float] = None) -> List[int]:
        if self.timeout_s <= 0:
            return []
        now = time.monotonic() if now is None else now
        return [
            r for r, last in enumerate(self._last)
            if last is not None and now - last > self.timeout_s
        ]


def _ft_metrics():
    """The executor's fault-tolerance metric families on the process
    registry (driver-side, so they ride the merged /metrics cluster
    scrape). Families are registered idempotently."""
    from ray_tpu._private import metrics_core

    reg = metrics_core.registry()
    return (
        reg.counter("train_worker_failures_total",
                    "train gang failures by cause "
                    "(actor_died/wedged/unresponsive/drain)"),
        reg.counter("train_restarts_total",
                    "gang recovery restarts (teardown -> re-place -> "
                    "restore from checkpoint)"),
        reg.histogram("train_recovery_seconds",
                      "failure detection -> new generation training-ready",
                      scale=metrics_core.LATENCY),
    )


class _CheckpointBook:
    """Keep top-K checkpoints (ray parity: air/_internal/checkpoint_manager.py:251)."""

    def __init__(self, trial_dir: str, config: CheckpointConfig):
        self.trial_dir = trial_dir
        self.config = config
        self.saved: List[tuple] = []  # (score, index, path)
        self.index = 0

    def persist(self, data: Optional[dict], src_path: Optional[str],
                metrics: dict) -> Checkpoint:
        path = os.path.join(self.trial_dir, f"checkpoint_{self.index:06d}")
        self.index += 1
        ckpt = Checkpoint(_data=data) if data is not None else Checkpoint(path=src_path)
        ckpt.to_directory(path)
        final = Checkpoint(path=path)
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr and attr in metrics:
            score = metrics[attr]
        self.saved.append((score, self.index - 1, path))
        self._evict()
        return final

    def _evict(self):
        keep = self.config.num_to_keep
        if keep is None or len(self.saved) <= keep:
            return
        attr = self.config.checkpoint_score_attribute
        if attr:
            reverse = self.config.checkpoint_score_order == "max"
            ranked = sorted(
                self.saved,
                key=lambda t: (t[0] is not None, t[0] if t[0] is not None else 0),
                reverse=reverse,
            )
        else:
            ranked = sorted(self.saved, key=lambda t: -t[1])  # newest first
        for score, idx, path in ranked[keep:]:
            import shutil

            shutil.rmtree(path, ignore_errors=True)
            self.saved.remove((score, idx, path))

    def latest(self) -> Optional[Checkpoint]:
        if not self.saved:
            return None
        path = max(self.saved, key=lambda t: t[1])[2]
        return Checkpoint(path=path)


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        run_config: Optional[RunConfig] = None,
        trial_dir: Optional[str] = None,
        trial_id: str = "train",
    ):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling = scaling_config
        self.run_config = run_config or RunConfig()
        self.trial_id = trial_id
        storage = self.run_config.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.run_config.name or f"train_{time.strftime('%Y%m%d-%H%M%S')}"
        self.trial_dir = trial_dir or os.path.join(storage, name, trial_id)
        os.makedirs(self.trial_dir, exist_ok=True)
        self.pg = None
        self.worker_group: Optional[WorkerGroup] = None
        self._ckpts = _CheckpointBook(self.trial_dir, self.run_config.checkpoint_config)
        self._runtime_env: Optional[dict] = None
        self._last_metrics = None

    # ------------------------------------------------------------------
    def start(self, runtime_env: Optional[dict] = None,
              checkpoint: Optional[Checkpoint] = None, generation: int = 0):
        from ray_tpu.util.placement_group import placement_group

        self._runtime_env = runtime_env
        bundles = self.scaling.as_placement_group_bundles()
        strategy = self.scaling.placement_strategy
        self.pg = placement_group(bundles, strategy=strategy)
        if not self.pg.wait(120):
            raise TrainingFailedError(
                f"placement group infeasible: {bundles} ({strategy})"
            )
        self.worker_group = WorkerGroup(
            self.scaling.num_workers,
            self.scaling.worker_resources(),
            placement_group=self.pg,
            runtime_env=runtime_env,
            generation=generation,
        )
        # rank wiring (ray parity: backend_executor.py:273)
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            refs.append(
                w.setup_session.remote(
                    rank, self.scaling.num_workers, 0, rank,
                    self.run_config.name or "experiment", self.trial_id,
                    self.trial_dir, checkpoint,
                )
            )
        ray_tpu.get(refs, timeout=GLOBAL_CONFIG.train_worker_start_timeout_s)
        self.backend.on_start(self.worker_group, self.backend_config)

    # ------------------------------------------------------------------
    def run(self, train_fn: Callable, config: Optional[dict] = None,
            result_callback=None) -> Result:
        assert self.worker_group is not None, "start() must be called first"
        self._last_metrics = None
        budget = self.run_config.failure_config.max_failures
        failures, restarts, recovery_hist = _ft_metrics()
        while True:
            outcome = self._run_attempt(train_fn, config, result_callback)
            status = outcome["status"]
            if status == "done":
                return self._result(error=None)
            if status == "app_error":
                return self._result(error=outcome["error"])
            # recoverable gang failure (actor_died / unresponsive /
            # wedged) or a clean preemption drain
            cause = outcome["cause"]
            detected = outcome["detected"]
            failures.labels(cause=cause).inc()
            if not GLOBAL_CONFIG.train_recovery_enabled:
                return self._result(
                    error=outcome["error"]
                    or TrainingFailedError(f"gang failure: {cause}")
                )
            if cause != "drain":
                # drain (spot preemption with a clean checkpoint handoff)
                # is free; real failures spend the budget. max_failures<0
                # means unlimited, ray semantics.
                if budget == 0:
                    return self._result(error=FailureBudgetExhaustedError(
                        f"gang failure ({cause}) with no max_failures "
                        f"budget left: {outcome['error']}"
                    ))
                if budget > 0:
                    budget -= 1
            old_gen = self.worker_group.generation if self.worker_group else 0
            try:
                self._recover(old_gen)
            except Exception as e:
                return self._result(error=TrainingFailedError(
                    f"gang recovery after {cause} failed: {e}"
                ))
            ready = time.time()
            restarts.inc()
            recovery_hist.record(ready - detected)
            steptrace.record_restart(cause, detected, ready, old_gen + 1)
            logger.warning(
                "train gang recovered from %s in %.2fs (generation %d, "
                "restored from %s)", cause, ready - detected, old_gen + 1,
                "latest checkpoint" if self._ckpts.latest() else "scratch",
            )

    def _result(self, error) -> Result:
        return Result(
            metrics=self._last_metrics,
            checkpoint=self._ckpts.latest(),
            error=error,
            path=self.trial_dir,
        )

    def _run_attempt(self, train_fn: Callable, config: Optional[dict],
                     result_callback) -> dict:
        """One gang generation's pump. Returns a terminal outcome dict:
        ``{"status": "done"}``, ``{"status": "app_error", "error"}``, or
        ``{"status": "failed", "cause", "error", "detected"}`` where
        ``detected`` is the wall-clock failure-detection instant the
        recovery histogram measures from."""
        wg = self.worker_group
        try:
            self.backend.on_training_start(wg, self.backend_config)
            ray_tpu.get(
                [w.start_training.remote(train_fn, dict(config or {}))
                 for w in wg.workers],
                timeout=GLOBAL_CONFIG.train_worker_start_timeout_s,
            )
        except Exception as e:
            # a rank that dies during gang setup is a gang failure, not a
            # user-code error: the recovery loop should re-place it
            if "died" in f"{type(e).__name__}: {e}".lower():
                return {"status": "failed", "cause": "actor_died",
                        "error": TrainingFailedError(
                            f"worker died during startup: {e}"),
                        "detected": time.time()}
            return {"status": "app_error",
                    "error": TrainingFailedError(f"worker startup failed: {e}")}
        n = len(wg.workers)
        done = [False] * n
        interval = max(0.1, GLOBAL_CONFIG.train_health_check_interval_s)
        watchdog = ProgressWatchdog(n, GLOBAL_CONFIG.train_progress_timeout_s)
        while not all(done):
            # Short-interval polls double as liveness probes: a dead rank
            # fails the in-flight call promptly (ActorDiedError), and an
            # empty poll returns within ``interval`` carrying the rank's
            # session health for the progress watchdog.
            polls = [
                (i, wg.workers[i].next_result.remote(interval))
                for i in range(n) if not done[i]
            ]
            try:
                results = ray_tpu.get([r for _, r in polls],
                                      timeout=interval + 60.0)
            except Exception as e:
                cause = ("actor_died"
                         if "died" in f"{type(e).__name__}: {e}".lower()
                         else "unresponsive")
                return {"status": "failed", "cause": cause,
                        "error": TrainingFailedError(f"train worker died: {e}"),
                        "detected": time.time()}
            for (i, _), res in zip(polls, results):
                kind = res.get("type")
                if kind == "done":
                    done[i] = True
                    watchdog.disarm(i)
                elif kind == "error":
                    return {"status": "app_error",
                            "error": TrainingFailedError(
                                f"worker {i} failed: {res['error']}\n"
                                f"{res.get('traceback', '')}")}
                elif kind == "report":
                    watchdog.touch(i)
                    self._handle_report(i, res, result_callback)
                    if res.get("drain"):
                        # the rank checkpointed at this step boundary and
                        # is exiting for preemption: requeue the gang
                        return {"status": "failed", "cause": "drain",
                                "error": None, "detected": time.time()}
                elif kind == "timeout":
                    h = res.get("health") or {}
                    if h.get("active"):
                        watchdog.observe(i, int(h.get("step", 0)))
            wedged = watchdog.wedged()
            if wedged:
                return {"status": "failed", "cause": "wedged",
                        "error": TrainingFailedError(
                            f"rank(s) {wedged} made no step progress for "
                            f"{watchdog.timeout_s}s (progress watchdog)"),
                        "detected": time.time()}
        return {"status": "done"}

    def _handle_report(self, rank: int, res: dict, result_callback):
        """Rank-0 reports are canonical for metrics/checkpoints (ray
        semantics); a drain report from ANY rank persists its checkpoint —
        that checkpoint is exactly what recovery restores from."""
        if rank != 0 and not res.get("drain"):
            return
        metrics = res["metrics"]
        if rank == 0:
            self._last_metrics = metrics
        ck_data = res.get("checkpoint_data")
        ck_path = res.get("checkpoint_path")
        if ck_data is not None or ck_path is not None:
            self._ckpts.persist(ck_data, ck_path, metrics)
        if rank == 0 and result_callback:
            result_callback(metrics, self._ckpts.latest())

    # ------------------------------------------------------------------
    def _recover(self, old_generation: int):
        """Teardown + re-place + restore: the recovery half of the loop.

        Order matters: plant the collective abort marker FIRST so
        surviving ranks blocked in a rendezvous fail over with
        ``CollectiveWorldChangedError`` within a poll interval instead of
        sitting out collective_timeout_s while we tear down around them.
        """
        from ray_tpu.util import collective as col

        try:
            col.abort_group(TRAIN_GROUP_NAME, epoch=old_generation)
        except Exception:
            pass
        self._teardown_gang()
        # the dead generation's rendezvous keys (and its abort marker —
        # every survivor that could see it is gone now) serve no one
        try:
            col.destroy_collective_group(TRAIN_GROUP_NAME)
        except Exception:
            pass
        self.start(
            runtime_env=self._runtime_env,
            checkpoint=self._ckpts.latest(),
            generation=old_generation + 1,
        )

    def _drain_steptrace(self):
        """Drain the gang's step-telemetry rings into the GCS aggregator
        while the workers still exist: the merged train timeline
        (`ray_tpu train timeline`, util.state.train_timeline) must
        outlive the run — and on the recovery path, outlive the dead
        generation, so its wedged rank shows as missing instead of
        vanishing. Best-effort — an unreachable GCS or a disabled
        steptrace plane costs nothing here."""
        if self.worker_group and self.worker_group.workers:
            try:
                from ray_tpu.util import state

                if steptrace.is_enabled():
                    # limit=1: the fold (ring drain) is the point — skip
                    # building + shipping the full merged timeline here
                    state.steptrace_summary(limit=1)
            except Exception:
                pass

    def _teardown_gang(self):
        """Shared by shutdown() and the recovery path: steptrace drain,
        then kill the workers and release the placement."""
        self._drain_steptrace()
        if self.worker_group:
            self.worker_group.shutdown()
            self.worker_group = None
        if self.pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None

    # ------------------------------------------------------------------
    def shutdown(self):
        try:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
        except Exception:
            pass
        self._teardown_gang()
