"""BackendExecutor: worker-gang lifecycle + training loop pump.

ray parity: python/ray/train/_internal/backend_executor.py:46 — create the
placement group (:165), start the WorkerGroup, wire ranks (:273), run the
backend's process-group setup, pump reports/checkpoints (:343-466), restart
on failure (:647). TPU delta: one worker per host (not per chip), STRICT_PACK
maps the gang onto one slice when requested.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    pass


class _CheckpointBook:
    """Keep top-K checkpoints (ray parity: air/_internal/checkpoint_manager.py:251)."""

    def __init__(self, trial_dir: str, config: CheckpointConfig):
        self.trial_dir = trial_dir
        self.config = config
        self.saved: List[tuple] = []  # (score, index, path)
        self.index = 0

    def persist(self, data: Optional[dict], src_path: Optional[str],
                metrics: dict) -> Checkpoint:
        path = os.path.join(self.trial_dir, f"checkpoint_{self.index:06d}")
        self.index += 1
        ckpt = Checkpoint(_data=data) if data is not None else Checkpoint(path=src_path)
        ckpt.to_directory(path)
        final = Checkpoint(path=path)
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr and attr in metrics:
            score = metrics[attr]
        self.saved.append((score, self.index - 1, path))
        self._evict()
        return final

    def _evict(self):
        keep = self.config.num_to_keep
        if keep is None or len(self.saved) <= keep:
            return
        attr = self.config.checkpoint_score_attribute
        if attr:
            reverse = self.config.checkpoint_score_order == "max"
            ranked = sorted(
                self.saved,
                key=lambda t: (t[0] is not None, t[0] if t[0] is not None else 0),
                reverse=reverse,
            )
        else:
            ranked = sorted(self.saved, key=lambda t: -t[1])  # newest first
        for score, idx, path in ranked[keep:]:
            import shutil

            shutil.rmtree(path, ignore_errors=True)
            self.saved.remove((score, idx, path))

    def latest(self) -> Optional[Checkpoint]:
        if not self.saved:
            return None
        path = max(self.saved, key=lambda t: t[1])[2]
        return Checkpoint(path=path)


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        run_config: Optional[RunConfig] = None,
        trial_dir: Optional[str] = None,
        trial_id: str = "train",
    ):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling = scaling_config
        self.run_config = run_config or RunConfig()
        self.trial_id = trial_id
        storage = self.run_config.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.run_config.name or f"train_{time.strftime('%Y%m%d-%H%M%S')}"
        self.trial_dir = trial_dir or os.path.join(storage, name, trial_id)
        os.makedirs(self.trial_dir, exist_ok=True)
        self.pg = None
        self.worker_group: Optional[WorkerGroup] = None
        self._ckpts = _CheckpointBook(self.trial_dir, self.run_config.checkpoint_config)

    # ------------------------------------------------------------------
    def start(self, runtime_env: Optional[dict] = None,
              checkpoint: Optional[Checkpoint] = None):
        from ray_tpu.util.placement_group import placement_group

        bundles = self.scaling.as_placement_group_bundles()
        strategy = self.scaling.placement_strategy
        self.pg = placement_group(bundles, strategy=strategy)
        if not self.pg.wait(120):
            raise TrainingFailedError(
                f"placement group infeasible: {bundles} ({strategy})"
            )
        self.worker_group = WorkerGroup(
            self.scaling.num_workers,
            self.scaling.worker_resources(),
            placement_group=self.pg,
            runtime_env=runtime_env,
        )
        # rank wiring (ray parity: backend_executor.py:273)
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            refs.append(
                w.setup_session.remote(
                    rank, self.scaling.num_workers, 0, rank,
                    self.run_config.name or "experiment", self.trial_id,
                    self.trial_dir, checkpoint,
                )
            )
        ray_tpu.get(refs, timeout=GLOBAL_CONFIG.train_worker_start_timeout_s)
        self.backend.on_start(self.worker_group, self.backend_config)

    # ------------------------------------------------------------------
    def run(self, train_fn: Callable, config: Optional[dict] = None,
            result_callback=None) -> Result:
        wg = self.worker_group
        assert wg is not None, "start() must be called first"
        self.backend.on_training_start(wg, self.backend_config)
        try:
            ray_tpu.get(
                [w.start_training.remote(train_fn, config or {}) for w in wg.workers],
                timeout=GLOBAL_CONFIG.train_worker_start_timeout_s,
            )
        except Exception as e:
            return Result(
                metrics=None, checkpoint=self._ckpts.latest(),
                error=TrainingFailedError(f"worker startup failed: {e}"),
                path=self.trial_dir,
            )
        last_metrics = None
        final_error = None
        done = [False] * len(wg.workers)
        while not all(done):
            polls = [
                (i, w.next_result.remote()) for i, w in enumerate(wg.workers)
                if not done[i]
            ]
            try:
                results = ray_tpu.get(
                    [r for _, r in polls],
                    timeout=GLOBAL_CONFIG.train_result_poll_timeout_s,
                )
            except Exception as e:
                # A worker actor died mid-training (process exit / node loss).
                final_error = TrainingFailedError(f"train worker died: {e}")
                break
            reports = []
            for (i, _), res in zip(polls, results):
                kind = res.get("type")
                if kind == "done":
                    done[i] = True
                elif kind == "error":
                    final_error = TrainingFailedError(
                        f"worker {i} failed: {res['error']}\n{res.get('traceback','')}"
                    )
                    done = [True] * len(done)
                    break
                elif kind == "report":
                    reports.append((i, res))
            if final_error:
                break
            if reports:
                # rank-0's metrics are canonical (ray semantics)
                rank0 = next((r for i, r in reports if i == 0), reports[0][1])
                last_metrics = rank0["metrics"]
                ck_data = rank0.get("checkpoint_data")
                ck_path = rank0.get("checkpoint_path")
                if ck_data is not None or ck_path is not None:
                    self._ckpts.persist(ck_data, ck_path, last_metrics)
                if result_callback:
                    result_callback(last_metrics, self._ckpts.latest())
        return Result(
            metrics=last_metrics,
            checkpoint=self._ckpts.latest(),
            error=final_error,
            path=self.trial_dir,
        )

    # ------------------------------------------------------------------
    def shutdown(self):
        try:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
        except Exception:
            pass
        # Drain the gang's step-telemetry rings into the GCS aggregator
        # BEFORE killing the workers: the merged train timeline
        # (`ray_tpu train timeline`, util.state.train_timeline) must
        # outlive the run. Best-effort — an unreachable GCS or a
        # disabled steptrace plane costs nothing here.
        if self.worker_group and self.worker_group.workers:
            try:
                from ray_tpu._private import steptrace
                from ray_tpu.util import state

                if steptrace.is_enabled():
                    # limit=1: the fold (ring drain) is the point — skip
                    # building + shipping the full merged timeline here
                    state.steptrace_summary(limit=1)
            except Exception:
                pass
        if self.worker_group:
            self.worker_group.shutdown()
        if self.pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
