"""Backend plugin interface + JAX and Torch backends.

ray parity: python/ray/train/backend.py:41,53 (Backend/BackendConfig) and the
framework configs (torch/config.py:29 TorchConfig + :69
_setup_torch_process_group, tensorflow/config.py TF_CONFIG). The TPU-native
backend is JaxConfig: instead of a NCCL process group, workers form a JAX
distributed system — one worker process per host owning all local chips,
``jax.distributed.initialize`` keyed by the worker group, collectives riding
ICI inside jitted steps.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group, backend_config):
        pass

    def on_training_start(self, worker_group, backend_config):
        pass

    def on_shutdown(self, worker_group, backend_config):
        pass


# ---------------------------------------------------------------------------
# JAX backend (the TPU path)
# ---------------------------------------------------------------------------


@dataclass
class JaxConfig(BackendConfig):
    """Per-worker JAX setup.

    distributed: "auto" initializes jax.distributed only for multi-worker
    TPU gangs (multi-host pods); "off" leaves workers as independent JAX
    processes whose host-level sync goes through ray_tpu.util.collective;
    "force" always initializes.

    overlap_grads arms ``session.GradSync`` overlap on every worker:
    gradient allreduces dispatch on a background thread so their chunked
    collective spans interleave with the step's compute phase spans.
    collective_quant ("int8") makes the train_dp group's SUM/MEAN
    allreduces ride the block-quantized wire format.

    ingraph_psum ("chunked" | "quantized") sets the IN-GRAPH gradient
    collective mode on every worker (the train_ingraph_psum flag):
    ``models.gpt2.build_train_step`` then reduces gradients with the
    explicit chunked/int8 psum twins from parallel/collectives.py
    instead of the partitioner-inserted fused psum. "" keeps the
    default (byte-identical) path.
    """

    distributed: str = "auto"
    use_tpu: bool = False
    env_vars: Dict[str, str] = field(default_factory=dict)
    overlap_grads: bool = False
    collective_quant: str = ""
    ingraph_psum: str = ""
    ingraph_psum_chunks: int = 4

    @property
    def backend_cls(self):
        return _JaxBackend


def _jax_worker_setup(coordinator: Optional[str], num_processes: int,
                      process_id: int, env_vars: Dict[str, str]):
    for k, v in env_vars.items():
        os.environ[k] = str(v)
    if coordinator is not None:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return True


def _enable_overlap():
    from ray_tpu.train import session

    session.set_overlap_grads(True)
    return True


def _set_ingraph_psum(mode: str, chunks: int):
    """Sticky process default: every build_train_step on this worker
    picks the mode up from the flag table (same posture as
    set_overlap_grads — per-run config, not per-call plumbing)."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    GLOBAL_CONFIG.update({"train_ingraph_psum": mode,
                          "train_ingraph_psum_chunks": int(chunks)})
    return True


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_host() -> str:
    return socket.gethostbyname(socket.gethostname())


class _JaxBackend(Backend):
    def on_start(self, worker_group, config: JaxConfig):
        n = worker_group.num_workers
        coordinator = None
        if config.distributed == "force" or (
            config.distributed == "auto" and config.use_tpu and n > 1
        ):
            host = worker_group.execute_single(0, _get_host)
            coordinator = f"{host}:{_free_port()}"
        import ray_tpu

        refs = []
        for i, w in enumerate(worker_group.workers):
            refs.append(
                w.execute.remote(
                    _jax_worker_setup, coordinator, n, i, dict(config.env_vars)
                )
            )
        ray_tpu.get(refs, timeout=300)
        if config.overlap_grads:
            ray_tpu.get(
                [w.execute.remote(_enable_overlap) for w in worker_group.workers],
                timeout=300,
            )
        if config.ingraph_psum:
            ray_tpu.get(
                [w.execute.remote(_set_ingraph_psum, config.ingraph_psum,
                                  config.ingraph_psum_chunks)
                 for w in worker_group.workers],
                timeout=300,
            )
        # Host-level collective group for out-of-graph sync (weight
        # broadcast, metric reduction) — the Gloo-analog path.
        if n > 1:
            from ray_tpu.util import collective as col

            # epoch = gang generation: a recovery re-placement must not
            # rendezvous against the dead generation's KV state
            col.create_collective_group(
                worker_group.workers, n, list(range(n)),
                backend="store", group_name="train_dp",
                epoch=getattr(worker_group, "generation", 0),
                quant=config.collective_quant,
            )


# ---------------------------------------------------------------------------
# Torch backend (CPU gloo — API parity for reference workloads)
# ---------------------------------------------------------------------------


@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_method: str = "tcp"
    timeout_s: int = 1800

    @property
    def backend_cls(self):
        return _TorchBackend


def _torch_worker_setup(master_addr: str, master_port: int, rank: int,
                        world_size: int, backend: str, timeout_s: int):
    """ray parity: train/torch/config.py:69 _setup_torch_process_group."""
    import datetime

    import torch.distributed as dist

    if dist.is_initialized():
        return True
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    dist.init_process_group(
        backend=backend,
        init_method=f"tcp://{master_addr}:{master_port}",
        rank=rank,
        world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s),
    )
    return True


class _TorchBackend(Backend):
    def on_start(self, worker_group, config: TorchConfig):
        import ray_tpu

        master_addr = "127.0.0.1"
        master_port = _free_port()
        refs = []
        for i, w in enumerate(worker_group.workers):
            refs.append(
                w.execute.remote(
                    _torch_worker_setup, master_addr, master_port, i,
                    worker_group.num_workers, config.backend, config.timeout_s,
                )
            )
        ray_tpu.get(refs, timeout=300)

    def on_shutdown(self, worker_group, config):
        def _destroy():
            import torch.distributed as dist

            if dist.is_initialized():
                dist.destroy_process_group()
            return True

        try:
            worker_group.execute(_destroy)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# TensorFlow backend (TF_CONFIG — API parity for reference workloads)
# ---------------------------------------------------------------------------


@dataclass
class TensorflowConfig(BackendConfig):
    """ray parity: train/tensorflow/config.py — wires the TF_CONFIG env var
    (cluster spec + task index) on every worker so
    tf.distribute.MultiWorkerMirroredStrategy discovers the gang."""

    @property
    def backend_cls(self):
        return _TensorflowBackend


def _tf_grab_port() -> str:
    return f"{_get_host()}:{_free_port()}"


def _tf_worker_setup(tf_config: Dict):
    import json

    os.environ["TF_CONFIG"] = json.dumps(tf_config)
    return True


class _TensorflowBackend(Backend):
    def on_start(self, worker_group, config: TensorflowConfig):
        import ray_tpu

        # one fan-out round trip, not N serialized ones
        addrs = ray_tpu.get(
            [w.execute.remote(_tf_grab_port) for w in worker_group.workers],
            timeout=300,
        )
        refs = []
        for i, w in enumerate(worker_group.workers):
            refs.append(w.execute.remote(_tf_worker_setup, {
                "cluster": {"worker": addrs},
                "task": {"type": "worker", "index": i},
            }))
        ray_tpu.get(refs, timeout=300)

    def on_shutdown(self, worker_group, config):
        def _clear():
            os.environ.pop("TF_CONFIG", None)
            return True

        try:
            worker_group.execute(_clear)
        except Exception:
            pass
