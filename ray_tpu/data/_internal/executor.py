"""Streaming executor: runs a logical plan as pipelined stages of remote
tasks over the cluster.

Each stage is a pull-based generator of ``(block_ref, BlockMetadata)``:
map stages keep a bounded window of in-flight tasks per stage (backpressure)
and yield results as tasks finish, so downstream stages start before
upstream ones drain — the behavior of the reference's StreamingExecutor
(ray python/ray/data/_internal/execution/streaming_executor.py:49,
streaming_executor_state.py) without its standalone control thread: the
consumer's own pull drives scheduling.

All-to-all stages (shuffle/sort/repartition/groupby) are barriers, as in the
reference's exchange ops (_internal/planner/exchange/).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata, concat_blocks
from ray_tpu.data._internal import logical as L

logger = logging.getLogger(__name__)

RefBundle = Tuple[Any, BlockMetadata]  # (ObjectRef[Block], meta)


class ExecutionOptions:
    def __init__(self, max_in_flight: int = 8, preserve_order: bool = True,
                 resources: Optional[dict] = None):
        self.max_in_flight = max_in_flight
        self.preserve_order = preserve_order
        self.resources = resources or {}


# ----------------------------------------------------------------------
# remote task bodies (stateless; shipped per call)
# ----------------------------------------------------------------------

def _run_read_task(read_task) -> tuple:
    block = read_task()
    if not isinstance(block, Block):
        from ray_tpu.data.block import _to_table

        block = _to_table(block)
    return block, BlockMetadata.for_block(block)


def _run_block_fn(fn, block: Block) -> tuple:
    out = fn(block)
    return out, BlockMetadata.for_block(out)


class _MapWorker:
    """Actor-pool worker hosting a stateful transform (ray parity:
    ActorPoolMapOperator's _MapWorker)."""

    def __init__(self, fn_factory):
        self._fn = fn_factory()

    def apply(self, block: Block) -> tuple:
        out = self._fn(block)
        return out, BlockMetadata.for_block(out)


# ----------------------------------------------------------------------
# stage iterators
# ----------------------------------------------------------------------

def _windowed(task_iter: Iterator[Callable[[], List[Any]]],
              window: int, preserve_order: bool) -> Iterator[RefBundle]:
    """Submit thunks from ``task_iter`` keeping <= window in flight; yield
    (block_ref, meta) as tasks complete."""
    import ray_tpu

    in_flight: List[Tuple[Any, Any]] = []  # (meta_ref, block_ref)
    exhausted = False
    while in_flight or not exhausted:
        while not exhausted and len(in_flight) < window:
            try:
                thunk = next(task_iter)
            except StopIteration:
                exhausted = True
                break
            block_ref, meta_ref = thunk()
            in_flight.append((meta_ref, block_ref))
        if not in_flight:
            break
        if preserve_order:
            meta_ref, block_ref = in_flight.pop(0)
            meta = ray_tpu.get(meta_ref)
        else:
            ready, _ = ray_tpu.wait(
                [m for m, _ in in_flight], num_returns=1, timeout=None
            )
            idx = next(i for i, (m, _) in enumerate(in_flight) if m in ready)
            meta_ref, block_ref = in_flight.pop(idx)
            meta = ray_tpu.get(meta_ref)
        yield block_ref, meta


def _read_stage(op: L.Read, opts: ExecutionOptions) -> Iterator[RefBundle]:
    import ray_tpu

    read_remote = ray_tpu.remote(num_returns=2)(_run_read_task)

    def thunks():
        for rt in op.read_tasks:
            yield lambda rt=rt: read_remote.remote(rt)

    return _windowed(thunks(), opts.max_in_flight, opts.preserve_order)


def _map_stage(op: L.MapBlocks, upstream: Iterator[RefBundle],
               opts: ExecutionOptions) -> Iterator[RefBundle]:
    import ray_tpu

    if op.compute is None:
        res = dict(op.resources)
        num_cpus = res.pop("CPU", 1.0)
        map_remote = ray_tpu.remote(
            num_returns=2, num_cpus=num_cpus, **({"resources": res} if res else {})
        )(_run_block_fn)
        fn = op.block_fn

        def thunks():
            for block_ref, _meta in upstream:
                yield lambda b=block_ref: map_remote.remote(fn, b)

        return _windowed(thunks(), opts.max_in_flight, opts.preserve_order)

    # actor pool
    _, pool_size = op.compute
    res = dict(op.resources)
    num_cpus = res.pop("CPU", 1.0)
    worker_cls = ray_tpu.remote(
        num_cpus=num_cpus, **({"resources": res} if res else {})
    )(_MapWorker)
    actors = [worker_cls.remote(op.block_fn) for _ in range(pool_size)]
    rr = [0]

    def thunks():
        try:
            for block_ref, _meta in upstream:
                def call(b=block_ref):
                    a = actors[rr[0] % len(actors)]
                    rr[0] += 1
                    ref = a.apply.options(num_returns=2).remote(b)
                    return ref
                yield call
        finally:
            pass

    def run():
        try:
            yield from _windowed(
                thunks(), max(opts.max_in_flight, pool_size), opts.preserve_order
            )
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    return run()


def _limit_stage(op: L.Limit, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
    import ray_tpu

    remaining = op.limit
    for block_ref, meta in upstream:
        if remaining <= 0:
            break
        if meta.num_rows <= remaining:
            remaining -= meta.num_rows
            yield block_ref, meta
        else:
            sliced = BlockAccessor(ray_tpu.get(block_ref)).slice(0, remaining)
            remaining = 0
            yield ray_tpu.put(sliced), BlockMetadata.for_block(sliced)
            break


def _zip_stage(left: List[RefBundle], right: List[RefBundle]) -> Iterator[RefBundle]:
    """Align two materialized sides row-for-row and concat columns."""
    import ray_tpu

    def _zip_slices(l_blocks, r_blocks, n_rows):
        import pyarrow as pa

        import ray_tpu

        # refs arrive nested inside lists: resolve them in-task
        lt = concat_blocks(ray_tpu.get(list(l_blocks)))
        rt = concat_blocks(ray_tpu.get(list(r_blocks)))
        lt, rt = lt.slice(0, n_rows), rt.slice(0, n_rows)
        cols = {c: lt.column(c) for c in lt.column_names}
        for c in rt.column_names:
            name = c if c not in cols else f"{c}_1"
            cols[name] = rt.column(c)
        out = pa.table(cols)
        return out, BlockMetadata.for_block(out)

    zip_remote = ray_tpu.remote(num_returns=2)(_zip_slices)
    n = min(sum(m.num_rows for _, m in left), sum(m.num_rows for _, m in right))
    # v1: one task zips everything; fine for moderate datasets, and the
    # all-to-all barrier semantics match the reference.
    block_ref, meta_ref = zip_remote.remote(
        [r for r, _ in left], [r for r, _ in right], n
    )
    yield block_ref, ray_tpu.get(meta_ref)


# ----------------------------------------------------------------------
# all-to-all helpers (used by Dataset to build AllToAll ops)
# ----------------------------------------------------------------------

def shuffle_exchange(bundles: List[RefBundle], n_out: int,
                     partition_fn: Callable[[Block, int], List[Block]],
                     reduce_fn: Optional[Callable[[List[Block]], Block]] = None,
                     ) -> List[RefBundle]:
    """Generic 2-stage map/reduce exchange (ray parity: exchange/
    shuffle_task_scheduler). partition_fn splits one block into n_out parts;
    reduce_fn (default concat) merges part i of every map output."""
    import ray_tpu

    if not bundles:
        return []

    def _map(block, n):
        parts = partition_fn(block, n)
        assert len(parts) == n, (len(parts), n)
        return tuple(parts) if n > 1 else parts[0]

    def _reduce(*parts):
        block = (reduce_fn or concat_blocks)(list(parts))
        return block, BlockMetadata.for_block(block)

    map_remote = ray_tpu.remote(num_returns=n_out)(_map)
    red_remote = ray_tpu.remote(num_returns=2)(_reduce)

    map_out = [map_remote.remote(ref, n_out) for ref, _ in bundles]
    if n_out == 1:
        cols = [[r] for r in map_out]
    else:
        cols = [[row[i] for row in map_out] for i in range(n_out)]
    out: List[RefBundle] = []
    pending = []
    for col in cols:
        block_ref, meta_ref = red_remote.remote(*col)
        pending.append((block_ref, meta_ref))
    for block_ref, meta_ref in pending:
        out.append((block_ref, ray_tpu.get(meta_ref)))
    return out


# ----------------------------------------------------------------------
# plan execution
# ----------------------------------------------------------------------

def execute_streaming(plan: L.LogicalPlan,
                      opts: Optional[ExecutionOptions] = None
                      ) -> Iterator[RefBundle]:
    """Yield output (block_ref, meta) pairs of the optimized plan."""
    opts = opts or ExecutionOptions()
    return _exec_op(plan.optimized().dag, opts)


def execute(plan: L.LogicalPlan,
            opts: Optional[ExecutionOptions] = None) -> List[RefBundle]:
    return list(execute_streaming(plan, opts))


def _exec_op(op: L.LogicalOp, opts: ExecutionOptions) -> Iterator[RefBundle]:
    if isinstance(op, L.InputData):
        return iter(list(zip(op.refs, op.metas)))
    if isinstance(op, L.Read):
        return _read_stage(op, opts)
    if isinstance(op, L.MapBlocks):
        return _map_stage(op, _exec_op(op.inputs[0], opts), opts)
    if isinstance(op, L.Limit):
        return _limit_stage(op, _exec_op(op.inputs[0], opts))
    if isinstance(op, L.AllToAll):
        bundles = list(_exec_op(op.inputs[0], opts))
        return iter(op.fn(bundles))
    if isinstance(op, L.Union):
        def chain():
            for child in op.inputs:
                yield from _exec_op(child, opts)
        return chain()
    if isinstance(op, L.Zip):
        left = list(_exec_op(op.inputs[0], opts))
        right = list(_exec_op(op.inputs[1], opts))
        return _zip_stage(left, right)
    raise TypeError(f"unknown logical op {op!r}")
