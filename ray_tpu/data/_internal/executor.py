"""Streaming executor: runs a logical plan as pipelined stages of remote
tasks over the cluster.

Each stage is a pull-based generator of ``(block_ref, BlockMetadata)``:
map stages keep a bounded window of in-flight tasks per stage (backpressure)
and yield results as tasks finish, so downstream stages start before
upstream ones drain — the behavior of the reference's StreamingExecutor
(ray python/ray/data/_internal/execution/streaming_executor.py:49,
streaming_executor_state.py) without its standalone control thread: the
consumer's own pull drives scheduling.

All-to-all stages (shuffle/sort/repartition/groupby) are barriers, as in the
reference's exchange ops (_internal/planner/exchange/).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata, concat_blocks
from ray_tpu.data._internal import logical as L
from ray_tpu.data._internal.stats import ExecStats, OpStats

logger = logging.getLogger(__name__)

RefBundle = Tuple[Any, BlockMetadata]  # (ObjectRef[Block], meta)


class ExecutionOptions:
    def __init__(self, max_in_flight: Optional[int] = None,
                 preserve_order: Optional[bool] = None,
                 resources: Optional[dict] = None,
                 op_memory_budget: Optional[int] = None):
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        self.max_in_flight = max_in_flight if max_in_flight is not None \
            else ctx.max_in_flight_tasks
        self.preserve_order = preserve_order if preserve_order is not None \
            else ctx.preserve_order
        self.resources = resources or {}
        self.op_memory_budget = op_memory_budget if op_memory_budget \
            is not None else ctx.op_memory_budget
        self.block_size_seed = ctx.target_max_block_size


# ----------------------------------------------------------------------
# remote task bodies (stateless; shipped per call)
# ----------------------------------------------------------------------

def _run_read_task(read_task) -> tuple:
    block = read_task()
    if not isinstance(block, Block):
        from ray_tpu.data.block import _to_table

        block = _to_table(block)
    return block, BlockMetadata.for_block(block)


def _run_block_fn(fn, block: Block) -> tuple:
    out = fn(block)
    return out, BlockMetadata.for_block(out)


class _MapWorker:
    """Actor-pool worker hosting a stateful transform (ray parity:
    ActorPoolMapOperator's _MapWorker)."""

    def __init__(self, fn_factory):
        self._fn = fn_factory()

    def apply(self, block: Block) -> tuple:
        out = self._fn(block)
        return out, BlockMetadata.for_block(out)


# ----------------------------------------------------------------------
# stage iterators
# ----------------------------------------------------------------------

def _windowed(task_iter: Iterator[Callable[[], List[Any]]],
              opts: ExecutionOptions,
              stats: Optional[OpStats] = None,
              window: Optional[int] = None) -> Iterator[RefBundle]:
    """Submit thunks from ``task_iter`` under DOUBLE backpressure: at most
    ``window`` tasks in flight AND an estimated in-flight output-byte
    budget (ray parity: streaming_executor_state.py:100,376 — per-operator
    memory budgets, not just task counts). Output size is estimated from
    the running mean of this operator's completed blocks (seeded with
    target_max_block_size); at least one task is always admitted so a
    single huge block still flows."""
    import time as _time

    import ray_tpu

    window = window or opts.max_in_flight
    budget = opts.op_memory_budget
    avg_bytes = float(opts.block_size_seed)
    done_count = 0
    bp_started: Optional[float] = None
    in_flight: List[Tuple[Any, Any]] = []  # (meta_ref, block_ref)
    exhausted = False
    while in_flight or not exhausted:
        while not exhausted and len(in_flight) < window:
            if in_flight and avg_bytes * (len(in_flight) + 1) > budget:
                # over the memory budget: drain one completion first
                if bp_started is None:
                    bp_started = _time.perf_counter()
                break
            try:
                thunk = next(task_iter)
            except StopIteration:
                exhausted = True
                break
            block_ref, meta_ref = thunk()
            in_flight.append((meta_ref, block_ref))
            if stats is not None:
                stats.peak_inflight_tasks = max(
                    stats.peak_inflight_tasks, len(in_flight)
                )
        if not in_flight:
            break
        if opts.preserve_order:
            meta_ref, block_ref = in_flight.pop(0)
            meta = ray_tpu.get(meta_ref)
        else:
            ready, _ = ray_tpu.wait(
                [m for m, _ in in_flight], num_returns=1, timeout=None
            )
            idx = next(i for i, (m, _) in enumerate(in_flight) if m in ready)
            meta_ref, block_ref = in_flight.pop(idx)
            meta = ray_tpu.get(meta_ref)
        if bp_started is not None:
            if stats is not None:
                stats.backpressure_s += _time.perf_counter() - bp_started
            bp_started = None
        if stats is not None:
            stats.record_output(meta)
        # refine the per-task output estimate with the observed mean
        done_count += 1
        size = meta.size_bytes or 0
        avg_bytes += (size - avg_bytes) / done_count
        yield block_ref, meta


def _read_stage(op: L.Read, opts: ExecutionOptions,
                stats: Optional[OpStats] = None) -> Iterator[RefBundle]:
    import ray_tpu

    read_remote = ray_tpu.remote(num_returns=2)(_run_read_task)

    def thunks():
        for rt in op.read_tasks:
            yield lambda rt=rt: read_remote.remote(rt)

    return _windowed(thunks(), opts, stats=stats)


def _map_stage(op: L.MapBlocks, upstream: Iterator[RefBundle],
               opts: ExecutionOptions,
               stats: Optional[OpStats] = None) -> Iterator[RefBundle]:
    import ray_tpu

    if op.compute is None:
        res = dict(op.resources)
        num_cpus = res.pop("CPU", 1.0)
        map_remote = ray_tpu.remote(
            num_returns=2, num_cpus=num_cpus, **({"resources": res} if res else {})
        )(_run_block_fn)
        fn = op.block_fn

        def thunks():
            for block_ref, _meta in upstream:
                yield lambda b=block_ref: map_remote.remote(fn, b)

        return _windowed(thunks(), opts, stats=stats)

    # actor pool
    _, pool_size = op.compute
    res = dict(op.resources)
    num_cpus = res.pop("CPU", 1.0)
    worker_cls = ray_tpu.remote(
        num_cpus=num_cpus, **({"resources": res} if res else {})
    )(_MapWorker)
    actors = [worker_cls.remote(op.block_fn) for _ in range(pool_size)]
    rr = [0]

    def thunks():
        try:
            for block_ref, _meta in upstream:
                def call(b=block_ref):
                    a = actors[rr[0] % len(actors)]
                    rr[0] += 1
                    ref = a.apply.options(num_returns=2).remote(b)
                    return ref
                yield call
        finally:
            pass

    def run():
        try:
            yield from _windowed(
                thunks(), opts, stats=stats,
                window=max(opts.max_in_flight, pool_size),
            )
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    return run()


def _limit_stage(op: L.Limit, upstream: Iterator[RefBundle]) -> Iterator[RefBundle]:
    import ray_tpu

    remaining = op.limit
    for block_ref, meta in upstream:
        if remaining <= 0:
            break
        if meta.num_rows <= remaining:
            remaining -= meta.num_rows
            yield block_ref, meta
        else:
            sliced = BlockAccessor(ray_tpu.get(block_ref)).slice(0, remaining)
            remaining = 0
            yield ray_tpu.put(sliced), BlockMetadata.for_block(sliced)
            break


def _zip_stage(left: List[RefBundle], right: List[RefBundle]) -> Iterator[RefBundle]:
    """Align two materialized sides row-for-row and concat columns."""
    import ray_tpu

    def _zip_slices(l_blocks, r_blocks, n_rows):
        import pyarrow as pa

        import ray_tpu

        # refs arrive nested inside lists: resolve them in-task
        lt = concat_blocks(ray_tpu.get(list(l_blocks)))
        rt = concat_blocks(ray_tpu.get(list(r_blocks)))
        lt, rt = lt.slice(0, n_rows), rt.slice(0, n_rows)
        cols = {c: lt.column(c) for c in lt.column_names}
        for c in rt.column_names:
            name = c if c not in cols else f"{c}_1"
            cols[name] = rt.column(c)
        out = pa.table(cols)
        return out, BlockMetadata.for_block(out)

    zip_remote = ray_tpu.remote(num_returns=2)(_zip_slices)
    n = min(sum(m.num_rows for _, m in left), sum(m.num_rows for _, m in right))
    # v1: one task zips everything; fine for moderate datasets, and the
    # all-to-all barrier semantics match the reference.
    block_ref, meta_ref = zip_remote.remote(
        [r for r, _ in left], [r for r, _ in right], n
    )
    yield block_ref, ray_tpu.get(meta_ref)


# ----------------------------------------------------------------------
# all-to-all helpers (used by Dataset to build AllToAll ops)
# ----------------------------------------------------------------------

def shuffle_exchange(bundles: List[RefBundle], n_out: int,
                     partition_fn: Callable[[Block, int], List[Block]],
                     reduce_fn: Optional[Callable[[List[Block]], Block]] = None,
                     ) -> List[RefBundle]:
    """Generic 2-stage map/reduce exchange (ray parity: exchange/
    shuffle_task_scheduler). partition_fn splits one block into n_out parts;
    reduce_fn (default concat) merges part i of every map output."""
    import ray_tpu

    if not bundles:
        return []

    def _map(block, n):
        parts = partition_fn(block, n)
        assert len(parts) == n, (len(parts), n)
        return tuple(parts) if n > 1 else parts[0]

    def _reduce(*parts):
        block = (reduce_fn or concat_blocks)(list(parts))
        return block, BlockMetadata.for_block(block)

    map_remote = ray_tpu.remote(num_returns=n_out)(_map)
    red_remote = ray_tpu.remote(num_returns=2)(_reduce)

    map_out = [map_remote.remote(ref, n_out) for ref, _ in bundles]
    if n_out == 1:
        cols = [[r] for r in map_out]
    else:
        cols = [[row[i] for row in map_out] for i in range(n_out)]
    out: List[RefBundle] = []
    pending = []
    for col in cols:
        block_ref, meta_ref = red_remote.remote(*col)
        pending.append((block_ref, meta_ref))
    for block_ref, meta_ref in pending:
        out.append((block_ref, ray_tpu.get(meta_ref)))
    return out


# ----------------------------------------------------------------------
# plan execution
# ----------------------------------------------------------------------

def execute_streaming(plan: L.LogicalPlan,
                      opts: Optional[ExecutionOptions] = None,
                      stats: Optional[ExecStats] = None
                      ) -> Iterator[RefBundle]:
    """Yield output (block_ref, meta) pairs of the optimized plan; fill
    ``stats`` (one OpStats per operator) while running."""
    opts = opts or ExecutionOptions()
    out = _exec_op(plan.optimized().dag, opts, stats)

    if stats is None:
        return out

    def finalize():
        try:
            for bundle in out:
                stats.record_yield(bundle[1])
                yield bundle
        finally:
            stats.finalize()

    return finalize()


def execute(plan: L.LogicalPlan,
            opts: Optional[ExecutionOptions] = None,
            stats: Optional[ExecStats] = None) -> List[RefBundle]:
    return list(execute_streaming(plan, opts, stats))


def _stat(stats: Optional[ExecStats], name: str) -> Optional[OpStats]:
    if stats is None:
        return None
    st = stats.op(name)
    st.start()
    return st


def _exec_op(op: L.LogicalOp, opts: ExecutionOptions,
             stats: Optional[ExecStats] = None) -> Iterator[RefBundle]:
    if isinstance(op, L.InputData):
        return iter(list(zip(op.refs, op.metas)))
    if isinstance(op, L.Read):
        return _read_stage(op, opts, _stat(stats, op.name))
    if isinstance(op, L.MapBlocks):
        return _map_stage(
            op, _exec_op(op.inputs[0], opts, stats), opts,
            _stat(stats, op.name),
        )
    if isinstance(op, L.Limit):
        return _limit_stage(op, _exec_op(op.inputs[0], opts, stats))
    if isinstance(op, L.AllToAll):
        bundles = list(_exec_op(op.inputs[0], opts, stats))
        st = _stat(stats, op.name)
        out = op.fn(bundles)
        if st is not None:
            for _, meta in out:
                st.record_output(meta)
            st.finish()
        return iter(out)
    if isinstance(op, L.Union):
        def chain():
            for child in op.inputs:
                yield from _exec_op(child, opts, stats)
        return chain()
    if isinstance(op, L.Zip):
        left = list(_exec_op(op.inputs[0], opts, stats))
        right = list(_exec_op(op.inputs[1], opts, stats))
        return _zip_stage(left, right)
    raise TypeError(f"unknown logical op {op!r}")
