"""Logical plan: lazy operator DAG a Dataset accumulates, optimized (map
fusion) before physical planning.

Reference parity: ray python/ray/data/_internal/logical/interfaces/
{logical_operator,logical_plan,optimizer}.py and rules/operator_fusion.py —
collapsed to the handful of node types the executor distinguishes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional


class LogicalOp:
    """Base node. ``inputs`` are upstream ops (linear chains mostly)."""

    def __init__(self, name: str, inputs: List["LogicalOp"]):
        self.name = name
        self.inputs = inputs

    def __repr__(self):
        return f"{self.name}({', '.join(repr(i) for i in self.inputs)})"


class Read(LogicalOp):
    def __init__(self, read_tasks: List[Callable], parallelism: int):
        super().__init__("Read", [])
        self.read_tasks = read_tasks
        self.parallelism = parallelism


class InputData(LogicalOp):
    """Pre-existing block refs (from_blocks / materialized datasets)."""

    def __init__(self, refs: List[Any], metas: List[Any]):
        super().__init__("InputData", [])
        self.refs = refs
        self.metas = metas


class MapBlocks(LogicalOp):
    """One block-level transform: fn(Block) -> Block.

    ``compute`` is None (stateless tasks) or ("actors", n) for an actor pool
    running a stateful callable class. ``fn_factory`` builds the transform —
    for actor compute it constructs the user class once per actor.
    """

    def __init__(self, name: str, input_op: LogicalOp,
                 block_fn: Callable, compute: Optional[tuple] = None,
                 resources: Optional[dict] = None):
        super().__init__(name, [input_op])
        self.block_fn = block_fn
        self.compute = compute
        self.resources = resources or {}


class AllToAll(LogicalOp):
    """Barrier op: fn(refs, metas, ctx) -> (refs, metas)."""

    def __init__(self, name: str, input_op: LogicalOp, fn: Callable,
                 sub_progress: Optional[List[str]] = None):
        super().__init__(name, [input_op])
        self.fn = fn


class Limit(LogicalOp):
    def __init__(self, input_op: LogicalOp, limit: int):
        super().__init__("Limit", [input_op])
        self.limit = limit


class Union(LogicalOp):
    def __init__(self, inputs: List[LogicalOp]):
        super().__init__("Union", inputs)


class Zip(LogicalOp):
    def __init__(self, left: LogicalOp, right: LogicalOp):
        super().__init__("Zip", [left, right])


@dataclass
class LogicalPlan:
    dag: LogicalOp

    def optimized(self) -> "LogicalPlan":
        return LogicalPlan(_fuse(self.dag))


def _fuse(op: LogicalOp) -> LogicalOp:
    """Fuse chains of stateless MapBlocks into one (operator fusion rule)."""
    op.inputs = [_fuse(i) for i in op.inputs]
    if (
        isinstance(op, MapBlocks)
        and op.compute is None
        and isinstance(op.inputs[0], MapBlocks)
        and op.inputs[0].compute is None
        and op.resources == op.inputs[0].resources
    ):
        inner = op.inputs[0]
        inner_fn, outer_fn = inner.block_fn, op.block_fn

        def fused(block, _a=inner_fn, _b=outer_fn):
            return _b(_a(block))

        fused_op = MapBlocks(
            f"{inner.name}->{op.name}", inner.inputs[0], fused,
            compute=None, resources=op.resources,
        )
        return _fuse(fused_op)
    return op
