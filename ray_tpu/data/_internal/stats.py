"""Per-operator execution stats.

ray parity: python/ray/data/_internal/stats.py (DatasetStats — per-stage
wall time, task counts, output rows/bytes, and the formatted summary
``Dataset.stats()`` prints).
"""

from __future__ import annotations

import time
from typing import List, Optional


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


class OpStats:
    """One operator's counters, filled in while its stage runs."""

    def __init__(self, name: str):
        self.name = name
        self.num_tasks = 0
        self.num_rows = 0
        self.output_bytes = 0
        self.wall_time_s = 0.0
        self.backpressure_s = 0.0  # time admission blocked on the budget
        self.peak_inflight_tasks = 0
        self._started: Optional[float] = None

    def start(self):
        if self._started is None:
            self._started = time.perf_counter()

    def finish(self):
        # idempotent: an operator that closed itself (AllToAll barrier)
        # must not have its wall time stretched by ExecStats.finalize()
        if self._started is not None:
            self.wall_time_s = time.perf_counter() - self._started
            self._started = None

    def record_output(self, meta):
        self.num_tasks += 1
        self.num_rows += meta.num_rows or 0
        self.output_bytes += meta.size_bytes or 0

    def summary_row(self) -> str:
        bp = f", backpressure {self.backpressure_s:.2f}s" \
            if self.backpressure_s > 0.005 else ""
        return (
            f"  {self.name}: {self.num_tasks} tasks, "
            f"{self.num_rows} rows, {_fmt_bytes(self.output_bytes)}, "
            f"{self.wall_time_s:.2f}s wall"
            f", peak {self.peak_inflight_tasks} in-flight{bp}"
        )


class ExecStats:
    """Whole-plan stats (one OpStats per executed operator)."""

    def __init__(self):
        self.ops: List[OpStats] = []
        self._t0 = time.perf_counter()
        self.total_s: Optional[float] = None
        # actual yielded plan output, counted by the executor; ops[-1] is
        # wrong when the final stage (Limit/Zip/Union) records no OpStats
        self.out_rows: Optional[int] = None
        self.out_bytes: Optional[int] = None

    def record_yield(self, meta):
        self.out_rows = (self.out_rows or 0) + (meta.num_rows or 0)
        self.out_bytes = (self.out_bytes or 0) + (meta.size_bytes or 0)

    def op(self, name: str) -> OpStats:
        st = OpStats(name)
        self.ops.append(st)
        return st

    def finalize(self):
        if self.total_s is None:
            self.total_s = time.perf_counter() - self._t0
            for op in self.ops:
                op.finish()

    def summary(self) -> str:
        self.finalize()
        lines = ["Execution stats:"]
        lines.extend(op.summary_row() for op in self.ops)
        if self.out_rows is not None:
            rows, out_bytes = self.out_rows, self.out_bytes or 0
        else:
            rows = self.ops[-1].num_rows if self.ops else 0
            out_bytes = self.ops[-1].output_bytes if self.ops else 0
        lines.append(
            f"Total: {self.total_s:.2f}s, output {rows} rows "
            f"({_fmt_bytes(out_bytes)})"
        )
        return "\n".join(lines)
