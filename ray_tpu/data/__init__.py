"""ray_tpu.data — distributed Arrow-blocked datasets (ray parity:
python/ray/data)."""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (
    from_arrow,
    from_arrow_refs,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_mongo,
    read_sql,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.datasource_api import (
    Datasource,
    FileBasedDatasource,
    read_datasource,
)
from ray_tpu.data import preprocessors

__all__ = [
    "DataContext",
    "Block",
    "BlockAccessor",
    "BlockMetadata",
    "DataIterator",
    "Dataset",
    "from_arrow",
    "from_arrow_refs",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_mongo",
    "read_sql",
    "read_datasource",
    "Datasource",
    "FileBasedDatasource",
    "read_tfrecords",
    "read_webdataset",
    "preprocessors",
]
