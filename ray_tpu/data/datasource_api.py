"""Public custom-datasource plugin surface.

Reference parity: ray python/ray/data/datasource/datasource.py (Datasource
+ ReadTask) and file_based_datasource.py:821 (FileBasedDatasource — the
partitioned-file base every file-format reader subclasses). Users plug a
new format into the streaming executor by subclassing one of these and
calling ``ray_tpu.data.read_datasource(my_source)``.

Worked example — a length-prefixed record format::

    class RecordDatasource(FileBasedDatasource):
        _FILE_EXTENSIONS = ["rec"]

        def _read_file(self, f, path):
            rows = []
            while True:
                hdr = f.read(4)
                if not hdr:
                    break
                n = int.from_bytes(hdr, "little")
                rows.append({"payload": f.read(n), "path": path})
            return rows

    ds = ray_tpu.data.read_datasource(
        RecordDatasource("/data/shards/"), parallelism=16
    )

Each read task materializes one group of files as a block; groups are
contiguous slices of the expanded (sorted) file list chunked over
``parallelism`` (one task per file when there are fewer files). Rows
within a file may differ in schema from other files — each file becomes
its own block and the concat promotes schemas.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ray_tpu.data.block import concat_blocks, rows_to_block
from ray_tpu.data.datasource import _chunk, _expand_paths


class Datasource:
    """Base contract: produce the read tasks one dataset read executes.

    ``get_read_tasks(parallelism)`` returns a list of zero-argument
    callables; each returns a block (a pyarrow Table, or a list of row
    dicts, which is converted with ``rows_to_block``). Tasks run inside
    the streaming executor with the same scheduling/backpressure as the
    built-in readers.
    """

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        raise NotImplementedError

    def get_name(self) -> str:
        return type(self).__name__


class FileBasedDatasource(Datasource):
    """Partitioned-file base (ray: file_based_datasource.py).

    Subclasses implement ONE of:

    - ``_read_file(f, path) -> rows/Block`` — called with an open binary
      file object per file (the common case);
    - ``_read_path(path) -> rows/Block`` — called with the path when the
      reader needs library-side opening (e.g. tarfile, pyarrow).

    ``_FILE_EXTENSIONS`` (optional) filters the expanded listing.
    """

    _FILE_EXTENSIONS: Optional[List[str]] = None

    def __init__(self, paths, **open_args):
        self._paths = paths
        self._open_args = open_args

    # -- subclass surface ----------------------------------------------
    def _read_file(self, f, path: str):
        raise NotImplementedError(
            f"{type(self).__name__} must implement _read_file or _read_path"
        )

    def _read_path(self, path: str):
        with open(path, "rb", **self._open_args) as f:
            return self._read_file(f, path)

    # -- Datasource ----------------------------------------------------
    def _expand(self) -> List[str]:
        files = _expand_paths(self._paths)
        exts = self._FILE_EXTENSIONS
        if exts:
            files = [
                p for p in files
                if any(p.endswith(f".{e.lstrip('.')}") for e in exts)
            ]
        if not files:
            raise FileNotFoundError(
                f"{self.get_name()}: no matching files under {self._paths!r}"
                + (f" (extensions {exts})" if exts else "")
            )
        return files

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        source = self

        def make(group: List[str]):
            def read():
                # one block PER FILE, then schema-promoting concat:
                # pooling rows across files would key columns off the
                # first row and silently drop fields later files add
                blocks: List[Any] = []
                for path in group:
                    out = source._read_path(path)
                    if isinstance(out, list):
                        if out:
                            blocks.append(rows_to_block(out))
                    else:
                        blocks.append(out)
                return concat_blocks(blocks)

            return read

        return [make(g) for g in _chunk(self._expand(), parallelism)]


def read_datasource(datasource: Datasource, *, parallelism: int = -1,
                    **_kw):
    """Materialize a custom Datasource as a Dataset through the streaming
    executor (ray parity: read_api.read_datasource)."""
    from ray_tpu.data.dataset import Dataset
    from ray_tpu.data.read_api import _par

    p = _par(parallelism)
    return Dataset.from_read_tasks(datasource.get_read_tasks(p), p)
