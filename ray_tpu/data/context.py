"""DataContext: per-driver execution configuration for Data pipelines.

ray parity: python/ray/data/context.py (DataContext.get_current() — the
ambient settings object every Dataset execution reads) — trimmed to the
knobs this executor honors: in-flight task window, per-operator memory
budget for streaming backpressure, ordering, and block sizing.
"""

from __future__ import annotations

import threading
from typing import Optional

# Default per-operator in-flight byte budget: matches the reference's
# default object-store-fraction heuristic scaled to one operator
# (streaming_executor_state.py budgets operator outqueues against the
# object store; a quarter GiB per op is its observed default envelope).
DEFAULT_OP_MEMORY_BUDGET = 256 * 1024 * 1024

DEFAULT_TARGET_MAX_BLOCK_SIZE = 128 * 1024 * 1024


class DataContext:
    _lock = threading.Lock()
    _current: Optional["DataContext"] = None

    def __init__(self):
        # max concurrently running tasks per map/read operator
        self.max_in_flight_tasks = 8
        # estimated in-flight output bytes an operator may hold before new
        # task admission blocks (memory-budget backpressure)
        self.op_memory_budget = DEFAULT_OP_MEMORY_BUDGET
        # seed estimate for a task's output before any task of the
        # operator has completed
        self.target_max_block_size = DEFAULT_TARGET_MAX_BLOCK_SIZE
        self.preserve_order = True
        # record per-operator stats during execution (Dataset.stats())
        self.enable_stats = True

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = DataContext()
            return cls._current

    @classmethod
    def _set_current(cls, ctx: "DataContext"):
        with cls._lock:
            cls._current = ctx
