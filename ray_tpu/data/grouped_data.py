"""GroupedData: groupby aggregations and map_groups.

Reference parity: ray python/ray/data/grouped_data.py + data/aggregate/ —
hash-partition exchange then per-partition grouped reduction.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np
import pyarrow as pa

from ray_tpu.data._internal import executor as X
from ray_tpu.data._internal import logical as L
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    BlockMetadata,
    DelegatingBlockBuilder,
    concat_blocks,
)

_AGG_FNS = {
    "sum": np.sum,
    "min": np.min,
    "max": np.max,
    "mean": np.mean,
    "std": lambda a: np.std(a, ddof=1),
    "count": len,
}


class GroupedData:
    def __init__(self, dataset, keys: List[str]):
        self._ds = dataset
        self._keys = keys

    # ------------------------------------------------------------------
    def _exchange(self, per_group_fn: Callable[[tuple, Block], Any]):
        """Hash-partition by key, then apply per_group_fn to each group."""
        keys = self._keys

        def fn(bundles):
            if not bundles:
                return bundles
            n = len(bundles)

            def part(block, n_out):
                return BlockAccessor(block).hash_partition(keys, n_out)

            def red(parts):
                merged = concat_blocks(parts)
                if merged.num_rows == 0:
                    return merged
                acc = BlockAccessor(merged)
                builder = DelegatingBlockBuilder()
                for gk in acc.group_keys(keys):
                    sub = acc.filter_by_key(keys, gk)
                    out = per_group_fn(gk, sub)
                    if isinstance(out, list):
                        for r in out:
                            builder.add(r)
                    elif isinstance(out, dict):
                        builder.add(out)
                    else:
                        builder.add_batch(out)
                return builder.build()

            return X.shuffle_exchange(bundles, n, part, red)

        from ray_tpu.data.dataset import Dataset

        return Dataset(L.AllToAll("Aggregate", self._ds._dag, fn))

    # ------------------------------------------------------------------
    def aggregate(self, **named: Dict[str, tuple]):
        """aggregate(out_col=("in_col", "sum"), ...)"""
        keys = self._keys
        specs = dict(named)

        def per_group(gk, sub: Block):
            row = {k: v for k, v in zip(keys, gk)}
            for out_col, (in_col, how) in specs.items():
                col = np.asarray(sub.column(in_col))
                row[out_col] = _AGG_FNS[how](col) if len(col) else None
            return row

        return self._exchange(per_group)

    def _simple(self, how: str, on: Union[str, List[str], None]):
        keys = self._keys

        def per_group(gk, sub: Block):
            row = {k: v for k, v in zip(keys, gk)}
            cols = (
                [on] if isinstance(on, str)
                else on if on
                else [c for c in sub.column_names if c not in keys]
            )
            for c in cols:
                arr = np.asarray(sub.column(c))
                row[f"{how}({c})"] = (
                    _AGG_FNS[how](arr) if len(arr) else None
                )
            return row

        return self._exchange(per_group)

    def sum(self, on=None):
        return self._simple("sum", on)

    def min(self, on=None):
        return self._simple("min", on)

    def max(self, on=None):
        return self._simple("max", on)

    def mean(self, on=None):
        return self._simple("mean", on)

    def std(self, on=None):
        return self._simple("std", on)

    def count(self):
        keys = self._keys

        def per_group(gk, sub: Block):
            row = {k: v for k, v in zip(keys, gk)}
            row["count()"] = sub.num_rows
            return row

        return self._exchange(per_group)

    def map_groups(self, fn: Callable, *, batch_format: str = "pyarrow",
                   **_ignored):
        def per_group(gk, sub: Block):
            batch = BlockAccessor(sub).to_batch(batch_format)
            return fn(batch)

        return self._exchange(per_group)
