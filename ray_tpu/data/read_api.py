"""Read API: the ``ray_tpu.data.read_* / from_*`` entry points
(ray parity: python/ray/data/read_api.py)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data import datasource as ds
from ray_tpu.data.block import BlockMetadata, rows_to_block, tensor_column
from ray_tpu.data.dataset import Dataset

DEFAULT_PARALLELISM = 8


def _par(parallelism: int) -> int:
    return parallelism if parallelism and parallelism > 0 else DEFAULT_PARALLELISM


def range(n: int, *, parallelism: int = -1, **_kw) -> Dataset:  # noqa: A001
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.range_tasks(n, p), p)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1,
                 **_kw) -> Dataset:
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.range_tensor_tasks(n, shape, p), p)


def from_items(items: List[Any], *, parallelism: int = -1, **_kw) -> Dataset:
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.items_tasks(items, p), p)


def read_parquet(paths, *, parallelism: int = -1,
                 columns: Optional[List[str]] = None, **_kw) -> Dataset:
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.parquet_tasks(paths, p, columns), p)


def read_csv(paths, *, parallelism: int = -1, **arrow_csv_kwargs) -> Dataset:
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.csv_tasks(paths, p, **arrow_csv_kwargs), p)


def read_json(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.json_tasks(paths, p), p)


def read_numpy(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.numpy_tasks(paths, p), p)


def read_binary_files(paths, *, include_paths: bool = False,
                      parallelism: int = -1, **_kw) -> Dataset:
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.binary_tasks(paths, p, include_paths), p)


def from_pandas(dfs, *, parallelism: int = -1) -> Dataset:
    import ray_tpu

    if not isinstance(dfs, list):
        dfs = [dfs]
    bundles = []
    for df in dfs:
        t = pa.Table.from_pandas(df, preserve_index=False)
        bundles.append((ray_tpu.put(t), BlockMetadata.for_block(t)))
    return Dataset.from_bundles(bundles)


def from_numpy(arrays, *, column: str = "data", parallelism: int = -1) -> Dataset:
    import ray_tpu

    if not isinstance(arrays, list):
        arrays = [arrays]
    bundles = []
    for arr in arrays:
        if arr.ndim == 1:
            t = pa.table({column: pa.array(arr)})
        else:
            t = pa.table({column: tensor_column(arr)})
        bundles.append((ray_tpu.put(t), BlockMetadata.for_block(t)))
    return Dataset.from_bundles(bundles)


def from_arrow(tables, *, parallelism: int = -1) -> Dataset:
    import ray_tpu

    if not isinstance(tables, list):
        tables = [tables]
    return Dataset.from_bundles(
        [(ray_tpu.put(t), BlockMetadata.for_block(t)) for t in tables]
    )


def from_arrow_refs(refs: List[Any]) -> Dataset:
    import ray_tpu

    return Dataset.from_bundles(
        [(r, BlockMetadata.for_block(ray_tpu.get(r))) for r in refs]
    )


def read_images(paths, *, size: Optional[tuple] = None,
                mode: Optional[str] = None, include_paths: bool = False,
                parallelism: int = -1, **_kw) -> Dataset:
    """ray parity: read_images (data/datasource/image_datasource.py)."""
    p = _par(parallelism)
    return Dataset.from_read_tasks(
        ds.image_tasks(paths, p, size=size, mode=mode,
                       include_paths=include_paths), p
    )


def read_tfrecords(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    """ray parity: read_tfrecords — tf.train.Example protos parsed without
    a tensorflow dependency."""
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.tfrecord_tasks(paths, p), p)


def read_webdataset(paths, *, parallelism: int = -1, **_kw) -> Dataset:
    """ray parity: read_webdataset — tar shards, one row per sample key."""
    p = _par(parallelism)
    return Dataset.from_read_tasks(ds.webdataset_tasks(paths, p), p)


def read_sql(sql: str, connection_factory, *, parallelism: int = -1,
             **_kw) -> Dataset:
    """ray parity: read_sql — any DB-API connection factory (sqlite3,
    psycopg2, ...)."""
    p = _par(parallelism)
    return Dataset.from_read_tasks(
        ds.sql_tasks(sql, connection_factory, p), p
    )


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None, parallelism: int = -1, **_kw) -> Dataset:
    """ray parity: read_mongo — _id-sliced partitioned read of a MongoDB
    collection; requires pymongo (clear error here if absent)."""
    p = _par(parallelism)
    return Dataset.from_read_tasks(
        ds.mongo_tasks(uri, database, collection, pipeline=pipeline,
                       parallelism=p), p
    )
