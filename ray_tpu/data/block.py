"""Block layer: the unit of data a Dataset moves through the cluster.

A block is a ``pyarrow.Table`` riding the shared-memory object store
(zero-copy on read thanks to pickle5 out-of-band buffers). ``BlockAccessor``
bundles the per-block operations the physical operators need.

Reference parity: ray python/ray/data/block.py (BlockAccessor),
_internal/arrow_block.py (ArrowBlockAccessor) — redesigned: one Arrow-only
block type instead of the Arrow/pandas/simple triple, since TPU-host RAM is
plentiful and Arrow → numpy is zero-copy for fixed-width types.
"""

from __future__ import annotations

import builtins
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

import numpy as np
import pyarrow as pa
import pyarrow.compute  # noqa: F401 — pa.compute is a lazy submodule; a
# worker that only imports pyarrow crashes on pa.compute.* without this

Block = pa.Table
# Batches cross the user boundary in one of these shapes.
BatchFormat = ("pyarrow", "pandas", "numpy", "dict")

# Tables with a single unnamed value column (simple datasets: range(),
# from_items([1,2,3])) use this column name, like the reference's
# TENSOR_COLUMN_NAME / "item" convention.
VALUE_COL = "item"


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema] = None
    input_files: Optional[List[str]] = None

    @staticmethod
    def for_block(block: Block, input_files: Optional[List[str]] = None
                  ) -> "BlockMetadata":
        return BlockMetadata(
            num_rows=block.num_rows,
            size_bytes=block.nbytes,
            schema=block.schema,
            input_files=input_files,
        )


def _to_table(batch: Any) -> Block:
    """Coerce any user-returned batch into an Arrow table."""
    if isinstance(batch, pa.Table):
        return batch
    if batch is None:
        return pa.table({})
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:  # pragma: no cover
        pass
    if isinstance(batch, dict):
        cols = {}
        for k, v in batch.items():
            cols[k] = tensor_column(v) if (
                isinstance(v, np.ndarray) and v.ndim > 1
            ) else pa.array(v)
        return pa.table(cols)
    if isinstance(batch, list):
        return rows_to_block(batch)
    raise TypeError(f"cannot convert batch of type {type(batch)} to a block")


def tensor_column(arr: np.ndarray) -> pa.Array:
    """Store a (N, ...) ndarray as a fixed-shape tensor column so the
    per-row shape survives the Arrow round-trip (reference parity:
    the ArrowTensorArray extension type)."""
    return pa.FixedShapeTensorArray.from_numpy_ndarray(np.ascontiguousarray(arr))


def column_to_numpy(col) -> np.ndarray:
    """Column -> ndarray, restoring tensor shapes for tensor columns."""
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    if isinstance(col.type, pa.FixedShapeTensorType):
        return col.to_numpy_ndarray()
    return np.asarray(col)


def rows_to_block(rows: List[Any]) -> Block:
    """Build a block from a list of rows (dicts or bare values)."""
    if rows and isinstance(rows[0], dict):
        cols: Dict[str, list] = {k: [] for k in rows[0]}
        for r in rows:
            for k in cols:
                cols[k].append(r.get(k))
        return pa.table({k: pa.array(v) for k, v in cols.items()})
    return pa.table({VALUE_COL: pa.array(rows)})


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b is not None and b.num_rows > 0]
    if not blocks:
        return pa.table({})
    if len(blocks) == 1:
        return blocks[0]
    return pa.concat_tables(blocks, promote_options="default")


class BlockAccessor:
    """Operations over one block (ray parity: data/block.py BlockAccessor)."""

    def __init__(self, block: Block):
        self._t = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- shape ---------------------------------------------------------
    def num_rows(self) -> int:
        return self._t.num_rows

    def size_bytes(self) -> int:
        return self._t.nbytes

    def schema(self) -> pa.Schema:
        return self._t.schema

    def metadata(self) -> BlockMetadata:
        return BlockMetadata.for_block(self._t)

    # -- conversions ---------------------------------------------------
    def to_arrow(self) -> pa.Table:
        return self._t

    def to_pandas(self):
        return self._t.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        cols = columns or self._t.column_names
        return {c: column_to_numpy(self._t.column(c)) for c in cols}

    def to_batch(self, batch_format: str):
        if batch_format in ("pyarrow", "arrow"):
            return self._t
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("numpy", "dict"):
            out = self.to_numpy()
            if batch_format == "numpy" and set(out) == {VALUE_COL}:
                return out[VALUE_COL]
            return out
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterable[Any]:
        simple = self._t.column_names == [VALUE_COL]
        for chunk in self._t.to_pylist():
            yield chunk[VALUE_COL] if simple else chunk

    # -- slicing -------------------------------------------------------
    def slice(self, start: int, end: int) -> Block:
        return self._t.slice(start, end - start)

    def take(self, indices: List[int]) -> Block:
        return self._t.take(pa.array(indices))

    def select(self, columns: List[str]) -> Block:
        return self._t.select(columns)

    def drop(self, columns: List[str]) -> Block:
        keep = [c for c in self._t.column_names if c not in columns]
        return self._t.select(keep)

    def rename(self, mapping: Dict[str, str]) -> Block:
        names = [mapping.get(c, c) for c in self._t.column_names]
        return self._t.rename_columns(names)

    # -- compute -------------------------------------------------------
    def sort_by(self, key: Union[str, List[str]], descending: bool = False) -> Block:
        keys = [key] if isinstance(key, str) else list(key)
        order = "descending" if descending else "ascending"
        return self._t.sort_by([(k, order) for k in keys])

    def sample_boundaries(self, key: str, n: int) -> List[Any]:
        """Sample n-1 split points for range partitioning."""
        col = np.asarray(self._t.column(key))
        if len(col) == 0 or n <= 1:
            return []
        qs = np.linspace(0, 1, n + 1)[1:-1]
        return list(np.quantile(col, qs, method="nearest"))

    def range_partition(self, key: str, boundaries: List[Any],
                        descending: bool = False) -> List[Block]:
        """Split into len(boundaries)+1 blocks by key ranges."""
        if not boundaries:
            return [self._t]
        col = np.asarray(self._t.column(key))
        idx = np.searchsorted(np.asarray(boundaries), col, side="right")
        if descending:
            idx = len(boundaries) - idx
        return [self._t.filter(pa.array(idx == p))
                for p in range(len(boundaries) + 1)]

    def hash_partition(self, key: Union[str, List[str]], n: int) -> List[Block]:
        if n <= 1:
            return [self._t]
        import zlib

        keys = [key] if isinstance(key, str) else list(key)
        h = np.zeros(self._t.num_rows, dtype=np.uint64)
        for k in keys:
            col = self._t.column(k)
            vals = col.to_pylist()
            # crc32 of the value repr: deterministic ACROSS PROCESSES —
            # builtin hash() is salted per interpreter, which would split
            # one group over several partitions when map tasks run in
            # different workers.
            h = h * np.uint64(1000003) + np.array(
                [zlib.crc32(repr(v).encode()) for v in vals],
                dtype=np.uint64,
            )
        part = (h % np.uint64(n)).astype(np.int64)
        return [self._t.filter(pa.array(part == p)) for p in range(n)]

    def random_shuffle_indices(self, seed: Optional[int]) -> Block:
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._t.num_rows)
        return self._t.take(pa.array(perm))

    # -- aggregation helpers -------------------------------------------
    def group_keys(self, keys: List[str]) -> List[tuple]:
        cols = [self._t.column(k).to_pylist() for k in keys]
        return list(dict.fromkeys(zip(*cols))) if cols else []

    def filter_by_key(self, keys: List[str], value: tuple) -> Block:
        mask = np.ones(self._t.num_rows, dtype=bool)
        for k, v in zip(keys, value):
            mask &= np.asarray(
                pa.compute.equal(self._t.column(k), pa.scalar(v)).combine_chunks()
            )
        return self._t.filter(pa.array(mask))


class DelegatingBlockBuilder:
    """Accumulate rows / batches into output blocks capped at a target size
    (ray parity: _internal/delegating_block_builder.py)."""

    def __init__(self, target_rows: Optional[int] = None):
        self._rows: List[Any] = []
        self._tables: List[Block] = []
        self._target = target_rows

    def add(self, row: Any):
        self._rows.append(row)

    def add_batch(self, batch: Any):
        self._flush_rows()
        self._tables.append(_to_table(batch))

    def _flush_rows(self):
        if self._rows:
            self._tables.append(rows_to_block(self._rows))
            self._rows = []

    def num_rows(self) -> int:
        return sum(t.num_rows for t in self._tables) + len(self._rows)

    def build(self) -> Block:
        self._flush_rows()
        return concat_blocks(self._tables)
