"""Datasources: build per-task read closures and write blocks out.

Reference parity: ray python/ray/data/datasource/ (file_based_datasource.py,
parquet_datasource.py, ...) — compressed to closure-returning factories: a
``ReadTask`` here is just a zero-arg callable returning one block, shipped
to a remote task by the executor.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import (VALUE_COL, column_to_numpy,
                                rows_to_block, tensor_column)

ReadTask = Callable[[], pa.Table]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if not f.startswith((".", "_")):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files under {paths}")
    return out


def _chunk(items: List[Any], n: int) -> List[List[Any]]:
    n = max(1, min(n, len(items)))
    size, rem = divmod(len(items), n)
    chunks, i = [], 0
    for k in range(n):
        step = size + (1 if k < rem else 0)
        if step:
            chunks.append(items[i : i + step])
        i += step
    return chunks


# -- readers ------------------------------------------------------------

def range_tasks(n: int, parallelism: int) -> List[ReadTask]:
    tasks = []
    per = max(1, -(-n // max(parallelism, 1)))
    start = 0
    while start < n:
        end = min(start + per, n)

        def read(s=start, e=end):
            return pa.table({VALUE_COL: pa.array(np.arange(s, e))})

        tasks.append(read)
        start = end
    return tasks


def range_tensor_tasks(n: int, shape: tuple, parallelism: int) -> List[ReadTask]:
    tasks = []
    per = max(1, -(-n // max(parallelism, 1)))
    start = 0
    while start < n:
        end = min(start + per, n)

        def read(s=start, e=end, shape=shape):
            flat = int(np.prod(shape))
            data = (
                np.arange(s, e, dtype=np.int64)
                .repeat(flat)
                .reshape(e - s, *shape)
            )
            return pa.table({"data": tensor_column(data)})

        tasks.append(read)
        start = end
    return tasks


def items_tasks(items: List[Any], parallelism: int) -> List[ReadTask]:
    return [
        (lambda chunk=chunk: rows_to_block(chunk))
        for chunk in _chunk(list(items), parallelism)
    ]


def parquet_tasks(paths, parallelism: int,
                  columns: Optional[List[str]] = None) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            import pyarrow.parquet as pq

            tables = [pq.read_table(f, columns=columns) for f in group]
            return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def csv_tasks(paths, parallelism: int, **arrow_csv_kwargs) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            import pyarrow.csv as pcsv

            tables = [pcsv.read_csv(f, **arrow_csv_kwargs) for f in group]
            return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def json_tasks(paths, parallelism: int) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            import pyarrow.json as pjson

            tables = [pjson.read_json(f) for f in group]
            return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def numpy_tasks(paths, parallelism: int) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            arrs = [np.load(f) for f in group]
            arr = np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
            if arr.ndim == 1:
                return pa.table({"data": pa.array(arr)})
            return pa.table({"data": tensor_column(arr)})

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def binary_tasks(paths, parallelism: int,
                 include_paths: bool = False) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            rows = []
            for f in group:
                with open(f, "rb") as fh:
                    row: Dict[str, Any] = {"bytes": fh.read()}
                if include_paths:
                    row["path"] = f
                rows.append(row)
            return rows_to_block(rows)

        return read

    return [make(g) for g in _chunk(files, parallelism)]


# -- writers ------------------------------------------------------------

def write_block_parquet(block: pa.Table, path: str, idx: int) -> str:
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.parquet")
    pq.write_table(block, out)
    return out


def write_block_csv(block: pa.Table, path: str, idx: int) -> str:
    import pyarrow.csv as pcsv

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.csv")
    pcsv.write_csv(block, out)
    return out


def write_block_json(block: pa.Table, path: str, idx: int) -> str:
    import json

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.json")
    with open(out, "w") as fh:
        for row in block.to_pylist():
            fh.write(json.dumps(row, default=str) + "\n")
    return out


def write_block_numpy(block: pa.Table, path: str, idx: int,
                      column: str = "data") -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.npy")
    np.save(out, column_to_numpy(block.column(column)))
    return out


# -- extended datasources (ray: data/datasource/{image_datasource.py,
# tfrecords_datasource.py, webdataset_datasource.py, sql_datasource.py}) --

def image_tasks(paths, parallelism: int, *, size: Optional[tuple] = None,
                mode: Optional[str] = None,
                include_paths: bool = False) -> List[ReadTask]:
    """Decode images into a tensor column (ray: ImageDatasource). ``size``
    resizes, ``mode`` converts (e.g. "RGB", "L")."""
    files = [p for p in _expand_paths(paths)
             if p.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".gif",
                                    ".tif", ".tiff", ".webp"))]
    if not files:
        raise FileNotFoundError(f"no image files under {paths}")

    def make(group: List[str]):
        def read():
            from PIL import Image

            arrays, names = [], []
            for f in group:
                img = Image.open(f)
                if mode is not None:
                    img = img.convert(mode)
                if size is not None:
                    img = img.resize(size)
                arrays.append(np.asarray(img))
                names.append(f)
            shapes = {a.shape for a in arrays}
            if len(shapes) > 1:
                raise ValueError(
                    f"images under the path have mixed shapes {shapes}; "
                    "pass size=(w, h) (and mode='RGB'/'L' for mixed color "
                    "modes) to read_images to normalize them"
                )
            cols = {"image": tensor_column(np.stack(arrays))}
            if include_paths:
                cols["path"] = pa.array(names)
            return pa.table(cols)

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def _read_tfrecord_frames(path: str):
    """Yield raw record payloads from a TFRecord file. Wire format per
    record: 8B little-endian length, 4B length-CRC, payload, 4B data-CRC
    (CRCs unverified — malformed files surface as struct errors)."""
    import struct

    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            f.read(4)  # length crc
            payload = f.read(length)
            f.read(4)  # data crc
            if len(payload) < length:
                return
            yield payload


def _parse_tf_example(payload: bytes) -> Dict[str, Any]:
    """Minimal tf.train.Example protobuf parser (no tensorflow dep).

    Example = { features(1): Features { feature(1): map<string, Feature> }}
    Feature = one of bytes_list(1) / float_list(2) / int64_list(3).
    """
    def read_varint(buf, i):
        shift = result = 0
        while True:
            b = buf[i]
            i += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result, i
            shift += 7

    def read_fields(buf):
        i = 0
        while i < len(buf):
            tag, i = read_varint(buf, i)
            field, wire = tag >> 3, tag & 7
            if wire == 2:  # length-delimited
                n, i = read_varint(buf, i)
                yield field, buf[i:i + n]
                i += n
            elif wire == 0:
                v, i = read_varint(buf, i)
                yield field, v
            elif wire == 5:
                yield field, buf[i:i + 4]
                i += 4
            elif wire == 1:
                yield field, buf[i:i + 8]
                i += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    import struct

    out: Dict[str, Any] = {}
    for f1, features in read_fields(payload):
        if f1 != 1:
            continue
        for f2, entry in read_fields(features):
            if f2 != 1:
                continue
            key = value = None
            for f3, kv in read_fields(entry):
                if f3 == 1:
                    key = kv.decode()
                elif f3 == 2:
                    for f4, lst in read_fields(kv):
                        # Repeated fields accumulate: both non-packed
                        # encodings (one entry per value) and packed
                        # payloads split across chunks are legal protobuf.
                        if f4 == 1:  # bytes_list
                            got = [v for f5, v in read_fields(lst) if f5 == 1]
                            value = (value or []) + got
                        elif f4 == 2:  # float_list (packed or repeated)
                            for f5, packed in read_fields(lst):
                                if f5 != 1:
                                    continue
                                if isinstance(packed, int):
                                    got = [packed]
                                elif len(packed) == 4:
                                    got = [struct.unpack("<f", packed)[0]]
                                else:
                                    got = list(struct.unpack(
                                        f"<{len(packed) // 4}f", packed
                                    ))
                                value = (value or []) + got
                        elif f4 == 3:  # int64_list (packed or repeated)
                            for f5, packed in read_fields(lst):
                                if f5 != 1:
                                    continue
                                if isinstance(packed, int):
                                    got = [packed]
                                else:
                                    got, i = [], 0
                                    while i < len(packed):
                                        v, i = read_varint(packed, i)
                                        got.append(v)
                                value = (value or []) + got
            if key is not None and value is not None:
                out[key] = value[0] if len(value) == 1 else value
    return out


def tfrecord_tasks(paths, parallelism: int) -> List[ReadTask]:
    """Read TFRecord files of tf.train.Example protos without a tensorflow
    dependency (ray: TFRecordDatasource)."""
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            rows = []
            for f in group:
                for payload in _read_tfrecord_frames(f):
                    rows.append(_parse_tf_example(payload))
            return rows_to_block(rows)

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def webdataset_tasks(paths, parallelism: int) -> List[ReadTask]:
    """Read WebDataset-style tar shards: members grouped by basename stem
    become one row with one column per extension (ray:
    WebDatasetDatasource)."""
    files = [p for p in _expand_paths(paths) if p.endswith(".tar")]
    if not files:
        raise FileNotFoundError(f"no .tar shards under {paths}")

    def make(group: List[str]):
        def read():
            import tarfile

            rows: List[Dict[str, Any]] = []
            for shard in group:
                samples: Dict[str, Dict[str, Any]] = {}
                with tarfile.open(shard) as tf:
                    for member in tf.getmembers():
                        if not member.isfile():
                            continue
                        stem, _, ext = member.name.partition(".")
                        data = tf.extractfile(member).read()
                        if ext in ("txt", "cls", "json"):
                            value: Any = data.decode()
                        else:
                            value = data
                        samples.setdefault(stem, {"__key__": stem})[ext] = value
                rows.extend(samples[k] for k in sorted(samples))
            return rows_to_block(rows)

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def sql_tasks(sql: str, connection_factory: Callable[[], Any],
              parallelism: int) -> List[ReadTask]:
    """Run a SQL query through a DB-API connection factory (ray:
    SQLDatasource). The query runs once (DB-API has no generic
    partitioning); parallelism applies to downstream transforms."""

    def read():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, row)) for row in cur.fetchall()]
        finally:
            conn.close()
        return rows_to_block(rows)

    return [read]


def mongo_tasks(uri: str, database: str, collection: str,
                pipeline=None, parallelism: int = 1) -> List[ReadTask]:
    """Read a MongoDB collection (ray: python/ray/data/datasource/
    mongo_datasource.py). Partitioned by DISJOINT _id ranges planned with
    one $bucketAuto pass (the reference's approach): each task runs
    [$match _id-range] + user pipeline over its own index-driven slice —
    no $skip rescans, no overlap, no dropped documents for the snapshot
    taken at planning time. Gated on pymongo — a clear ImportError at
    read_mongo() call time, not at task time."""
    try:
        import pymongo  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_mongo requires pymongo, which this image does not "
            "ship; install it in your runtime environment"
        ) from e

    def make_read(id_range):
        def read():
            import pymongo as pm

            client = pm.MongoClient(uri)
            try:
                coll = client[database][collection]
                stages = []
                if id_range is not None:
                    lo, hi, last = id_range
                    cond = {"$gte": lo, ("$lte" if last else "$lt"): hi}
                    stages.append({"$match": {"_id": cond}})
                stages += list(pipeline or [])
                rows = [dict(doc) for doc in coll.aggregate(stages)]
            finally:
                client.close()
            return rows_to_block(rows)

        return read

    if parallelism <= 1:
        return [make_read(None)]

    import pymongo as pm

    client = pm.MongoClient(uri)
    try:
        # one planning pass: P contiguous _id buckets (min inclusive; max
        # exclusive except the final bucket, which $bucketAuto closes)
        buckets = list(client[database][collection].aggregate([
            {"$bucketAuto": {"groupBy": "$_id", "buckets": parallelism}}
        ]))
    finally:
        client.close()
    if not buckets:
        return [make_read(None)]
    return [
        make_read((b["_id"]["min"], b["_id"]["max"],
                   i == len(buckets) - 1))
        for i, b in enumerate(buckets)
    ]
