"""Datasources: build per-task read closures and write blocks out.

Reference parity: ray python/ray/data/datasource/ (file_based_datasource.py,
parquet_datasource.py, ...) — compressed to closure-returning factories: a
``ReadTask`` here is just a zero-arg callable returning one block, shipped
to a remote task by the executor.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import (VALUE_COL, column_to_numpy,
                                rows_to_block, tensor_column)

ReadTask = Callable[[], pa.Table]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if not f.startswith((".", "_")):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files under {paths}")
    return out


def _chunk(items: List[Any], n: int) -> List[List[Any]]:
    n = max(1, min(n, len(items)))
    size, rem = divmod(len(items), n)
    chunks, i = [], 0
    for k in range(n):
        step = size + (1 if k < rem else 0)
        if step:
            chunks.append(items[i : i + step])
        i += step
    return chunks


# -- readers ------------------------------------------------------------

def range_tasks(n: int, parallelism: int) -> List[ReadTask]:
    tasks = []
    per = max(1, -(-n // max(parallelism, 1)))
    start = 0
    while start < n:
        end = min(start + per, n)

        def read(s=start, e=end):
            return pa.table({VALUE_COL: pa.array(np.arange(s, e))})

        tasks.append(read)
        start = end
    return tasks


def range_tensor_tasks(n: int, shape: tuple, parallelism: int) -> List[ReadTask]:
    tasks = []
    per = max(1, -(-n // max(parallelism, 1)))
    start = 0
    while start < n:
        end = min(start + per, n)

        def read(s=start, e=end, shape=shape):
            flat = int(np.prod(shape))
            data = (
                np.arange(s, e, dtype=np.int64)
                .repeat(flat)
                .reshape(e - s, *shape)
            )
            return pa.table({"data": tensor_column(data)})

        tasks.append(read)
        start = end
    return tasks


def items_tasks(items: List[Any], parallelism: int) -> List[ReadTask]:
    return [
        (lambda chunk=chunk: rows_to_block(chunk))
        for chunk in _chunk(list(items), parallelism)
    ]


def parquet_tasks(paths, parallelism: int,
                  columns: Optional[List[str]] = None) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            import pyarrow.parquet as pq

            tables = [pq.read_table(f, columns=columns) for f in group]
            return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def csv_tasks(paths, parallelism: int, **arrow_csv_kwargs) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            import pyarrow.csv as pcsv

            tables = [pcsv.read_csv(f, **arrow_csv_kwargs) for f in group]
            return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def json_tasks(paths, parallelism: int) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            import pyarrow.json as pjson

            tables = [pjson.read_json(f) for f in group]
            return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def numpy_tasks(paths, parallelism: int) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            arrs = [np.load(f) for f in group]
            arr = np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
            if arr.ndim == 1:
                return pa.table({"data": pa.array(arr)})
            return pa.table({"data": tensor_column(arr)})

        return read

    return [make(g) for g in _chunk(files, parallelism)]


def binary_tasks(paths, parallelism: int,
                 include_paths: bool = False) -> List[ReadTask]:
    files = _expand_paths(paths)

    def make(group: List[str]):
        def read():
            rows = []
            for f in group:
                with open(f, "rb") as fh:
                    row: Dict[str, Any] = {"bytes": fh.read()}
                if include_paths:
                    row["path"] = f
                rows.append(row)
            return rows_to_block(rows)

        return read

    return [make(g) for g in _chunk(files, parallelism)]


# -- writers ------------------------------------------------------------

def write_block_parquet(block: pa.Table, path: str, idx: int) -> str:
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.parquet")
    pq.write_table(block, out)
    return out


def write_block_csv(block: pa.Table, path: str, idx: int) -> str:
    import pyarrow.csv as pcsv

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.csv")
    pcsv.write_csv(block, out)
    return out


def write_block_json(block: pa.Table, path: str, idx: int) -> str:
    import json

    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.json")
    with open(out, "w") as fh:
        for row in block.to_pylist():
            fh.write(json.dumps(row, default=str) + "\n")
    return out


def write_block_numpy(block: pa.Table, path: str, idx: int,
                      column: str = "data") -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{idx:05d}.npy")
    np.save(out, column_to_numpy(block.column(column)))
    return out
