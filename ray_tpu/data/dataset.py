"""Dataset: lazy, distributed, Arrow-blocked data pipelines.

Reference parity: ray python/ray/data/dataset.py (5.2k LoC facade) — same
user surface (map_batches/filter/groupby/sort/random_shuffle/repartition/
iter_batches/streaming_split/write_*), rebuilt over this package's logical
plan + streaming executor instead of the reference's physical-operator tree.
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

from ray_tpu.data import datasource as ds
from ray_tpu.data._internal import executor as X
from ray_tpu.data._internal import logical as L
from ray_tpu.data.block import (
    VALUE_COL,
    Block,
    BlockAccessor,
    BlockMetadata,
    DelegatingBlockBuilder,
    concat_blocks,
    rows_to_block,
)


def _row_fn_to_block_fn(fn: Callable, kind: str,
                        fn_args=None, fn_kwargs=None) -> Callable:
    """Lift a per-row UDF into a per-block transform."""
    fn_args = fn_args or ()
    fn_kwargs = fn_kwargs or {}

    def block_fn(block: Block) -> Block:
        acc = BlockAccessor(block)
        builder = DelegatingBlockBuilder()
        for row in acc.iter_rows():
            if kind == "map":
                builder.add(fn(row, *fn_args, **fn_kwargs))
            elif kind == "flat_map":
                for out in fn(row, *fn_args, **fn_kwargs):
                    builder.add(out)
            elif kind == "filter":
                if fn(row, *fn_args, **fn_kwargs):
                    builder.add(row)
        out = builder.build()
        # keep schema for empty outputs
        return out if out.num_rows or not block.num_rows else block.slice(0, 0)

    return block_fn


def _batch_fn_to_block_fn(fn: Callable, batch_size: Optional[int],
                          batch_format: str, fn_args=None, fn_kwargs=None,
                          zero_copy: bool = False) -> Callable:
    from ray_tpu.data.block import _to_table

    fn_args = fn_args or ()
    fn_kwargs = fn_kwargs or {}

    def block_fn(block: Block) -> Block:
        acc = BlockAccessor(block)
        n = acc.num_rows()
        outs = []
        step = batch_size or max(n, 1)
        for start in range(0, max(n, 1), step):
            sub = BlockAccessor(acc.slice(start, min(start + step, n)))
            batch = sub.to_batch(batch_format)
            out = fn(batch, *fn_args, **fn_kwargs)
            outs.append(_to_table(out))
            if n == 0:
                break
        return concat_blocks(outs)

    return block_fn


class Dataset:
    """A lazy pipeline of blocks. All transforms return a new Dataset."""

    def __init__(self, dag: L.LogicalOp):
        self._dag = dag
        self._cached: Optional[List[X.RefBundle]] = None
        self._exec_stats = None  # ExecStats from the last execution

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    @staticmethod
    def from_read_tasks(tasks: List[ds.ReadTask], parallelism: int) -> "Dataset":
        return Dataset(L.Read(tasks, parallelism))

    @staticmethod
    def from_bundles(bundles: List[X.RefBundle]) -> "Dataset":
        refs = [r for r, _ in bundles]
        metas = [m for _, m in bundles]
        d = Dataset(L.InputData(refs, metas))
        d._cached = list(bundles)
        return d

    def _plan(self) -> L.LogicalPlan:
        return L.LogicalPlan(self._dag)

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------
    def map(self, fn: Callable, *, compute=None, fn_args=None, fn_kwargs=None,
            num_cpus: Optional[float] = None, concurrency=None, **_ignored
            ) -> "Dataset":
        return self._add_map("Map", _row_fn_to_block_fn(fn, "map", fn_args,
                                                        fn_kwargs),
                             fn, compute, concurrency, num_cpus)

    def flat_map(self, fn: Callable, *, compute=None, concurrency=None,
                 num_cpus: Optional[float] = None, **_ignored) -> "Dataset":
        return self._add_map("FlatMap",
                             _row_fn_to_block_fn(fn, "flat_map"),
                             fn, compute, concurrency, num_cpus)

    def filter(self, fn: Callable, *, compute=None, concurrency=None,
               num_cpus: Optional[float] = None, **_ignored) -> "Dataset":
        return self._add_map("Filter", _row_fn_to_block_fn(fn, "filter"),
                             fn, compute, concurrency, num_cpus)

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    compute=None, concurrency=None,
                    fn_args=None, fn_kwargs=None,
                    fn_constructor_args=None, fn_constructor_kwargs=None,
                    num_cpus: Optional[float] = None,
                    zero_copy_batch: bool = False, **_ignored) -> "Dataset":
        if isinstance(fn, type):
            # Stateful callable class -> actor pool.
            ctor_args = fn_constructor_args or ()
            ctor_kwargs = fn_constructor_kwargs or {}
            n = concurrency or 1
            if isinstance(n, (tuple, list)):
                n = n[-1]
            cls = fn

            def fn_factory():
                inst = cls(*ctor_args, **ctor_kwargs)
                return _batch_fn_to_block_fn(
                    inst, batch_size, batch_format, fn_args, fn_kwargs
                )

            op = L.MapBlocks(
                "MapBatches(actors)", self._dag, fn_factory,
                compute=("actors", int(n)),
                resources={"CPU": num_cpus} if num_cpus else {},
            )
            return Dataset(op)
        block_fn = _batch_fn_to_block_fn(fn, batch_size, batch_format,
                                         fn_args, fn_kwargs, zero_copy_batch)
        return self._add_map("MapBatches", block_fn, fn, compute, concurrency,
                             num_cpus)

    def _add_map(self, name, block_fn, fn, compute, concurrency, num_cpus
                 ) -> "Dataset":
        if compute is not None or (concurrency and not callable(fn)):
            pass  # actor compute only via class UDFs (map_batches)
        op = L.MapBlocks(
            name, self._dag, block_fn, compute=None,
            resources={"CPU": num_cpus} if num_cpus else {},
        )
        return Dataset(op)

    # -- column ops ----------------------------------------------------
    def add_column(self, name: str, fn: Callable, **kw) -> "Dataset":
        def block_fn(block: Block) -> Block:
            import pandas as pd

            df = block.to_pandas()
            col = fn(df)
            if name in df.columns:
                df[name] = col
            else:
                df.insert(len(df.columns), name, col)
            return pa.Table.from_pandas(df, preserve_index=False)

        return Dataset(L.MapBlocks("AddColumn", self._dag, block_fn))

    def drop_columns(self, cols: List[str], **kw) -> "Dataset":
        return Dataset(L.MapBlocks(
            "DropColumns", self._dag, lambda b: BlockAccessor(b).drop(cols)
        ))

    def select_columns(self, cols: List[str], **kw) -> "Dataset":
        return Dataset(L.MapBlocks(
            "SelectColumns", self._dag, lambda b: BlockAccessor(b).select(cols)
        ))

    def rename_columns(self, mapping: Dict[str, str], **kw) -> "Dataset":
        return Dataset(L.MapBlocks(
            "RenameColumns", self._dag, lambda b: BlockAccessor(b).rename(mapping)
        ))

    # -- all-to-all ----------------------------------------------------
    def repartition(self, num_blocks: int, *, shuffle: bool = False
                    ) -> "Dataset":
        def fn(bundles):
            if shuffle:
                def part(block, n):
                    shuffled = BlockAccessor(block).random_shuffle_indices(None)
                    return _round_robin_split(shuffled, n)

                return X.shuffle_exchange(bundles, num_blocks, part)
            return X.shuffle_exchange(bundles, num_blocks, _contiguous_split)

        return Dataset(L.AllToAll("Repartition", self._dag, fn))

    def random_shuffle(self, *, seed: Optional[int] = None, **kw) -> "Dataset":
        def fn(bundles):
            n = max(len(bundles), 1)

            def part(block, n_out, seed=seed):
                acc = BlockAccessor(block)
                shuffled = acc.random_shuffle_indices(seed)
                return _round_robin_split(shuffled, n_out)

            out = X.shuffle_exchange(bundles, n, part)
            # shuffle the reduce outputs' internal order too
            import ray_tpu

            def reshuffle(block, seed=seed):
                return BlockAccessor(block).random_shuffle_indices(seed)

            rr = ray_tpu.remote(num_returns=2)(
                lambda b: (lambda o: (o, BlockMetadata.for_block(o)))(reshuffle(b))
            )
            final = []
            for ref, _m in out:
                bref, mref = rr.remote(ref)
                final.append((bref, mref))
            return [(b, ray_tpu.get(m)) for b, m in final]

        return Dataset(L.AllToAll("RandomShuffle", self._dag, fn))

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        def fn(bundles):
            rng = np.random.default_rng(seed)
            order = rng.permutation(len(bundles))
            return [bundles[i] for i in order]

        return Dataset(L.AllToAll("RandomizeBlockOrder", self._dag, fn))

    def sort(self, key: Union[str, List[str]], descending: bool = False
             ) -> "Dataset":
        key0 = key if isinstance(key, str) else key[0]

        def fn(bundles):
            import ray_tpu

            if not bundles:
                return bundles
            n = len(bundles)
            # sample boundaries from first block
            first = ray_tpu.get(bundles[0][0])
            bounds = BlockAccessor(first).sample_boundaries(key0, n)

            def part(block, n_out):
                acc = BlockAccessor(block)
                sorted_b = acc.sort_by(key, descending)
                parts = BlockAccessor(sorted_b).range_partition(
                    key0, bounds, descending
                )
                while len(parts) < n_out:
                    parts.append(sorted_b.slice(0, 0))
                return parts[:n_out]

            def red(parts):
                merged = concat_blocks(parts)
                return BlockAccessor(merged).sort_by(key, descending)

            out = X.shuffle_exchange(bundles, n, part, red)
            return out if not descending else out

        return Dataset(L.AllToAll("Sort", self._dag, fn))

    def groupby(self, key: Union[str, List[str]]):
        from ray_tpu.data.grouped_data import GroupedData

        return GroupedData(self, [key] if isinstance(key, str) else list(key))

    def limit(self, n: int) -> "Dataset":
        return Dataset(L.Limit(self._dag, n))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(L.Union([self._dag] + [o._dag for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(L.Zip(self._dag, other._dag))

    # ------------------------------------------------------------------
    # execution / consumption
    # ------------------------------------------------------------------
    def _bundles(self) -> List[X.RefBundle]:
        if self._cached is None:
            from ray_tpu.data.context import DataContext
            from ray_tpu.data._internal.stats import ExecStats

            stats = ExecStats() if DataContext.get_current().enable_stats \
                else None
            self._cached = X.execute(self._plan(), stats=stats)
            self._exec_stats = stats
        return self._cached

    def iter_bundles(self) -> Iterator[X.RefBundle]:
        if self._cached is not None:
            return iter(self._cached)
        from ray_tpu.data.context import DataContext
        from ray_tpu.data._internal.stats import ExecStats

        stats = ExecStats() if DataContext.get_current().enable_stats \
            else None
        self._exec_stats = stats
        return X.execute_streaming(self._plan(), stats=stats)

    def materialize(self) -> "Dataset":
        out = Dataset.from_bundles(self._bundles())
        out._exec_stats = self._exec_stats  # stats survive materialization
        return out

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._bundles())

    def num_blocks(self) -> int:
        return len(self._bundles())

    def size_bytes(self) -> int:
        return sum(m.size_bytes for _, m in self._bundles())

    def schema(self) -> Optional[pa.Schema]:
        for _r, m in self._bundles():
            if m.schema is not None and len(m.schema) > 0:
                return m.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def input_files(self) -> List[str]:
        out = []
        for _r, m in self._bundles():
            out.extend(m.input_files or [])
        return out

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        import ray_tpu

        for ref, _m in self.iter_bundles():
            block = ray_tpu.get(ref)
            for row in BlockAccessor(block).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self, limit: Optional[int] = None) -> List[Any]:
        rows = self.take(limit or 10**12)
        return rows

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "numpy"):
        for b in self.iter_batches(batch_size=batch_size,
                                   batch_format=batch_format):
            return b
        raise ValueError("dataset is empty, cannot take a batch")

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Any]:
        import ray_tpu

        for ref, _m in self.iter_bundles():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: int = 1, **_ignored) -> Iterator[Any]:
        from ray_tpu.data.iterator import iter_batches_over

        return iter_batches_over(
            self.iter_bundles(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            shuffle_buffer_size=local_shuffle_buffer_size,
            shuffle_seed=local_shuffle_seed,
        )

    def iterator(self):
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self)

    def to_pandas(self, limit: Optional[int] = None):
        import ray_tpu

        tables = [ray_tpu.get(r) for r, _ in self._bundles()]
        t = concat_blocks(tables)
        if limit:
            t = t.slice(0, limit)
        return t.to_pandas()

    def to_arrow_refs(self) -> List[Any]:
        return [r for r, _ in self._bundles()]

    def to_numpy_refs(self) -> List[Any]:
        import ray_tpu

        conv = ray_tpu.remote(
            lambda b: BlockAccessor(b).to_batch("numpy")
        )
        return [conv.remote(r) for r, _ in self._bundles()]

    def unique(self, column: str) -> List[Any]:
        import ray_tpu

        vals = set()
        for ref, _m in self.iter_bundles():
            col = ray_tpu.get(ref).column(column)
            vals.update(col.to_pylist())
        return sorted(vals)

    # -- simple aggregates over a column --------------------------------
    def _col_agg(self, on: Optional[str], npfn) -> Any:
        import ray_tpu

        on = on or VALUE_COL
        agg = ray_tpu.remote(
            lambda b, c=on: npfn(np.asarray(b.column(c))) if b.num_rows else None
        )
        parts = [agg.remote(r) for r, _ in self._bundles()]
        vals = [v for v in ray_tpu.get(parts) if v is not None]
        return npfn(np.asarray(vals)) if vals else None

    def sum(self, on: Optional[str] = None):
        import ray_tpu

        on = on or VALUE_COL
        agg = ray_tpu.remote(
            lambda b, c=on: float(np.asarray(b.column(c)).sum()) if b.num_rows else 0.0
        )
        return float(sum(ray_tpu.get([agg.remote(r) for r, _ in self._bundles()])))

    def min(self, on: Optional[str] = None):
        return self._col_agg(on, np.min)

    def max(self, on: Optional[str] = None):
        return self._col_agg(on, np.max)

    def mean(self, on: Optional[str] = None):
        import ray_tpu

        on = on or VALUE_COL
        agg = ray_tpu.remote(
            lambda b, c=on: (float(np.asarray(b.column(c)).sum()), b.num_rows)
        )
        parts = ray_tpu.get([agg.remote(r) for r, _ in self._bundles()])
        total = sum(p[0] for p in parts)
        n = sum(p[1] for p in parts)
        return total / n if n else None

    def std(self, on: Optional[str] = None, ddof: int = 1):
        import ray_tpu

        on = on or VALUE_COL
        vals = []
        for ref, _m in self._bundles():
            vals.append(np.asarray(ray_tpu.get(ref).column(on)))
        allv = np.concatenate(vals) if vals else np.array([])
        return float(np.std(allv, ddof=ddof)) if allv.size else None

    # -- splits ---------------------------------------------------------
    def split(self, n: int, *, equal: bool = False, locality_hints=None
              ) -> List["Dataset"]:
        bundles = self._bundles()
        if equal:
            return self._split_equal(n)
        groups: List[List[X.RefBundle]] = [[] for _ in range(n)]
        for i, b in enumerate(bundles):
            groups[i % n].append(b)
        return [Dataset.from_bundles(g) for g in groups]

    def _split_equal(self, n: int) -> List["Dataset"]:
        import ray_tpu

        total = self.count()
        per = total // n
        splits, acc, need = [], [], per
        it = iter(self._bundles())
        carry = None
        for k in range(n):
            rows_needed = per
            group: List[X.RefBundle] = []
            while rows_needed > 0:
                if carry is not None:
                    ref, meta = carry
                    carry = None
                else:
                    try:
                        ref, meta = next(it)
                    except StopIteration:
                        break
                if meta.num_rows <= rows_needed:
                    group.append((ref, meta))
                    rows_needed -= meta.num_rows
                else:
                    block = ray_tpu.get(ref)
                    head = BlockAccessor(block).slice(0, rows_needed)
                    tail = BlockAccessor(block).slice(rows_needed, meta.num_rows)
                    group.append(
                        (ray_tpu.put(head), BlockMetadata.for_block(head))
                    )
                    carry = (ray_tpu.put(tail), BlockMetadata.for_block(tail))
                    rows_needed = 0
            splits.append(Dataset.from_bundles(group))
        return splits

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        import ray_tpu

        rows = self.take_all()
        bounds = [0] + list(indices) + [len(rows)]
        out = []
        for a, b in itertools.pairwise(bounds):
            chunk = rows[a:b]
            block = rows_to_block(chunk) if chunk else pa.table({})
            out.append(Dataset.from_bundles(
                [(ray_tpu.put(block), BlockMetadata.for_block(block))]
            ))
        return out

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None) -> List["Dataset"]:
        d = self.random_shuffle(seed=seed) if shuffle else self
        n = d.count()
        k = int(n * (1 - test_size))
        mat = d.materialize()
        return mat.split_at_indices([k])

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["Any"]:
        from ray_tpu.data.iterator import build_streaming_split

        return build_streaming_split(self, n, equal=equal)

    # -- writes ---------------------------------------------------------
    def _write(self, writer, path: str, **kw) -> List[str]:
        import ray_tpu

        w = ray_tpu.remote(writer)
        refs = [
            w.remote(r, path, i, **kw)
            for i, (r, _m) in enumerate(self.iter_bundles())
        ]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str, **kw) -> None:
        self._write(ds.write_block_parquet, path, **kw)

    def write_csv(self, path: str, **kw) -> None:
        self._write(ds.write_block_csv, path, **kw)

    def write_json(self, path: str, **kw) -> None:
        self._write(ds.write_block_json, path, **kw)

    def write_numpy(self, path: str, *, column: str = "data", **kw) -> None:
        self._write(ds.write_block_numpy, path, column=column)

    # ------------------------------------------------------------------
    def stats(self) -> str:
        """Per-operator execution stats (ray parity: Dataset.stats() /
        _internal/stats.py DatasetStats summary). Covers both cached
        executions and drained streaming iterations (iter_bundles)."""
        bundles = self._cached
        if bundles is None:
            if self._exec_stats is not None and self._exec_stats.ops:
                return self._exec_stats.summary()
            return "(dataset not yet executed)"
        head = (
            f"Dataset: {len(bundles)} blocks, "
            f"{sum(m.num_rows for _, m in bundles)} rows, "
            f"{sum(m.size_bytes for _, m in bundles)} bytes"
        )
        if self._exec_stats is not None and self._exec_stats.ops:
            return head + "\n" + self._exec_stats.summary()
        return head

    def __repr__(self):
        name = self._dag.name
        if self._cached is not None:
            n = sum(m.num_rows for _, m in self._cached)
            return f"Dataset(op={name}, num_rows={n}, blocks={len(self._cached)})"
        return f"Dataset(op={name}, lazy)"


def _contiguous_split(block: Block, n: int) -> List[Block]:
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    per, rem = divmod(rows, n)
    out, start = [], 0
    for i in range(n):
        step = per + (1 if i < rem else 0)
        out.append(acc.slice(start, start + step))
        start += step
    return out


def _round_robin_split(block: Block, n: int) -> List[Block]:
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    return [acc.take(list(range(i, rows, n))) for i in range(n)]
