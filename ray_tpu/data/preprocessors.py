"""Preprocessors: fit statistics on a Dataset, transform batches.

ray parity: python/ray/data/preprocessors/ — Preprocessor base
(fit/transform/fit_transform/transform_batch), StandardScaler,
MinMaxScaler, LabelEncoder, OneHotEncoder, SimpleImputer, Concatenator,
Chain, BatchMapper. Stats are computed with Dataset aggregations
(distributed) and applied via map_batches; transform_batch applies the
fitted stats to a standalone pandas/dict batch for serving-time use.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    _is_fittable = True

    def __init__(self):
        self.stats_: Optional[dict] = None

    def fit(self, dataset) -> "Preprocessor":
        if self._is_fittable:
            self.stats_ = self._fit(dataset)
        return self

    def transform(self, dataset):
        if self._is_fittable and self.stats_ is None:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit before transform"
            )
        return dataset.map_batches(self._transform_batch, batch_format="pandas")

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def transform_batch(self, batch):
        """Apply to a standalone batch (pandas DataFrame or dict of
        arrays) — the serving-time path."""
        import pandas as pd

        if isinstance(batch, dict):
            return self._transform_batch(pd.DataFrame(batch))
        return self._transform_batch(batch)

    # subclass hooks
    def _fit(self, dataset) -> dict:
        raise NotImplementedError

    def _transform_batch(self, df):
        raise NotImplementedError


def _col_stats(dataset, columns: List[str], fns: List[str]) -> Dict[str, dict]:
    """One pass of per-column aggregates via pandas on each block."""

    def agg_batch(df):
        import pandas as pd

        out = {}
        for col in columns:
            s = df[col].dropna()
            out[f"{col}__count"] = [len(s)]
            out[f"{col}__sum"] = [float(s.sum()) if len(s) else 0.0]
            out[f"{col}__sumsq"] = [float((s.astype(float) ** 2).sum()) if len(s) else 0.0]
            out[f"{col}__min"] = [float(s.min()) if len(s) else np.inf]
            out[f"{col}__max"] = [float(s.max()) if len(s) else -np.inf]
        return pd.DataFrame(out)

    parts = dataset.map_batches(agg_batch, batch_format="pandas").to_pandas()
    stats: Dict[str, dict] = {}
    for col in columns:
        count = parts[f"{col}__count"].sum()
        total = parts[f"{col}__sum"].sum()
        sumsq = parts[f"{col}__sumsq"].sum()
        mean = total / count if count else 0.0
        var = max(sumsq / count - mean * mean, 0.0) if count else 0.0
        stats[col] = {
            "count": int(count),
            "mean": mean,
            "std": float(np.sqrt(var)),
            "min": float(parts[f"{col}__min"].min()),
            "max": float(parts[f"{col}__max"].max()),
        }
    return stats


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (ray: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, dataset):
        return _col_stats(dataset, self.columns, ["mean", "std"])

    def _transform_batch(self, df):
        df = df.copy()
        for col in self.columns:
            s = self.stats_[col]
            std = s["std"] or 1.0
            df[col] = (df[col] - s["mean"]) / std
        return df


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, dataset):
        return _col_stats(dataset, self.columns, ["min", "max"])

    def _transform_batch(self, df):
        df = df.copy()
        for col in self.columns:
            s = self.stats_[col]
            span = (s["max"] - s["min"]) or 1.0
            df[col] = (df[col] - s["min"]) / span
        return df


def _unique_values(dataset, columns: List[str]) -> Dict[str, list]:
    def uniq_batch(df):
        import pandas as pd

        return pd.DataFrame({
            col: [sorted(df[col].dropna().unique().tolist())]
            for col in columns
        })

    parts = dataset.map_batches(uniq_batch, batch_format="pandas").to_pandas()
    return {
        col: sorted({v for row in parts[col] for v in row})
        for col in columns
    }


class LabelEncoder(Preprocessor):
    """Map a label column to contiguous ints (ray: preprocessors/encoder.py)."""

    def __init__(self, label_column: str):
        super().__init__()
        self.label_column = label_column

    def _fit(self, dataset):
        values = _unique_values(dataset, [self.label_column])[self.label_column]
        return {"mapping": {v: i for i, v in enumerate(values)}}

    def _transform_batch(self, df):
        df = df.copy()
        df[self.label_column] = df[self.label_column].map(self.stats_["mapping"])
        return df


class OneHotEncoder(Preprocessor):
    """Expand categorical columns into 0/1 indicator columns."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, dataset):
        return {"values": _unique_values(dataset, self.columns)}

    def _transform_batch(self, df):
        df = df.copy()
        for col in self.columns:
            for v in self.stats_["values"][col]:
                df[f"{col}_{v}"] = (df[col] == v).astype(np.int8)
            df = df.drop(columns=[col])
        return df


class SimpleImputer(Preprocessor):
    """Fill missing values with mean ("mean") or a constant."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value=None):
        super().__init__()
        if strategy not in ("mean", "constant"):
            raise ValueError("strategy must be 'mean' or 'constant'")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value

    def _fit(self, dataset):
        if self.strategy == "constant":
            return {"fill": {c: self.fill_value for c in self.columns}}
        stats = _col_stats(dataset, self.columns, ["mean"])
        return {"fill": {c: stats[c]["mean"] for c in self.columns}}

    def _transform_batch(self, df):
        df = df.copy()
        for col in self.columns:
            df[col] = df[col].fillna(self.stats_["fill"][col])
        return df


class Concatenator(Preprocessor):
    """Concatenate numeric columns into one vector column."""

    _is_fittable = False

    def __init__(self, columns: List[str], output_column_name: str = "concat"):
        super().__init__()
        self.columns = list(columns)
        self.output_column_name = output_column_name

    def _transform_batch(self, df):
        df = df.copy()
        stacked = np.stack([df[c].to_numpy() for c in self.columns], axis=1)
        df = df.drop(columns=self.columns)
        df[self.output_column_name] = list(stacked)
        return df


class BatchMapper(Preprocessor):
    """Arbitrary per-batch function as a preprocessor."""

    _is_fittable = False

    def __init__(self, fn: Callable, batch_format: str = "pandas"):
        super().__init__()
        self.fn = fn
        self.batch_format = batch_format

    def _transform_batch(self, df):
        return self.fn(df)


class Chain(Preprocessor):
    """Apply preprocessors in sequence; fit propagates transformed data."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)

    def fit(self, dataset):
        for p in self.preprocessors:
            dataset = p.fit_transform(dataset)
        self.stats_ = {"fitted": True}
        return self

    def transform(self, dataset):
        for p in self.preprocessors:
            dataset = p.transform(dataset)
        return dataset

    def fit_transform(self, dataset):
        self.fit(dataset)  # fitting already transforms stepwise
        for p in self.preprocessors:
            dataset = p.transform(dataset)
        return dataset

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
