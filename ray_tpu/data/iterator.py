"""DataIterator + streaming split.

Reference parity: ray python/ray/data/iterator.py (iter_batches formats,
local shuffle buffer) and _internal/execution/operators/output_splitter.py
(streaming_split coordinator feeding Train workers).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import BlockAccessor, VALUE_COL, concat_blocks


def _emit(table: pa.Table, batch_format: str):
    acc = BlockAccessor(table)
    return acc.to_batch(batch_format)


def iter_batches_over(bundles, *, batch_size: Optional[int],
                      batch_format: str = "numpy",
                      drop_last: bool = False,
                      shuffle_buffer_size: Optional[int] = None,
                      shuffle_seed: Optional[int] = None) -> Iterator[Any]:
    """Re-batch a stream of (ref, meta) into fixed-size batches, carrying
    remainders across block boundaries (the reference's batcher)."""
    import ray_tpu

    rng = np.random.default_rng(shuffle_seed)
    carry: List[pa.Table] = []
    carry_rows = 0

    def blocks():
        for ref, _m in bundles:
            b = ray_tpu.get(ref)
            if b.num_rows:
                yield b

    source = blocks()
    if shuffle_buffer_size:
        def shuffled(src):
            for b in src:
                perm = rng.permutation(b.num_rows)
                yield BlockAccessor(b).take(list(perm))
        source = shuffled(source)

    if batch_size is None:
        for b in source:
            yield _emit(b, batch_format)
        return

    for block in source:
        carry.append(block)
        carry_rows += block.num_rows
        while carry_rows >= batch_size:
            merged = concat_blocks(carry)
            head = merged.slice(0, batch_size)
            tail = merged.slice(batch_size)
            yield _emit(head, batch_format)
            carry = [tail] if tail.num_rows else []
            carry_rows = tail.num_rows
    if carry_rows and not drop_last:
        yield _emit(concat_blocks(carry), batch_format)


class DataIterator:
    """Iteration facade handed to Train workers (ray parity:
    DataIterator / iterator.py)."""

    def __init__(self, source):
        self._source = source  # Dataset or _SplitStream

    def _bundles(self):
        if hasattr(self._source, "iter_bundles"):
            return self._source.iter_bundles()
        return iter(self._source)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: int = 1, **_ignored) -> Iterator[Any]:
        return iter_batches_over(
            self._bundles(), batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last,
            shuffle_buffer_size=local_shuffle_buffer_size,
            shuffle_seed=local_shuffle_seed,
        )

    def _iter_mapped_batches(self, convert, *, batch_size, **kwargs):
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            if isinstance(batch, dict):
                yield {k: convert(k, v) for k, v in batch.items()}
            else:
                yield convert(None, batch)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: Optional[str] = None,
                           **kwargs) -> Iterator[Any]:
        """Batches as torch tensors (ray parity: iter_torch_batches) —
        dict of tensors for tabular data, a single tensor for simple
        blocks. ``dtypes``: torch dtype or {column: dtype}."""
        import numpy as np
        import torch

        def convert(col, arr):
            arr = np.asarray(arr)
            if not arr.flags.writeable:
                # zero-copy Arrow view: a tensor sharing it would make
                # in-place train-loop ops corrupt the block store
                arr = arr.copy()
            t = torch.as_tensor(arr)
            want = dtypes.get(col) if isinstance(dtypes, dict) else dtypes
            if want is not None:
                t = t.to(want)
            if device:
                t = t.to(device)
            return t

        return self._iter_mapped_batches(convert, batch_size=batch_size,
                                         **kwargs)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, **kwargs) -> Iterator[Any]:
        """Batches as jax arrays, optionally placed with a Sharding —
        the TPU-native analog of iter_torch_batches: pass the mesh's data
        sharding so host->device transfer lands batches already laid out
        for the pjit step (no per-step device_put in the train loop).

        With a sharding, ``drop_last`` defaults to True: a partial final
        batch cannot be laid out over a fixed device axis (device_put
        would fail on the non-divisible batch dim). Pass drop_last=False
        explicitly only with shardings that admit ragged batch sizes.
        """
        import jax

        if sharding is not None:
            kwargs.setdefault("drop_last", True)

        def place(_col, arr):
            if sharding is not None:
                return jax.device_put(arr, sharding)
            return jax.numpy.asarray(arr)

        return self._iter_mapped_batches(place, batch_size=batch_size,
                                         **kwargs)

    def iter_rows(self) -> Iterator[Any]:
        import ray_tpu

        for ref, _m in self._bundles():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def materialize(self):
        from ray_tpu.data.dataset import Dataset

        return Dataset.from_bundles(list(self._bundles()))


class _SplitCoordinator:
    """Actor: executes the dataset and hands out blocks to n consumers on
    demand. Re-executes the dataset for every epoch — a consumer that
    starts iterating again (epoch e+1) triggers a fresh pump once the
    previous epoch is fully drained, matching the reference's per-epoch
    streaming_split semantics. ``equal=True`` gives every consumer exactly
    the same row count (boundary blocks are sliced)."""

    def __init__(self, dataset, n: int, equal: bool):
        self._dataset = dataset
        self._n = n
        self._equal = equal
        self._queues = [collections.deque() for _ in range(n)]
        self._epoch = 0
        self._done = False
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self):
        try:
            if self._equal:
                splits = self._dataset.split(self._n, equal=True)
                for i, part in enumerate(splits):
                    for item in part.iter_bundles():
                        with self._cv:
                            self._queues[i].append(item)
                            self._cv.notify_all()
            else:
                i = 0
                for item in self._dataset.iter_bundles():
                    with self._cv:
                        self._queues[i % self._n].append(item)
                        i += 1
                        self._cv.notify_all()
        finally:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    def next(self, consumer: int, epoch: int):
        """Next (ref, meta) of ``epoch`` for this consumer; None at the
        epoch's end. Asking for a later epoch restarts execution once the
        current epoch is drained."""
        with self._cv:
            while True:
                if epoch < self._epoch:
                    return None  # that epoch is over
                if epoch == self._epoch:
                    if self._queues[consumer]:
                        return self._queues[consumer].popleft()
                    if self._done:
                        return None
                else:  # epoch > self._epoch: previous epoch must finish
                    # a consumer moving on abandons its own leftovers
                    # (early break mid-epoch must not deadlock the advance)
                    self._queues[consumer].clear()
                    if self._done and not any(self._queues):
                        self._epoch = epoch
                        self._done = False
                        self._thread = threading.Thread(
                            target=self._pump, daemon=True
                        )
                        self._thread.start()
                        continue
                self._cv.wait(timeout=1.0)


class _SplitStream:
    """Iterable over one consumer's share of a streaming split. Each
    ``iter()`` is one epoch: the coordinator re-runs the dataset."""

    def __init__(self, coordinator, idx: int):
        self._coord = coordinator
        self._idx = idx
        self._epoch = -1

    def __iter__(self):
        import ray_tpu

        self._epoch += 1
        while True:
            item = ray_tpu.get(
                self._coord.next.remote(self._idx, self._epoch)
            )
            if item is None:
                return
            yield item


def build_streaming_split(dataset, n: int, *, equal: bool = False
                          ) -> List[DataIterator]:
    import ray_tpu

    coord_cls = ray_tpu.remote(num_cpus=0)(_SplitCoordinator)
    coord = coord_cls.remote(dataset, n, equal)
    return [DataIterator(_SplitStream(coord, i)) for i in range(n)]
