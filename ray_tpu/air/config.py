"""AIR configs (ray parity: python/ray/air/config.py:93,526,577,707).

ScalingConfig's TPU delta: the unit of a "worker" is a HOST owning all its
local chips (libtpu single-client constraint, SURVEY §7) — so
``use_tpu + chips_per_worker`` replaces the reference's one-GPU-per-worker
model, and ``topology`` requests a specific slice shape for gang scheduling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False  # accepted for API parity; mapped to nothing on TPU
    chips_per_worker: Optional[int] = None  # TPU chips each host-worker owns
    topology: Optional[str] = None  # e.g. "v5e-8": slice request label
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if "CPU" not in res:
            res["CPU"] = 1.0
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = float(self.chips_per_worker or 1)
        return res

    def as_placement_group_bundles(self) -> List[Dict[str, float]]:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    max_failures: int = 0
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    log_to_file: bool = False
    callbacks: Optional[list] = None
