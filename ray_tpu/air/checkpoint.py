"""Checkpoint abstraction (ray parity: python/ray/air/checkpoint.py:66 and
the file-based train/_checkpoint.py:30).

A Checkpoint is a directory (canonical form) or an in-memory dict that
morphs to/from a directory. JAX pytrees checkpoint via orbax when available
(msgpack fallback), so trainer state is TPU-native (sharded-array-aware)
rather than torch-pickled.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "checkpoint_dict.pkl"


class Checkpoint:
    def __init__(self, path: Optional[str] = None,
                 _data: Optional[Dict[str, Any]] = None):
        self._path = path
        self._data = _data

    # -- constructors ---------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(_data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=path)

    # -- accessors ------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        f = os.path.join(self._path, _DICT_FILE)
        if os.path.exists(f):
            with open(f, "rb") as fh:
                return pickle.load(fh)
        raise ValueError(f"checkpoint at {self._path} has no dict payload")

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(tempfile.gettempdir(),
                                    f"rt_ckpt_{uuid.uuid4().hex[:8]}")
        os.makedirs(path, exist_ok=True)
        if self._path is not None and os.path.abspath(self._path) != os.path.abspath(path):
            for item in os.listdir(self._path):
                src = os.path.join(self._path, item)
                dst = os.path.join(path, item)
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True)
                else:
                    shutil.copy2(src, dst)
        if self._data is not None:
            with open(os.path.join(path, _DICT_FILE), "wb") as fh:
                pickle.dump(self._data, fh, protocol=5)
        return path

    def as_directory(self):
        """Context manager yielding a directory view."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            if self._path is not None and self._data is None:
                yield self._path
            else:
                tmp = self.to_directory()
                try:
                    yield tmp
                finally:
                    shutil.rmtree(tmp, ignore_errors=True)

        return _cm()

    @property
    def path(self) -> Optional[str]:
        return self._path

    def __repr__(self):
        return f"Checkpoint(path={self._path!r}, in_memory={self._data is not None})"


def save_pytree(tree, directory: str, name: str = "params"):
    """Checkpoint a JAX pytree (orbax if available, msgpack fallback)."""
    os.makedirs(directory, exist_ok=True)
    target = os.path.join(directory, name)
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(target) + "_orbax", tree, force=True)
        ckptr.wait_until_finished()
        return
    except Exception:
        pass
    from flax import serialization

    with open(target + ".msgpack", "wb") as f:
        f.write(serialization.to_bytes(tree))


def load_pytree(directory: str, target, name: str = "params"):
    path = os.path.join(directory, name)
    orbax_path = os.path.abspath(path) + "_orbax"
    if os.path.exists(orbax_path):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(orbax_path, target)
    from flax import serialization

    with open(path + ".msgpack", "rb") as f:
        return serialization.from_bytes(target, f.read())
