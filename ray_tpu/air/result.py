"""Result (ray parity: python/ray/air/result.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: Optional[str] = None
    metrics_dataframe: Any = None
    best_checkpoints: List = field(default_factory=list)

    @property
    def config(self):
        return (self.metrics or {}).get("config")
