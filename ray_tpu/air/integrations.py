"""AIR experiment-tracking integrations: MLflow and Weights & Biases.

Reference parity: ray python/ray/air/integrations/mlflow.py
(MLflowLoggerCallback / setup_mlflow) and wandb.py (WandbLoggerCallback /
setup_wandb). Each callback mirrors Tune trial lifecycle into the
tracking backend: one run per trial, metrics on every report, params at
start, terminal status at completion. Imports are lazy and validated at
CONSTRUCTION so a missing client library fails loudly up front instead
of silently dropping experiment history mid-run.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.logger import Callback, _flatten


def _numeric_only(result: Dict) -> Dict[str, float]:
    out = {}
    for k, v in _flatten(result).items():
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[k] = float(v)
    return out


class MLflowLoggerCallback(Callback):
    """Logs each trial as an MLflow run (ray parity:
    air/integrations/mlflow.py MLflowLoggerCallback)."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: str = "ray_tpu",
                 tags: Optional[Dict[str, Any]] = None,
                 save_artifact: bool = False):
        try:
            import mlflow  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "MLflowLoggerCallback requires the `mlflow` package"
            ) from e
        self._tracking_uri = tracking_uri
        self._experiment_name = experiment_name
        self._tags = dict(tags or {})
        self._save_artifact = save_artifact
        self._runs: Dict[str, Any] = {}  # trial_id -> mlflow run_id
        self._client_obj = None

    def _client(self):
        # one client for the experiment (construction already validated
        # the import); rebuilding per report would reset the global
        # tracking URI on the controller hot path
        if self._client_obj is None:
            import mlflow

            if self._tracking_uri:
                mlflow.set_tracking_uri(self._tracking_uri)
            from mlflow.tracking import MlflowClient

            self._client_obj = MlflowClient(tracking_uri=self._tracking_uri)
        return self._client_obj

    def on_trial_start(self, trial):
        client = self._client()
        exp = client.get_experiment_by_name(self._experiment_name)
        exp_id = exp.experiment_id if exp else client.create_experiment(
            self._experiment_name
        )
        run = client.create_run(
            exp_id, tags={**self._tags, "trial_name": str(trial)},
        )
        self._runs[trial.trial_id] = run.info.run_id
        for k, v in _flatten(trial.config or {}).items():
            try:
                client.log_param(run.info.run_id, k, v)
            except Exception:
                pass  # non-stringable param: tracking is best-effort

    def on_trial_result(self, trial, result: Dict):
        run_id = self._runs.get(trial.trial_id)
        if run_id is None:
            return
        client = self._client()
        step = int(result.get("training_iteration", 0))
        for k, v in _numeric_only(result).items():
            client.log_metric(run_id, k, v, step=step)

    def _finish(self, trial, status: str):
        run_id = self._runs.pop(trial.trial_id, None)
        if run_id is None:
            return
        client = self._client()
        trial_dir = getattr(trial, "local_path", None) or getattr(
            trial, "local_dir", None
        )
        if self._save_artifact and trial_dir:
            try:
                client.log_artifacts(run_id, trial_dir)
            except Exception:
                pass
        client.set_terminated(run_id, status=status)

    def on_trial_complete(self, trial):
        self._finish(trial, "FINISHED")

    def on_trial_error(self, trial):
        self._finish(trial, "FAILED")


class WandbLoggerCallback(Callback):
    """Logs each trial as a W&B run (ray parity:
    air/integrations/wandb.py WandbLoggerCallback)."""

    def __init__(self, project: str = "ray_tpu",
                 group: Optional[str] = None, **init_kwargs):
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package"
            ) from e
        self._project = project
        self._group = group
        self._init_kwargs = init_kwargs
        self._runs: Dict[str, Any] = {}

    def on_trial_start(self, trial):
        import wandb

        # reinit="create_new": independent concurrent Run objects, one
        # per live trial — plain reinit=True would FINISH the previous
        # trial's run while it is still reporting (Tune runs trials
        # concurrently; the reference isolates runs in subprocesses for
        # the same reason)
        self._runs[trial.trial_id] = wandb.init(
            project=self._project, group=self._group,
            name=str(trial), config=dict(trial.config or {}),
            reinit="create_new", **self._init_kwargs,
        )

    def on_trial_result(self, trial, result: Dict):
        run = self._runs.get(trial.trial_id)
        if run is not None:
            run.log(_numeric_only(result))

    def _finish(self, trial, exit_code: int):
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish(exit_code=exit_code)

    def on_trial_complete(self, trial):
        self._finish(trial, 0)

    def on_trial_error(self, trial):
        self._finish(trial, 1)
