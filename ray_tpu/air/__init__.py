from ray_tpu.air.checkpoint import Checkpoint, load_pytree, save_pytree
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.train.session import get_checkpoint, get_context, report

# ray parity: ray.air.session.report etc (air/session.py)
class session:  # noqa: N801 — module-style namespace for parity
    report = staticmethod(report)
    get_checkpoint = staticmethod(get_checkpoint)
    get_context = staticmethod(get_context)


__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "load_pytree",
    "save_pytree",
    "session",
]
