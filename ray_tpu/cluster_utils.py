"""Multi-node-on-one-host test cluster.

ray parity: python/ray/cluster_utils.py:99 Cluster — N raylets (separate
processes, separate shm stores) sharing one GCS, so scheduling/spillback/
fault-tolerance tests exercise real multi-node semantics on one machine
(ray: cluster_utils.py add_node:165, remove_node:238).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ray_tpu._private.node import NodeProcesses


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.head: Optional[NodeProcesses] = None
        self.workers: list = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def address(self) -> str:
        return self.head.address

    def add_node(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> NodeProcesses:
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            res["TPU"] = float(num_tpus)
        if self.head is None:
            self.head = NodeProcesses(head=True, resources=res or None, labels=labels)
            return self.head
        node = NodeProcesses(
            head=False,
            gcs_port=self.head.gcs_port,
            session_dir=self.head.session_dir,
            resources=res or None,
            labels=labels,
        )
        self.workers.append(node)
        return node

    def remove_node(self, node: NodeProcesses, graceful: bool = False):
        node.kill_raylet(graceful=graceful)
        if node in self.workers:
            self.workers.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0):
        from ray_tpu._private.rpcio import EventLoopThread, connect

        expected = 1 + len(self.workers)
        io = EventLoopThread("cluster-wait")
        try:
            conn = io.run(connect("127.0.0.1", self.head.gcs_port))
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                nodes = io.run(conn.request("get_nodes", {}))
                if sum(1 for n in nodes if n["alive"]) >= expected:
                    io.run(conn.close())
                    return
                time.sleep(0.1)
            raise TimeoutError(f"cluster did not reach {expected} nodes")
        finally:
            io.stop()

    def shutdown(self):
        for w in self.workers:
            w.shutdown()
        if self.head is not None:
            self.head.shutdown()
        self.workers = []
        self.head = None
