from ray_tpu.parallel.collectives import (
    all_gather,
    chunked_psum,
    compiled_allreduce,
    pmean,
    ppermute_next,
    psum,
    quantized_psum,
    reduce_scatter,
)
from ray_tpu.parallel.mesh_utils import (
    auto_mesh,
    create_hybrid_mesh,
    create_mesh,
    data_sharding,
    logical_to_physical,
    mesh_from_cluster,
    replicated,
    shard_params_fsdp,
)

__all__ = [
    "all_gather",
    "auto_mesh",
    "chunked_psum",
    "compiled_allreduce",
    "quantized_psum",
    "create_hybrid_mesh",
    "create_mesh",
    "data_sharding",
    "logical_to_physical",
    "mesh_from_cluster",
    "pmean",
    "ppermute_next",
    "psum",
    "reduce_scatter",
    "replicated",
    "shard_params_fsdp",
]
