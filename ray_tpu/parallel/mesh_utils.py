"""Device-mesh construction and sharding helpers — the TPU device plane.

This is the layer the reference delegates to NCCL/torch-dist for
(ray: python/ray/train/torch/config.py:69 _setup_torch_process_group,
python/ray/util/collective/collective_group/nccl_collective_group.py).
TPU-native, the device plane is a `jax.sharding.Mesh` over the pod's chips:
axes name parallelism strategies (data/fsdp/model/seq), shardings are
`NamedSharding`s, and collectives are XLA ops (`psum`/`all_gather`/
`ppermute`) inserted by the compiler and lowered onto ICI rings.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis order: data-like axes outermost (ride DCN / slower links),
# model-like innermost (ride ICI nearest-neighbor links).
AXIS_ORDER = ("data", "fsdp", "pipeline", "seq", "expert", "model")


def _ordered_axis_names(axes: Dict[str, int]) -> List[str]:
    """Canonical axis order (AXIS_ORDER first, unknown axes after) — the
    single source of truth shared by flat and hybrid mesh construction."""
    names = [a for a in AXIS_ORDER if a in axes]
    names += [a for a in axes if a not in names]
    return names


def create_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence] = None,
    allow_split_physical_axes: bool = True,
) -> Mesh:
    """Build a Mesh with named axes from ``axes`` (e.g. {"data": 4, "model": 2}).

    Uses ``jax.experimental.mesh_utils.create_device_mesh`` when the full
    device set is used so the logical mesh is laid out along physical ICI
    topology; falls back to a reshape for partial device sets.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = _ordered_axis_names(axes)
    sizes = [axes[a] for a in names]
    total = math.prod(sizes)
    if total > len(devices):
        raise ValueError(f"mesh {axes} needs {total} devices, have {len(devices)}")
    use = devices[:total]
    if len(use) == len(jax.devices()):
        try:
            from jax.experimental import mesh_utils as jmu

            dev_array = jmu.create_device_mesh(sizes, devices=np.array(use))
            return Mesh(dev_array, names)
        except Exception:
            pass
    dev_array = np.array(use).reshape(sizes)
    return Mesh(dev_array, names)


def auto_mesh(
    n_devices: Optional[int] = None,
    data: int = -1,
    model: int = 1,
    fsdp: int = 1,
    pipeline: int = 1,
    seq: int = 1,
    expert: int = 1,
) -> Mesh:
    """Mesh with one wildcard axis (-1) absorbing the remaining devices."""
    n = n_devices if n_devices is not None else len(jax.devices())
    axes = {"data": data, "fsdp": fsdp, "pipeline": pipeline, "seq": seq,
            "expert": expert, "model": model}
    fixed = math.prod(v for v in axes.values() if v > 0)
    wild = [k for k, v in axes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("only one axis may be -1")
    if wild:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes {fixed}")
        axes[wild[0]] = n // fixed
    axes = {k: v for k, v in axes.items() if v > 1 or k == "data"}
    return create_mesh(axes, devices=jax.devices()[:n])


def _slice_groups(devices: Sequence, n_ici: int) -> List[List]:
    """Group devices into slices. Real TPU multi-slice devices carry
    ``slice_index``; multi-process CPU/TPU fall back to ``process_index``;
    a single-process virtual mesh (tests, dryrun) carves contiguous blocks
    of ``n_ici`` devices as virtual slices — contiguity mirrors how real
    slices are enumerated (all of slice 0's chips, then slice 1's)."""
    keys = [getattr(d, "slice_index", None) for d in devices]
    if any(k is None for k in keys):
        keys = [d.process_index for d in devices]
    if len(set(keys)) == 1:
        return [list(devices[i:i + n_ici])
                for i in range(0, len(devices), n_ici)], True
    groups: Dict[int, List] = {}
    for d, k in zip(devices, keys):
        groups.setdefault(k, []).append(d)
    return [groups[k] for k in sorted(groups)], False


def create_hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Dict[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Two-level mesh for multi-slice TPU pods (the v5e-256 shape): the
    ``dcn_axes`` span SLICES — collectives on them cross the data-center
    network — while ``ici_axes`` live WITHIN a slice and ride its ICI
    torus. Axis order puts dcn axes outermost, so the canonical layout
    ``create_hybrid_mesh({"fsdp": 4}, {"data": 2})`` runs data parallelism
    between slices (one gradient allreduce per step over DCN, bandwidth-
    tolerant) and keeps the chatty FSDP all-gathers on ICI.

    TPU-native replacement for the reference's NCCL rail-aware process
    groups (ray parity: python/ray/train/torch/config.py:69 pins NCCL
    rings to hosts; here XLA lowers each axis's collectives onto the
    interconnect the axis maps to). Uses
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` when real
    slice indices exist; for virtual/CPU meshes it groups devices by
    process (or contiguous blocks in-process) so multi-slice programs are
    testable without pod hardware.
    """
    devices = list(devices if devices is not None else jax.devices())
    ici_names = _ordered_axis_names(ici_axes)
    dcn_names = _ordered_axis_names(dcn_axes)
    overlap = set(ici_names) & set(dcn_names)
    if overlap:
        raise ValueError(f"axes {sorted(overlap)} appear in both levels")
    ici_sizes = [ici_axes[a] for a in ici_names]
    dcn_sizes = [dcn_axes[a] for a in dcn_names]
    n_ici = math.prod(ici_sizes)
    n_dcn = math.prod(dcn_sizes)
    if n_ici * n_dcn > len(devices):
        raise ValueError(
            f"hybrid mesh {dcn_axes}x{ici_axes} needs {n_ici * n_dcn} "
            f"devices, have {len(devices)}"
        )
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        try:
            from jax.experimental import mesh_utils as jmu

            dev_array = jmu.create_hybrid_device_mesh(
                ici_sizes, dcn_sizes, devices=devices,
                allow_split_physical_axes=True,
            )
            # jax returns shape dcn+ici with dcn outermost already
            return Mesh(dev_array, tuple(dcn_names) + tuple(ici_names))
        except Exception:
            pass
    groups, virtual = _slice_groups(devices, n_ici)
    if len(groups) < n_dcn:
        raise ValueError(
            f"need {n_dcn} slices for dcn axes {dcn_axes}, found "
            f"{len(groups)} device groups"
        )
    if len(groups) > n_dcn and not virtual:
        # In multi-controller JAX every process must own addressable
        # shards of the mesh it computes over; silently dropping surplus
        # slices/processes would strand them with an opaque "no
        # addressable devices" failure far from here. (Single-process
        # virtual carving may subset — same convention as create_mesh.)
        raise ValueError(
            f"dcn axes {dcn_axes} cover {n_dcn} slices but the device set "
            f"spans {len(groups)}; pass an explicit `devices=` subset or "
            f"widen the dcn axes"
        )
    blocks = []
    for g in groups[:n_dcn]:
        if len(g) < n_ici:
            raise ValueError(
                f"slice has {len(g)} devices, ici axes {ici_axes} need "
                f"{n_ici}"
            )
        blocks.append(np.array(g[:n_ici]).reshape(ici_sizes))
    dev_array = np.stack(blocks).reshape(dcn_sizes + ici_sizes)
    return Mesh(dev_array, tuple(dcn_names) + tuple(ici_names))


def data_sharding(mesh: Mesh, *data_axes: str) -> NamedSharding:
    """Sharding for a batch: leading dim split over data-like axes; replicated
    if the mesh has no data-like axis."""
    axes = data_axes or tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    if not axes:
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh, PartitionSpec(axes if len(axes) > 1 else axes[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def logical_to_physical(
    logical_axes: Tuple[Optional[str], ...],
    rules: Dict[str, Optional[str]],
) -> PartitionSpec:
    """Map logical array axes to mesh axes via sharding rules
    (the scaling-book recipe: annotate logically, map with one rule table)."""
    return PartitionSpec(*(rules.get(a) if a else None for a in logical_axes))


def shard_params_fsdp(params, mesh: Mesh, min_size: int = 2**16):
    """ZeRO-3-style parameter sharding: shard the largest dim of each big
    param over the fsdp axis, replicate small ones. Native equivalent of the
    reference's FSDP pass-through (ray: train/torch/train_loop_utils.py:101).
    """
    if "fsdp" not in mesh.axis_names:
        return jax.tree.map(lambda _: replicated(mesh), params)
    n_shard = mesh.shape["fsdp"]

    def spec_for(x):
        if x.size < min_size:
            return replicated(mesh)
        # Shard the largest divisible dimension.
        dims = sorted(range(x.ndim), key=lambda d: -x.shape[d])
        for d in dims:
            if x.shape[d] % n_shard == 0:
                spec = [None] * x.ndim
                spec[d] = "fsdp"
                return NamedSharding(mesh, PartitionSpec(*spec))
        return replicated(mesh)

    return jax.tree.map(spec_for, params)


def mesh_from_cluster(nodes: List[dict], axes: Dict[str, int]) -> Mesh:
    """Construct a mesh from GCS node-table entries (multi-host path): the
    caller must already have run ``jax.distributed.initialize`` so
    jax.devices() spans all hosts; nodes provide slice/topology labels used
    only for validation."""
    return create_mesh(axes)
