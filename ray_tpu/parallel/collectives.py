"""In-graph XLA collectives over a mesh — the ICI fast path.

These are the operations the reference obtains from NCCL
(ray: python/ray/util/collective/collective_group/nccl_collective_group.py);
TPU-native they are XLA ops inside `shard_map`/`pjit`, compiled onto ICI
rings by the partitioner. Use these inside jitted step functions; the
out-of-graph API (ray_tpu.util.collective) is for orchestration-sized data.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def shard_map_norep(body, *, mesh, in_specs, out_specs):
    """shard_map with the output-replication check disabled — the kwarg was
    renamed check_rep -> check_vma across jax versions; every call site
    shares this shim instead of hand-rolling the try/except."""
    try:
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def psum(x, axis: str):
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return jax.lax.pmean(x, axis_name=axis)


def all_gather(x, axis: str, *, axis_index: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name=axis, axis=axis_index, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    return jax.lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_axis,
                                tiled=True)


def ppermute_next(x, axis: str, mesh: Mesh):
    """Rotate shards to the next rank on the axis ring (ring-attention step)."""
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def compiled_allreduce(mesh: Mesh, axis: str = "data", dtype=jnp.float32):
    """Build a jitted allreduce over one mesh axis: the benchmarkable unit
    for ICI allreduce scaling (north-star metric #2). Input is sharded over
    ``axis``; output is the full psum on every shard."""
    in_spec = PartitionSpec(axis)
    out_spec = PartitionSpec(axis)

    def _body(x):
        return jax.lax.psum(x, axis_name=axis)

    fn = shard_map(_body, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)
    return jax.jit(
        fn,
        in_shardings=NamedSharding(mesh, in_spec),
        out_shardings=NamedSharding(mesh, out_spec),
    )
