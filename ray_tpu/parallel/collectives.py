"""In-graph XLA collectives over a mesh — the ICI fast path.

These are the operations the reference obtains from NCCL
(ray: python/ray/util/collective/collective_group/nccl_collective_group.py);
TPU-native they are XLA ops inside `shard_map`/`pjit`, compiled onto ICI
rings by the partitioner. Use these inside jitted step functions; the
out-of-graph API (ray_tpu.util.collective) is for orchestration-sized data.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def shard_map_norep(body, *, mesh, in_specs, out_specs):
    """shard_map with the output-replication check disabled — the kwarg was
    renamed check_rep -> check_vma across jax versions; every call site
    shares this shim instead of hand-rolling the try/except."""
    try:
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def psum(x, axis: str):
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return jax.lax.pmean(x, axis_name=axis)


def all_gather(x, axis: str, *, axis_index: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name=axis, axis=axis_index, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    return jax.lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_axis,
                                tiled=True)


def chunked_psum(x, axis: str, *, chunks: int = 4):
    """psum issued as ``chunks`` independent collectives over equal slices
    of the flattened operand — the in-graph twin of the store backend's
    chunked allreduce. Splitting the reduction lets XLA's latency-hiding
    scheduler start moving chunk 0 while upstream compute producing later
    chunks is still running, instead of waiting for one fused op's full
    operand. For tensors smaller than ``chunks`` elements (or chunks<=1)
    this degenerates to a plain psum."""
    if chunks <= 1 or x.size < chunks:
        return jax.lax.psum(x, axis_name=axis)
    flat = x.reshape(-1)
    pad = (-flat.size) % chunks
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    parts = jnp.split(flat, chunks)
    out = jnp.concatenate([jax.lax.psum(p, axis_name=axis) for p in parts])
    if pad:
        out = out[: x.size]
    return out.reshape(x.shape)


def quantized_psum(x, axis: str, *, mean: bool = False):
    """EQuARX-style int8 allreduce inside the graph: each shard block-
    quantizes its contribution (symmetric, scale = max|x|/127), int8 wire
    rides the all_gather, and every shard dequantizes + sums locally — so
    the cross-ICI bytes drop ~4x for fp32 at the cost of one rounding per
    contribution. Matches the store backend's ``quant="int8"`` semantics:
    SUM (or MEAN with ``mean=True``) only; result is float32."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf))
    # zero-safe: all-zero block keeps scale 1 so dequant stays exact zeros
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    qs = jax.lax.all_gather(q, axis_name=axis)          # [W, ...] int8
    scales = jax.lax.all_gather(scale, axis_name=axis)  # [W]
    deq = qs.astype(jnp.float32) * scales.reshape((-1,) + (1,) * x.ndim)
    out = jnp.sum(deq, axis=0)
    if mean:
        out = out / qs.shape[0]
    return out


def ppermute_next(x, axis: str, mesh: Mesh):
    """Rotate shards to the next rank on the axis ring (ring-attention step)."""
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def compiled_allreduce(mesh: Mesh, axis: str = "data", dtype=jnp.float32):
    """Build a jitted allreduce over one mesh axis: the benchmarkable unit
    for ICI allreduce scaling (north-star metric #2). Input is sharded over
    ``axis``; output is the full psum on every shard."""
    in_spec = PartitionSpec(axis)
    out_spec = PartitionSpec(axis)

    def _body(x):
        return jax.lax.psum(x, axis_name=axis)

    fn = shard_map(_body, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)
    return jax.jit(
        fn,
        in_shardings=NamedSharding(mesh, in_spec),
        out_shardings=NamedSharding(mesh, out_spec),
    )
