"""Pipeline parallelism: GPipe-style microbatched stages over a mesh axis.

The reference exercises pipeline parallelism only through external stacks
run on Ray (ray: release/alpa_tests/train_opt_2_7b_minimum.py; SURVEY
§2.9 marks PP "first-class to build" for the TPU framework). TPU-native
design: stages live on a ``pipeline`` mesh axis; every device holds ONE
stage's parameters (leading stage axis sharded over the mesh axis) and a
rotating activation buffer that ``lax.ppermute`` advances one hop per tick
— the classic collective-permute pipeline from the JAX/praxis playbook,
not a port of torch's send/recv stage graphs.

Schedule: with S stages and M microbatches, tick t ∈ [0, M+S-1):
  - stage 0 injects microbatch t (while t < M),
  - every device applies its stage to its current activation,
  - activations rotate to the next stage over ICI,
  - the last stage emits microbatch t-(S-1) starting at t = S-1.
Utilization is M/(M+S-1) (the pipeline bubble); reverse-mode AD flows
through ppermute (its transpose is the reverse permute), so one
``jax.grad`` of the pipelined loss trains all stages without any
hand-written backward schedule.

All functions here run INSIDE ``shard_map`` (they use ``lax.axis_index``/
``ppermute`` on ``axis_name``); ``build_pipeline_fn`` wraps the common
replicated-input case.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def axis_size(axis_name: str) -> int:
    """Size of a mapped axis inside shard_map/pmap, on any jax version
    (``lax.axis_size`` only exists from 0.4.32; ``psum(1, axis)`` folds
    to the same constant on older ones)."""
    try:
        return lax.axis_size(axis_name)
    except AttributeError:  # jax < 0.4.32
        return lax.psum(1, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   *, axis_name: str = "pipeline"):
    """Apply an S-stage pipeline to M microbatches. Call inside shard_map.

    stage_fn(params, x) -> y: one stage's computation; y must have x's
      shape (activations flow stage to stage unchanged in shape).
    stage_params: this device's stage parameters (stage axis already
      sharded away by the caller's in_specs).
    microbatches: (M, ...) array, replicated across the pipeline axis.

    Returns (M, ...) outputs, replicated across the pipeline axis.
    """
    S = axis_size(axis_name)
    M = microbatches.shape[0]
    idx = lax.axis_index(axis_name)
    is_first = idx == 0
    is_last = idx == S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    # mark the carries as device-varying over the pipeline axis up front:
    # the loop body makes them varying (axis_index/ppermute), and scan
    # requires carry types to be loop-invariant
    def _varying(x):
        try:
            return lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError):
            return x  # older jax: no varying-axis types

    state = _varying(jnp.zeros_like(microbatches[0]))
    outputs = _varying(jnp.zeros_like(microbatches))

    def tick(t, carry):
        state, outputs = carry
        # stage 0 takes microbatch t from the feed (clamped once drained)
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        x = jnp.where(is_first, feed, state)
        y = stage_fn(stage_params, x)
        # the last stage has finished microbatch t-(S-1) once t >= S-1;
        # other devices (and warm-up ticks) must leave the buffer unchanged
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        current = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        emit = jnp.logical_and(is_last, t >= S - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(emit, y, current), out_idx, 0
        )
        state = lax.ppermute(y, axis_name, perm)
        return state, outputs

    _, outputs = lax.fori_loop(0, M + S - 1, tick, (state, outputs))
    # replicate the last stage's outputs to every pipeline rank (zeros
    # elsewhere, so a psum is a broadcast); grads flow back through it
    return lax.psum(jnp.where(is_last, outputs, 0.0), axis_name)


def stack_stage_params(params_per_stage):
    """Stack a list of per-stage pytrees into one pytree with a leading
    stage axis — shard that axis over the pipeline mesh axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_per_stage)


def build_pipeline_fn(stage_fn: Callable, mesh: Mesh, *,
                      axis_name: str = "pipeline",
                      donate: bool = False) -> Callable:
    """jit(shard_map(...)) wrapper: (stacked_params, microbatches) ->
    outputs, with the stage axis of ``stacked_params`` sharded over
    ``axis_name`` and microbatches replicated."""

    def local(stacked, mb):
        # local stacked shape is (1, ...): this device's stage
        own = jax.tree.map(lambda p: p[0], stacked)
        return pipeline_apply(stage_fn, own, mb, axis_name=axis_name)

    stage_spec = PartitionSpec(axis_name)  # leading stage axis per leaf
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(stage_spec, PartitionSpec()),
        out_specs=PartitionSpec(),
    )
    return jax.jit(fn, donate_argnums=(0,) if donate else ())
