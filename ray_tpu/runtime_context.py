"""Runtime context (ray parity: python/ray/runtime_context.py)."""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.worker import global_worker


class RuntimeContext:
    @property
    def node_id(self) -> str:
        global_worker.check_connected()
        return global_worker.core_worker.node_id

    def get_node_id(self) -> str:
        return self.node_id

    @property
    def job_id(self) -> bytes:
        global_worker.check_connected()
        return global_worker.core_worker.job_id

    def get_job_id(self) -> str:
        return self.job_id.hex()

    @property
    def namespace(self) -> str:
        global_worker.check_connected()
        return global_worker.core_worker.namespace

    def get_task_id(self) -> Optional[str]:
        cw = global_worker.core_worker
        ex = getattr(cw, "executor", None)
        if ex is not None and ex.current_task_id is not None:
            return ex.current_task_id.hex()
        return None

    def get_actor_id(self) -> Optional[str]:
        cw = global_worker.core_worker
        ex = getattr(cw, "executor", None)
        if ex is not None and ex.actor_spec is not None:
            return ex.actor_spec.actor_id.hex()
        return None

    def get_worker_id(self) -> str:
        global_worker.check_connected()
        return global_worker.core_worker.client_id

    def get_node_labels(self) -> dict:
        global_worker.check_connected()
        return dict(global_worker.core_worker.node_labels)

    def get_resources(self) -> dict:
        """Node-total resources of the current node."""
        global_worker.check_connected()
        return dict(global_worker.core_worker.node_resources)

    def get_tpu_ids(self) -> list:
        """Local TPU chip indices on this node (TPU analog of
        ray.get_gpu_ids, ray: python/ray/_private/worker.py:838)."""
        n = int(self.get_resources().get("TPU", 0))
        return list(range(n))


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
