"""Ring attention: exact attention over sequences sharded across a mesh
axis, overlapping compute with neighbor KV exchange on the ICI ring.

The sequence axis is sharded over mesh axis ``axis_name``; each device holds
q/k/v chunks of shape (..., s/n, d). The kernel loops n times: fold the
resident KV chunk into flash accumulators (``online_block_update``), then
``lax.ppermute`` the KV chunk to the next ring neighbor — XLA overlaps the
permute with the next block's compute. Memory stays O(s/n) per device and
the softmax is exact (online renormalization), unlike approximations.

This is the sequence-parallel capability the reference lacks natively
(ray SURVEY §5: "no ring attention / context parallel in-repo") built the
TPU way: collectives ride the ICI ring via ppermute rather than NCCL P2P.

Use ``ring_self_attention`` for the shard_map-wrapped entry, or call
``ring_attention`` inside your own shard_map/pjit region.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops.attention import finalize_flash, online_block_update


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Exact attention with KV rotating around the ``axis_name`` ring.

    Call inside shard_map/pjit where q,k,v are the per-device sequence
    chunks: (..., s_local, d). Requires the same s_local on every device.
    """
    sm_scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    s_q = q.shape[-2]
    s_k = k.shape[-2]
    d = q.shape[-1]
    lead = q.shape[:-2]

    qf = q.astype(jnp.float32)
    # Derive the initial accumulators from q so they carry q's exact
    # varying-manual-axes type (scan requires carry-in == carry-out types;
    # fresh constants would be "unvarying" under newer shard_map).
    l0 = qf[..., 0] * 0.0
    m0 = l0 - jnp.inf
    a0 = qf * 0.0

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        m, l, acc, kk, vv = carry
        # the KV chunk we hold at `step` originated on device (me - step) % n
        src = jnp.mod(me - step, n)
        m, l, acc = online_block_update(
            qf, kk.astype(jnp.float32), vv.astype(jnp.float32), m, l, acc,
            sm_scale=sm_scale, q_offset=me * s_q, k_offset=src * s_k,
            causal=causal,
        )
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (m, l, acc, kk, vv), None

    (m, l, acc, _, _), _ = lax.scan(
        body, (m0, l0, a0, k, v), jnp.arange(n)
    )
    return finalize_flash(m, l, acc, q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "sp",
                        causal: bool = False,
                        sm_scale: Optional[float] = None) -> jax.Array:
    """shard_map wrapper: q,k,v are GLOBAL (b, h, s, d) arrays whose s dim
    is (or will be) sharded over ``seq_axis``; returns the global output
    with the same sharding."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(None, None, seq_axis, None)
    fn = shard_map(
        functools.partial(
            ring_attention, axis_name=seq_axis, causal=causal,
            sm_scale=sm_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
