"""ray_tpu.ops — TPU kernels (Pallas) and sequence-parallel attention."""

from ray_tpu.ops.attention import (
    attention_reference,
    flash_attention,
    finalize_flash,
    online_block_update,
)
from ray_tpu.ops.ring_attention import ring_attention, ring_self_attention
from ray_tpu.ops import moe

__all__ = [
    "moe",
    "attention_reference",
    "finalize_flash",
    "flash_attention",
    "online_block_update",
    "ring_attention",
    "ring_self_attention",
]
