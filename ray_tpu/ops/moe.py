"""Mixture-of-experts with expert parallelism (EP).

SURVEY §2.9: EP = "mesh axis + ragged_all_to_all style dispatch" — absent
in the reference (Ray delegates to external stacks); TPU-native it is a
first-class parallelism axis. This implements Switch-style top-1 routing
(Fedus et al.) with GShard's dense dispatch/combine einsums, which map
onto the MXU, and an expert-parallel execution mode where experts shard
over a mesh axis and tokens travel by `lax.all_to_all` over ICI.

Two execution modes with identical math:
- ``moe_ffn``: all experts local (single chip / replicated).
- ``moe_ffn_ep``: inside ``shard_map`` with experts sharded over
  ``axis`` — dispatch (E, C, d) splits over the expert dim, an
  all_to_all sends each expert its tokens from every data shard, local
  experts run, and the inverse all_to_all returns outputs for combine.

Capacity is static (compile-friendly): C = ceil(capacity_factor * T / E);
overflow tokens are dropped by the dispatch mask (their combine weight is
zero, so the residual path carries them — standard Switch behavior).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def switch_gating(logits: jnp.ndarray, capacity: int
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-1 routing.

    Args: logits (T, E); capacity C per expert.
    Returns (dispatch, combine, aux_loss):
      dispatch (T, E, C) one-hot token->slot assignment (bool as float),
      combine (T, E, C) = dispatch * router prob,
      aux_loss: Switch load-balance loss (scalar).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # (T,)
    onehot = jax.nn.one_hot(expert, E, dtype=logits.dtype)   # (T, E)
    # Queue positions in int32: a low-precision (bf16) cumsum silently
    # collides slots past 256 tokens per expert (8-bit mantissa).
    onehot_i = jax.nn.one_hot(expert, E, dtype=jnp.int32)
    pos = jnp.cumsum(onehot_i, axis=0) * onehot_i - onehot_i  # (T, E)
    keep = (pos < capacity).astype(logits.dtype) * onehot    # (T, E)
    slot = jax.nn.one_hot(
        jnp.sum(pos, axis=-1), capacity, dtype=logits.dtype
    )                                                        # (T, C)
    dispatch = keep[:, :, None] * slot[:, None, :]           # (T, E, C)
    gate = jnp.sum(probs * onehot, axis=-1)                  # (T,)
    combine = dispatch * gate[:, None, None]
    # load-balance loss: E * sum_e f_e * P_e (Switch eq. 4)
    f = jnp.mean(onehot, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * p)
    return dispatch, combine, aux


def init_moe_params(key, d_model: int, d_hidden: int, num_experts: int,
                    dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    """Router + stacked expert FFN parameters (experts stacked on dim 0 so
    an EP shard slices contiguously)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": (jax.random.normal(k1, (d_model, num_experts)) * scale_in
                   ).astype(dtype),
        "wi": (jax.random.normal(k2, (num_experts, d_model, d_hidden))
               * scale_in).astype(dtype),
        "wo": (jax.random.normal(k3, (num_experts, d_hidden, d_model))
               * scale_out).astype(dtype),
    }


def _expert_ffn(wi, wo, x):
    """Per-expert FFN over (E, C, d) inputs; einsums ride the MXU."""
    h = jnp.einsum("ecd,edh->ech", x, wi)
    h = jax.nn.gelu(h)
    return jnp.einsum("ech,ehd->ecd", h, wo)


def moe_ffn(params: Dict[str, jnp.ndarray], x: jnp.ndarray,
            capacity_factor: float = 1.25
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense-dispatch MoE with all experts local.

    x: (T, d). Returns (out (T, d), aux_loss)."""
    E = params["router"].shape[1]
    T = x.shape[0]
    capacity = max(1, -(-int(capacity_factor * T) // E))  # ceil, as documented
    logits = x @ params["router"]
    dispatch, combine, aux = switch_gating(logits, capacity)
    expert_in = jnp.einsum("td,tec->ecd", x, dispatch)
    expert_out = _expert_ffn(params["wi"], params["wo"], expert_in)
    out = jnp.einsum("ecd,tec->td", expert_out, combine)
    return out, aux


def ep_loss_and_grads(loss_fn, params: Dict[str, jnp.ndarray],
                      data_axis: str, ep_axis: str):
    """The verified EP training-step pattern (call inside ``shard_map``
    with tokens sharded over BOTH mesh axes — no shard may duplicate
    another's tokens, or collective transposes double-count):

    - differentiate the LOCAL loss scaled by 1/N_shards,
    - global loss = psum over both axes (the global token mean),
    - router grads psum over both axes; expert grads (ep-sharded) psum
      over the data axis only.

    Gradient parity with the dense path is exact (tests/test_moe.py).
    ``loss_fn(params) -> local scalar`` (unscaled)."""
    n = jax.lax.psum(1, data_axis) * jax.lax.psum(1, ep_axis)
    scaled, grads = jax.value_and_grad(
        lambda p: loss_fn(p) / n
    )(params)
    loss = jax.lax.psum(jax.lax.psum(scaled, data_axis), ep_axis)
    grads = dict(grads)
    for k in grads:
        grads[k] = jax.lax.psum(grads[k], data_axis)
        if k == "router":  # replicated over ep too
            grads[k] = jax.lax.psum(grads[k], ep_axis)
    return loss, grads


def moe_ffn_ep(params: Dict[str, jnp.ndarray], x: jnp.ndarray,
               axis: str, capacity_factor: float = 1.25
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel MoE: call inside ``shard_map`` with ``params["wi"]/
    ["wo"]`` sharded over ``axis`` on the expert dim and ``x`` sharded over
    the data axis. Tokens travel to their experts and back via
    ``lax.all_to_all`` on the ``axis`` ring (ICI on TPU pods).

    Router weights are replicated; gating runs on local tokens. The global
    expert count is n * E_local."""
    n = jax.lax.psum(1, axis)
    E_local = params["wi"].shape[0]
    E = n * E_local
    T = x.shape[0]
    capacity = max(1, -(-int(capacity_factor * T) // E))  # ceil, as documented
    logits = x @ params["router"]
    dispatch, combine, aux = switch_gating(logits, capacity)
    # local dispatch to ALL global experts: (E, C, d)
    expert_in = jnp.einsum("td,tec->ecd", x, dispatch)
    # exchange: split the expert dim across shards, concat the sender dim —
    # each shard ends with (E_local, n*C, d): its experts' tokens from
    # every data shard.
    expert_in = jax.lax.all_to_all(
        expert_in, axis, split_axis=0, concat_axis=1, tiled=True
    )
    expert_out = _expert_ffn(params["wi"], params["wo"], expert_in)
    # inverse exchange: send each sender's slice back, restore (E, C, d)
    expert_out = jax.lax.all_to_all(
        expert_out, axis, split_axis=1, concat_axis=0, tiled=True
    )
    out = jnp.einsum("ecd,tec->td", expert_out, combine)
    # aux loss is computed on local tokens; average over the data shards
    # happens in the caller's loss pmean.
    return out, aux
