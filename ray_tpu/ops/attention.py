"""Attention ops: Pallas TPU flash-attention kernel + chunked JAX fallback.

TPU-native replacement for the attention math the reference delegates to
torch/CUDA ecosystems (ray SURVEY §5: sequence-parallel/long-context paths
are absent in-repo and arrive via external stacks run on Ray). Here they are
first-class ops:

- ``flash_attention``: O(seq) memory online-softmax attention. On TPU it runs
  a Pallas kernel tiled for the MXU (q blocks x kv blocks, accumulators in
  VMEM); elsewhere it runs a numerically identical ``lax.scan`` formulation,
  so tests validate the same math on CPU.
- ``attention_reference``: naive full-matrix attention for numerics tests.

All paths are differentiable: the fallback natively, the Pallas path via
custom VJP (recompute-based backward using the same online-softmax blocks).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30
# lse/delta side tensors are stored lane-broadcast (last dim = one 128-lane
# register row) so their Pallas blocks satisfy the TPU (8, 128) tiling rule
_LANES = 128


def attention_reference(q, k, v, *, causal: bool = False,
                        sm_scale: Optional[float] = None) -> jax.Array:
    """Naive softmax(QK^T)V. Shapes: (..., s, d)."""
    sm_scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k) * sm_scale
    if causal:
        q_len, k_len = s.shape[-2], s.shape[-1]
        qi = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        ki = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        s = jnp.where(qi + (k_len - q_len) >= ki, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v).astype(q.dtype)


# ----------------------------------------------------------------------
# online-softmax block update (shared by fallback + ring attention)
# ----------------------------------------------------------------------

def online_block_update(q, k, v, m, l, acc, *, sm_scale: float,
                        q_offset=0, k_offset=0, causal: bool = False,
                        k_total: Optional[int] = None):
    """Fold one KV block into flash accumulators.

    q: (..., bq, d); k/v: (..., bk, d); m,l: (..., bq); acc: (..., bq, d).
    Offsets are the blocks' global sequence positions (for causal masks in
    blockwise/ring execution). ``k_total`` masks padding columns whose
    global position is past the true sequence end.
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * sm_scale
    bq, bk = s.shape[-2], s.shape[-1]
    qi = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + q_offset
    ki = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + k_offset
    if causal:
        s = jnp.where(qi >= ki, s, NEG_INF)
    if k_total is not None:
        s = jnp.where(ki < k_total, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard: fully-masked rows keep m at -inf; exp(s - (-inf)) must not NaN
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isfinite(m_new)[..., None], p, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def finalize_flash(m, l, acc, dtype):
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).astype(dtype)


# ----------------------------------------------------------------------
# chunked JAX fallback (CPU / any backend; differentiable)
# ----------------------------------------------------------------------

def _flash_scan(q, k, v, *, causal: bool, sm_scale: float, block_k: int):
    *lead, q_len, d = q.shape
    k_len = k.shape[-2]
    block_k = min(block_k, k_len)
    nk = -(-k_len // block_k)
    pad = nk * block_k - k_len
    if pad:
        kp = jnp.pad(k, [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)])
        vp = jnp.pad(v, [(0, 0)] * (v.ndim - 2) + [(0, pad), (0, 0)])
    else:
        kp, vp = k, v
    kb = kp.reshape(*lead, nk, block_k, d)
    vb = vp.reshape(*lead, nk, block_k, d)

    m0 = jnp.full((*lead, q_len), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((*lead, q_len), jnp.float32)
    a0 = jnp.zeros((*lead, q_len, d), jnp.float32)

    def body(carry, ib):
        m, l, acc = carry
        kk, vv, i = ib
        m2, l2, a2 = online_block_update(
            q, kk, vv, m, l, acc, sm_scale=sm_scale,
            q_offset=k_len - q_len, k_offset=i * block_k, causal=causal,
            k_total=k_len if pad else None,
        )
        return (m2, l2, a2), None

    # move block axis to front for scan
    kb_t = jnp.moveaxis(kb, -3, 0)
    vb_t = jnp.moveaxis(vb, -3, 0)
    idx = jnp.arange(nk)
    (m, l, acc), _ = lax.scan(body, (m0, l0, a0), (kb_t, vb_t, idx))
    return finalize_flash(m, l, acc, q.dtype)


# ----------------------------------------------------------------------
# Pallas TPU kernels
# ----------------------------------------------------------------------
#
# Grid-streamed K/V: the kv-block axis is the innermost ("arbitrary") grid
# dimension, so only one (block_k, d) K/V tile is resident in VMEM at a
# time — sequence length is bounded by HBM, not VMEM (the r1 kernel loaded
# the full K/V per q-block, capping seq length). The forward also emits the
# per-row logsumexp so the backward is real Pallas kernels (dq and dk/dv)
# instead of a scan-recompute VJP.

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale: float, causal: bool, block_q: int, block_k: int,
                q_len: int, k_len: int):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_offset = qi * block_q + (k_len - q_len)
    k_offset = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    # a causal block is live unless every row is above the diagonal
    live = (q_offset + block_q - 1 >= k_offset) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[...].astype(jnp.float32) * sm_scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            rows = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + q_offset >= cols + k_offset, s, NEG_INF)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        safe_m = jnp.where(m_new > NEG_INF / 2, m_new, 0.0)
        p = jnp.exp(s - safe_m)
        p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
        alpha = jnp.where(m_prev > NEG_INF / 2, jnp.exp(m_prev - safe_m), 0.0)
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        m = m_scr[...]
        # rows with no live columns get lse=+inf => p == 0 in the backward.
        # lse is stored lane-broadcast as (block_q, LANES): a (block_q,)
        # vector output would need a (1, block_q) block, which violates the
        # TPU (8, 128) tiling rule once the batch dim is squeezed.
        lse = jnp.where(
            l == 0.0, jnp.inf,
            jnp.where(m > NEG_INF / 2, m, 0.0) + jnp.log(l_safe),
        )
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale: float, causal: bool, block_q: int,
                   block_k: int, q_len: int, k_len: int):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_offset = qi * block_q + (k_len - q_len)
    k_offset = ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr[...])

    live = (q_offset + block_q - 1 >= k_offset) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[...].astype(jnp.float32)
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, 0:1]
        delta = delta_ref[...][:, 0:1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + q_offset >= cols + k_offset, s, NEG_INF)
        p = jnp.exp(s - lse)  # normalized probs; lse=+inf rows -> 0
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[...] += sm_scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale: float,
                    causal: bool, block_q: int, block_k: int, q_len: int,
                    k_len: int):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    q_offset = qi * block_q + (k_len - q_len)
    k_offset = ki * block_k

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr[...])
        dv_scr[...] = jnp.zeros_like(dv_scr[...])

    live = (q_offset + block_q - 1 >= k_offset) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[...].astype(jnp.float32)
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, 0:1]
        delta = delta_ref[...][:, 0:1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + q_offset >= cols + k_offset, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_scr[...] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[...] += sm_scale * jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


try:  # pallas import is TPU/CPU-interpret capable; guard for safety
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _compiler_params(interpret: bool, n_arbitrary: int = 1):
    if interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel")
        + ("arbitrary",) * n_arbitrary
    )


def _flash_pallas(q, k, v, *, causal: bool, sm_scale: float,
                  block_q: int, block_k: int, interpret: bool):
    """q,k,v: (B, S, D) with batch*heads folded into B. -> (out, lse)."""
    b, q_len, d = q.shape
    k_len = k.shape[1]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    assert q_len % block_q == 0, (q_len, block_q)
    assert k_len % block_k == 0, (k_len, block_k)

    grid = (b, q_len // block_q, k_len // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, q_len=q_len, k_len=k_len,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((None, block_k, d), lambda bi, qi, ki: (bi, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bi, qi, ki: (bi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((None, block_q, _LANES),
                         lambda bi, qi, ki: (bi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, q_len, d), q.dtype),
            jax.ShapeDtypeStruct((b, q_len, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v)


def _flash_pallas_bwd_kernels(q, k, v, do, lse, delta, *, causal: bool,
                              sm_scale: float, block_q: int, block_k: int,
                              interpret: bool):
    b, q_len, d = q.shape
    k_len = k.shape[1]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)

    qspec = lambda f: pl.BlockSpec((None, block_q, d), f)
    kspec = lambda f: pl.BlockSpec((None, block_k, d), f)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, q_len=q_len, k_len=k_len,
        ),
        grid=(b, q_len // block_q, k_len // block_k),
        in_specs=[
            qspec(lambda bi, qi, ki: (bi, qi, 0)),
            kspec(lambda bi, qi, ki: (bi, ki, 0)),
            kspec(lambda bi, qi, ki: (bi, ki, 0)),
            qspec(lambda bi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((None, block_q, _LANES),
                         lambda bi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((None, block_q, _LANES),
                         lambda bi, qi, ki: (bi, qi, 0)),
        ],
        out_specs=qspec(lambda bi, qi, ki: (bi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, q_len, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, q_len=q_len, k_len=k_len,
        ),
        grid=(b, k_len // block_k, q_len // block_q),
        in_specs=[
            qspec(lambda bi, ki, qi: (bi, qi, 0)),
            kspec(lambda bi, ki, qi: (bi, ki, 0)),
            kspec(lambda bi, ki, qi: (bi, ki, 0)),
            qspec(lambda bi, ki, qi: (bi, qi, 0)),
            pl.BlockSpec((None, block_q, _LANES),
                         lambda bi, ki, qi: (bi, qi, 0)),
            pl.BlockSpec((None, block_q, _LANES),
                         lambda bi, ki, qi: (bi, qi, 0)),
        ],
        out_specs=[
            kspec(lambda bi, ki, qi: (bi, ki, 0)),
            kspec(lambda bi, ki, qi: (bi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k_len, d), k.dtype),
            jax.ShapeDtypeStruct((b, k_len, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_pallas_diff(q, k, v, causal, sm_scale, block_q, block_k,
                       interpret):
    """Differentiable Pallas flash attention: both directions are Pallas
    kernels (forward saves the logsumexp; backward recomputes P per block
    from q,k,lse — O(seq) memory, no attention matrix ever materialized)."""
    out, _ = _flash_pallas(q, k, v, causal=causal, sm_scale=sm_scale,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)
    return out


def _flash_pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_pallas(q, k, v, causal=causal, sm_scale=sm_scale,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_pallas_bwd(causal, sm_scale, block_q, block_k, interpret,
                      res, g):
    q, k, v, out, lse = res
    # delta_i = rowsum(dO_i * O_i); tiny elementwise reduce — XLA fuses it.
    # Lane-broadcast to (b, q_len, _LANES) to match the lse layout.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))
    dq, dk, dv = _flash_pallas_bwd_kernels(
        q, k, v, g, lse, delta, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return dq, dk, dv


_flash_pallas_diff.defvjp(_flash_pallas_fwd, _flash_pallas_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "sm_scale", "block_q", "block_k", "impl"),
)
def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    impl: Optional[str] = None) -> jax.Array:
    """Flash attention over (..., seq, head_dim) inputs.

    Accepts (b, h, s, d) or (b, s, d); picks the Pallas TPU kernel on TPU
    backends and the scan fallback elsewhere. ``impl`` forces a path:
    "pallas" | "pallas_interpret" | "scan" | "reference".
    """
    sm_scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if impl is None:
        # devices()[0].platform, not default_backend(): relayed/experimental
        # PJRT plugins (axon tunnel) register under their own backend name
        # while the device platform still reports "tpu" — default_backend()
        # alone would silently drop the TPU onto the scan fallback.
        try:
            on_tpu = (jax.default_backend() == "tpu"
                      or jax.devices()[0].platform == "tpu")
        except Exception:
            on_tpu = False
        impl = "pallas" if on_tpu and _HAS_PALLAS else "scan"
    if impl == "reference":
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    if impl == "scan":
        return _flash_scan(q, k, v, causal=causal, sm_scale=sm_scale,
                           block_k=block_k)
    interpret = impl == "pallas_interpret"
    if q.ndim == 4:
        b, h, s, d = q.shape
        fold = lambda x: x.reshape(b * h, x.shape[-2], d)
        out = _flash_pallas_diff(fold(q), fold(k), fold(v), causal,
                                 sm_scale, block_q, block_k, interpret)
        return out.reshape(b, h, s, d)
    return _flash_pallas_diff(q, k, v, causal, sm_scale, block_q, block_k,
                              interpret)
