"""Public API: init/remote/get/put/wait + actors.

Mirrors the reference's user-facing surface (ray: python/ray/_private/worker.py
init:1108 get:2417 put:2546 wait:2609 remote:2952, remote_function.py:245,
actor.py) on top of the TPU-native runtime.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._private.common import SchedulingStrategy
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import ActorID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.worker import (
    ActorDiedError,
    CoreWorker,
    GetTimeoutError,
    TaskCancelledError,
    WorkerDiedError,
    global_worker,
)
from ray_tpu._private.serialization import TaskError

logger = logging.getLogger(__name__)

_init_lock = threading.Lock()


# ---------------------------------------------------------------------------
# init / shutdown
# ---------------------------------------------------------------------------


class RayContext:
    def __init__(self, address: str, node_id: str):
        self.address_info = {"address": address, "node_id": node_id}

    def __getitem__(self, k):
        return self.address_info[k]


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    namespace: Optional[str] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    _system_config: Optional[dict] = None,
    log_to_driver: bool = True,
) -> RayContext:
    """Start (or connect to) a cluster and connect this driver.

    ray parity: ray.init (python/ray/_private/worker.py:1108). With no
    address, starts a head node (GCS + raylet) owned by this process.
    """
    with _init_lock:
        if global_worker.connected:
            if ignore_reinit_error:
                cw = global_worker.core_worker
                return RayContext("existing", cw.node_id)
            raise RuntimeError("ray_tpu.init() called twice")
        if _system_config:
            cfg.update(_system_config)
        if object_store_memory:
            cfg.update({"object_store_memory": object_store_memory})
        if address == "auto":
            # Inside a cluster (worker/job-entrypoint subprocess): the
            # raylet stamps the GCS address into the env (ray parity:
            # RAY_ADDRESS/auto-discovery).
            address = os.environ.get("RAY_TPU_GCS_ADDR")
            if not address:
                raise ConnectionError(
                    "address='auto' but RAY_TPU_GCS_ADDR is not set"
                )
        if address is None:
            res = dict(resources or {})
            if num_cpus is not None:
                res["CPU"] = float(num_cpus)
            if num_tpus is not None:
                res["TPU"] = float(num_tpus)
            from ray_tpu._private.node import NodeProcesses

            node = NodeProcesses(head=True, resources=res or None, labels=labels)
            global_worker.node = node
            address = node.address
            raylet_host, raylet_port = "127.0.0.1", node.raylet_port
            gcs_host, gcs_port = address.rsplit(":", 1)
        else:
            gcs_host, gcs_port = address.rsplit(":", 1)
            # Separately launched driver: pick up the head's persisted
            # cluster token (session dir / CLI state file) when the env
            # doesn't already carry one, else rpcio auth silently drops us.
            from ray_tpu._private.node import load_cluster_token

            load_cluster_token()
            # Connecting to an existing cluster: find/start a local raylet is
            # out of scope round 1 — connect to the head's raylet via GCS.
            import asyncio

            from ray_tpu._private.rpcio import EventLoopThread, connect as rpc_connect

            tmp_io = EventLoopThread("init-probe")
            conn = tmp_io.run(rpc_connect(gcs_host, int(gcs_port)))
            nodes = tmp_io.run(conn.request("get_nodes", {}))
            tmp_io.run(conn.close())
            tmp_io.stop()
            alive = [n for n in nodes if n["alive"]]
            if not alive:
                raise ConnectionError(f"no alive nodes in cluster at {address}")
            raylet_host, raylet_port = alive[0]["host"], alive[0]["port"]
        cw = CoreWorker(
            raylet_host=raylet_host,
            raylet_port=int(raylet_port),
            gcs_host=gcs_host,
            gcs_port=int(gcs_port),
            is_driver=True,
            namespace=namespace,
        )
        global_worker.core_worker = cw
        global_worker.mode = "driver"
        # both gates must agree: the init() kwarg and the config flag
        # (RAY_TPU_LOG_TO_DRIVER=0 kills streaming cluster-wide without
        # touching code; with no subscribers, raylets skip tailing too)
        if log_to_driver and cfg.log_to_driver:
            _subscribe_worker_logs(cw)
        # local usage snapshot (reference: usage_lib's session report;
        # this build never phones home — see usage_lib docstring)
        if global_worker.node is not None:
            try:
                from ray_tpu._private import usage_lib

                if usage_lib.usage_stats_enabled():
                    usage_lib.write_usage_stats(
                        global_worker.node.session_dir
                    )
            except Exception:
                pass
        return RayContext(address, cw.node_id)


# per-worker prefix colors (ray parity: worker.py cycles colors by pid so
# interleaved workers stay tellable apart); 36=cyan first for continuity
_LOG_COLORS = (36, 35, 33, 32, 34, 31)


def _subscribe_worker_logs(cw):
    """Print worker stdout/stderr on the driver (ray parity:
    _private/log_monitor.py + worker.py print_logs — lines arrive over
    GCS pubsub from each raylet's log tailer, attributed to tasks by
    byte-offset spans, and render as ``(<TaskName> pid=<pid>
    node=<id8>)``-prefixed lines; identical lines fanning in from many
    workers collapse through a dedup window into one ``[repeated Nx]``
    summary. Entries are tagged with the worker's job so concurrent
    drivers only see their own job's output)."""
    import sys
    import time as _time

    from ray_tpu._private import logplane, metrics_core

    my_job = cw.job_id.hex() if cw.job_id else None
    dedup = logplane.LogDeduplicator(window_s=cfg.log_dedup_window_s)
    # self-measurement: printed-line count + handler CPU for the
    # BENCH_LOG_OVERHEAD lane (snapshot-time callbacks, zero hot-path
    # cost beyond the dict writes below)
    stats = {"lines": 0, "seconds": 0.0}
    reg = metrics_core.registry()
    ltags = {"channel": "logs"}
    reg.counter("driver_log_lines_printed_total",
                "Streamed worker log lines printed by this driver"
                ).labels(**ltags).set_fn(lambda: stats["lines"])
    reg.counter("driver_log_handler_seconds_total",
                "CPU seconds in the driver's log-print handler"
                ).labels(**ltags).set_fn(lambda: stats["seconds"])

    def on_logs(msg):
        # thread_time: CPU actually burned here, not GIL-contended wall
        t0 = _time.thread_time()
        node = (msg.get("node_id") or "")[:8]
        out = []
        for entry in msg.get("workers", ()):
            job = entry.get("job_id")
            if job is not None and my_job is not None and job != my_job:
                continue
            pid = entry.get("pid")
            color = _LOG_COLORS[(pid or 0) % len(_LOG_COLORS)]
            # "segs" groups consecutive lines by attributed task name
            for name, lines in entry.get("segs") or ():
                label = f"{name} pid={pid} node={node}" if name \
                    else f"pid={pid} node={node}"
                prefix = f"\x1b[{color}m({label})\x1b[0m "
                for line in lines:
                    out.extend(dedup.feed(prefix, line))
        out.extend(dedup.flush())
        if out:
            print("\n".join(out), file=sys.stderr)
            stats["lines"] += len(out)
        stats["seconds"] += _time.thread_time() - t0

    async def _summary_flusher():
        # a quiet stream must still surface its pending [repeated Nx]
        # summaries: without this tick they would wait for the NEXT log
        # message (or shutdown), hiding how many workers really printed
        import asyncio

        while True:
            await asyncio.sleep(max(0.25, cfg.log_dedup_window_s))
            try:
                out = dedup.flush()
                if out:
                    print("\n".join(out), file=sys.stderr)
                    stats["lines"] += len(out)
            except Exception:
                pass

    try:
        cw.subscribe("logs", on_logs)
        cw._log_dedup = dedup  # shutdown drains the last summaries
        import asyncio as _asyncio

        cw._log_flush_task = _asyncio.run_coroutine_threadsafe(
            _summary_flusher(), cw.io.loop)
    except Exception:
        pass  # logs stay in session files


def shutdown():
    with _init_lock:
        cw = global_worker.core_worker
        if cw is not None:
            task = getattr(cw, "_log_flush_task", None)
            if task is not None:
                task.cancel()
            dedup = getattr(cw, "_log_dedup", None)
            if dedup is not None:
                # drain pending [repeated Nx] summaries before the pubsub
                # subscription dies with the connection
                import sys

                tail = dedup.flush(force=True)
                if tail:
                    print("\n".join(tail), file=sys.stderr)
            try:
                cw.disconnect()
            except Exception:
                pass
            global_worker.core_worker = None
        if global_worker.node is not None:
            global_worker.node.shutdown()
            global_worker.node = None


def is_initialized() -> bool:
    return global_worker.connected


# ---------------------------------------------------------------------------
# core object API
# ---------------------------------------------------------------------------


def put(value: Any) -> ObjectRef:
    global_worker.check_connected()
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put on an ObjectRef is not allowed")
    return global_worker.core_worker.put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    global_worker.check_connected()
    if isinstance(refs, list):
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRefs, got {type(r)}")
    elif not isinstance(refs, ObjectRef):
        raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")
    return global_worker.core_worker.get(refs, timeout=timeout)


def wait(
    refs: List[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    global_worker.check_connected()
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(f"num_returns must be in 1..{len(refs)}")
    return global_worker.core_worker.wait(
        refs, num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def kill(actor: "ActorHandle", *, no_restart: bool = True):
    global_worker.check_connected()
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    global_worker.core_worker.kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    global_worker.check_connected()
    global_worker.core_worker.cancel_task(ref, force=force)


def nodes() -> list:
    """Cluster node table (ray parity: ray.nodes())."""
    global_worker.check_connected()
    return global_worker.core_worker.get_nodes()


def cluster_resources() -> Dict[str, float]:
    """Total resources across alive nodes (ray parity: ray.cluster_resources)."""
    totals: Dict[str, float] = {}
    for n in nodes():
        if not n.get("alive", True):
            continue
        for k, v in (n.get("resources_total") or {}).items():
            totals[k] = totals.get(k, 0.0) + v
    return totals


def available_resources() -> Dict[str, float]:
    """Currently-free resources (ray parity: ray.available_resources)."""
    avail: Dict[str, float] = {}
    for n in nodes():
        if not n.get("alive", True):
            continue
        for k, v in (n.get("resources_available") or {}).items():
            avail[k] = avail.get(k, 0.0) + v
    return avail


def get_actor(name: str, namespace: Optional[str] = None) -> "ActorHandle":
    global_worker.check_connected()
    table = global_worker.core_worker.get_actor_table(name=name, namespace=namespace)
    if table is None:
        raise ValueError(f"Failed to look up actor with name '{name}'")
    return ActorHandle(table["actor_id"], methods=None)


# ---------------------------------------------------------------------------
# options / resource translation
# ---------------------------------------------------------------------------


def _prepare_runtime_env(runtime_env: Optional[dict]) -> Optional[dict]:
    """Package working_dir/py_modules into GCS-stored URIs before the spec
    ships (ray: runtime_env packaging at submission time)."""
    if not runtime_env:
        return runtime_env
    from ray_tpu._private.runtime_env import prepare_runtime_env

    global_worker.check_connected()
    return prepare_runtime_env(global_worker.core_worker, runtime_env)


def _build_resources(opts: dict, default_cpu: float) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    elif "CPU" not in res:
        res["CPU"] = default_cpu
    if opts.get("num_gpus") is not None:
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("num_tpus") is not None:
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("memory") is not None:
        res["memory"] = float(opts["memory"])
    return {k: v for k, v in res.items() if v}


def _build_scheduling(opts: dict) -> SchedulingStrategy:
    strategy = opts.get("scheduling_strategy")
    if strategy is None or strategy == "DEFAULT":
        # legacy PG options (ray parity: .options(placement_group=pg,
        # placement_group_bundle_index=i) without an explicit strategy)
        pg = opts.get("placement_group")
        if pg is not None:
            idx = opts.get("placement_group_bundle_index")
            return SchedulingStrategy(
                kind="PLACEMENT_GROUP",
                pg_id=pg.id_hex,
                pg_bundle_index=None if idx in (None, -1) else idx,
            )
        return SchedulingStrategy()
    if strategy == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if isinstance(strategy, SchedulingStrategy):
        return strategy
    # util.scheduling_strategies objects
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        NodeLabelSchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(
            kind="NODE_AFFINITY", node_id=strategy.node_id, soft=strategy.soft
        )
    if isinstance(strategy, NodeLabelSchedulingStrategy):
        return SchedulingStrategy(
            kind="NODE_LABEL", labels_hard=strategy.hard,
            labels_soft=strategy.soft,
        )
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            pg_id=pg.id_hex,
            pg_bundle_index=(
                None
                if strategy.placement_group_bundle_index in (None, -1)
                else strategy.placement_group_bundle_index
            ),
            pg_capture_child_tasks=strategy.placement_group_capture_child_tasks,
        )
    raise TypeError(f"unsupported scheduling_strategy: {strategy!r}")


_VALID_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "memory", "resources", "num_returns",
    "max_retries", "retry_exceptions", "max_restarts", "max_task_retries",
    "max_concurrency", "concurrency_groups", "name", "namespace", "lifetime",
    "scheduling_strategy", "runtime_env", "max_calls", "get_if_exists",
    "placement_group", "placement_group_bundle_index",
}


def _check_options(opts: dict):
    for k in opts:
        if k not in _VALID_OPTIONS:
            raise ValueError(f"Invalid option keyword: {k!r}")


# ---------------------------------------------------------------------------
# RemoteFunction
# ---------------------------------------------------------------------------


class RemoteFunction:
    """ray parity: python/ray/remote_function.py:245 (_remote)."""

    def __init__(self, func, options: dict):
        import cloudpickle

        self._function = func
        self._options = options
        self._func_blob = cloudpickle.dumps(func)
        self._template = None  # per-callsite submit template (lazy)
        self.__name__ = getattr(func, "__name__", "remote_function")
        self.__doc__ = getattr(func, "__doc__", None)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self.__name__}' cannot be called directly; "
            f"use '{self.__name__}.remote()'."
        )

    def options(self, **opts):
        _check_options(opts)
        merged = {**self._options, **opts}
        rf = RemoteFunction.__new__(RemoteFunction)
        rf._function = self._function
        rf._options = merged
        rf._func_blob = self._func_blob
        rf._template = None  # new options set -> new template
        rf.__name__ = self.__name__
        rf.__doc__ = self.__doc__
        return rf

    def _build_template(self, cw):
        """Resolve options into a CoreWorker submit template — the
        constant per-call work (resource/scheduling translation, runtime
        env packaging) paid once per (RemoteFunction, options, worker)."""
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        if num_returns == "dynamic":
            # ray parity: num_returns="dynamic" — the single visible ref
            # resolves to a list of per-item ObjectRefs (task_manager.h
            # ObjectRefStream / legacy dynamic generators)
            num_returns = -1
        return cw.task_template(
            func=self._function,
            num_returns=num_returns,
            resources=_build_resources(opts, default_cpu=1.0),
            scheduling=_build_scheduling(opts),
            max_retries=opts.get("max_retries", 3),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            name=self.__name__,
            func_blob=self._func_blob,
            runtime_env=_prepare_runtime_env(opts.get("runtime_env")),
        )

    def remote(self, *args, **kwargs):
        global_worker.check_connected()
        cw = global_worker.core_worker
        tmpl = self._template
        if tmpl is None or tmpl.worker is not cw:
            # first call, new options, or a reconnect swapped the worker
            tmpl = self._template = self._build_template(cw)
        refs = cw.submit_from_template(tmpl, args, kwargs)
        if tmpl.num_returns in (1, -1):  # -1 = dynamic: one visible ref
            return refs[0]
        return refs

    def __getstate__(self):
        # a RemoteFunction captured in a task closure ships by value; the
        # template pins the local CoreWorker and must never ride along
        state = self.__dict__.copy()
        state["_template"] = None
        return state

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)


# ---------------------------------------------------------------------------
# Actors
# ---------------------------------------------------------------------------


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group
        self._template = None  # per-method submit template (lazy)

    def options(self, **opts):
        num_returns = opts.get("num_returns", self._num_returns)
        if num_returns == "dynamic":
            raise ValueError(
                "num_returns='dynamic' is not supported for actor tasks"
            )
        return ActorMethod(
            self._handle, self._name, num_returns=num_returns,
            concurrency_group=opts.get(
                "concurrency_group", self._concurrency_group
            ),
        )

    def remote(self, *args, **kwargs):
        return self._handle._invoke(
            self, args, kwargs
        )

    def __getstate__(self):
        # the template pins the local CoreWorker: never serialized (an
        # unpickled method rebuilds it lazily on first .remote())
        state = self.__dict__.copy()
        state["_template"] = None
        return state

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._name}' cannot be called directly; "
            f"use '.{self._name}.remote()'."
        )


class ActorHandle:
    """ray parity: python/ray/actor.py ActorHandle."""

    def __init__(self, actor_id: bytes, methods: Optional[dict] = None,
                 max_task_retries: int = 0,
                 method_groups: Optional[dict] = None,
                 concurrency_groups: Optional[dict] = None):
        self._actor_id = actor_id
        self._methods = methods or {}
        self._max_task_retries = max_task_retries
        self._method_groups = method_groups or {}
        self._concurrency_groups = concurrency_groups or {}

    def _invoke(self, method: "ActorMethod", args, kwargs):
        global_worker.check_connected()
        cw = global_worker.core_worker
        tmpl = method._template
        if tmpl is None or tmpl.worker is not cw:
            group = (method._concurrency_group
                     or self._method_groups.get(method._name))
            if group is not None and self._concurrency_groups and (
                group not in self._concurrency_groups
            ):
                raise ValueError(
                    f"concurrency group {group!r} not declared on this actor "
                    f"(declared: {sorted(self._concurrency_groups)})"
                )
            tmpl = method._template = cw.actor_task_template(
                self._actor_id,
                method._name,
                num_returns=method._num_returns,
                max_task_retries=self._max_task_retries,
                concurrency_group=group,
            )
        refs = cw.submit_actor_from_template(tmpl, args, kwargs)
        if method._num_returns == 1:
            return refs[0]
        return refs

    def __getattr__(self, name):
        # Underscore attributes must miss normally (pickle/IPython probe
        # private hooks like _repr_html_, and duck-typed hasattr checks rely
        # on AttributeError). Exception: the "_rt_" prefix is this framework's
        # convention for internal remote methods (e.g. _rt_init_collective).
        if name.startswith("_") and not name.startswith("_rt_"):
            raise AttributeError(name)
        method = ActorMethod(
            self, name, num_returns=self._methods.get(name, 1),
            concurrency_group=self._method_groups.get(name),
        )
        # memoize on the instance: later `handle.<name>` lookups hit the
        # instance dict directly (no __getattr__, no fresh ActorMethod per
        # call) and reuse the method's cached submit template. __reduce__
        # rebuilds handles from ids, so the cache never rides a pickle.
        self.__dict__[name] = method
        return method

    def __repr__(self):
        return f"ActorHandle({ActorID(self._actor_id).hex()[:16]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._methods,
                              self._max_task_retries, self._method_groups,
                              self._concurrency_groups))

    def _actor_id_hex(self):
        return ActorID(self._actor_id).hex()


class ActorClass:
    """ray parity: python/ray/actor.py ActorClass (remote/options)."""

    def __init__(self, cls, options: dict):
        self._cls = cls
        self._options = options
        self.__name__ = cls.__name__

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use '{self.__name__}.remote()'."
        )

    def options(self, **opts):
        _check_options(opts)
        return ActorClass(self._cls, {**self._options, **opts})

    def remote(self, *args, **kwargs):
        global_worker.check_connected()
        opts = self._options
        cw = global_worker.core_worker
        if opts.get("get_if_exists") and opts.get("name"):
            table = cw.get_actor_table(name=opts["name"], namespace=opts.get("namespace"))
            if table is not None:
                return ActorHandle(table["actor_id"],
                                   max_task_retries=opts.get("max_task_retries", 0))
        # Collect @ray_tpu.method(num_returns=N) annotations for the handle.
        method_returns = {
            name: getattr(m, "__ray_num_returns__")
            for name, m in vars(self._cls).items()
            if callable(m) and hasattr(m, "__ray_num_returns__")
        }
        # @ray_tpu.method(concurrency_group="io") annotations + the declared
        # groups (ray parity: concurrency_group_manager.h; groups are
        # enforced by per-group semaphores in executor.py).
        method_groups = {
            name: getattr(m, "__ray_concurrency_group__")
            for name, m in vars(self._cls).items()
            if callable(m) and hasattr(m, "__ray_concurrency_group__")
        }
        groups = dict(opts.get("concurrency_groups") or {})
        for gname, cap in groups.items():
            if not isinstance(cap, int) or cap < 1:
                raise ValueError(
                    f"concurrency_groups[{gname!r}] must be a positive int, "
                    f"got {cap!r}"
                )
        for mname, gname in method_groups.items():
            if gname not in groups:
                raise ValueError(
                    f"method {mname!r} declares concurrency_group {gname!r} "
                    f"but the actor only declares {sorted(groups)}"
                )
        actor_id = cw.create_actor(
            self._cls,
            args,
            kwargs,
            resources=_build_resources(opts, default_cpu=0.0),
            scheduling=_build_scheduling(opts),
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            concurrency_groups=groups,
            lifetime=opts.get("lifetime"),
            name=opts.get("name"),
            namespace=opts.get("namespace"),
            runtime_env=_prepare_runtime_env(opts.get("runtime_env")),
        )
        return ActorHandle(actor_id, methods=method_returns,
                           max_task_retries=opts.get("max_task_retries", 0),
                           method_groups=method_groups,
                           concurrency_groups=groups)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)


# ---------------------------------------------------------------------------
# @remote decorator
# ---------------------------------------------------------------------------


def remote(*args, **kwargs):
    """ray parity: ray.remote (python/ray/_private/worker.py:2952)."""

    def decorate(target, opts):
        import inspect

        if inspect.isclass(target):
            return ActorClass(target, opts)
        if callable(target):
            return RemoteFunction(target, opts)
        raise TypeError("@remote can only decorate functions or classes")

    if len(args) == 1 and not kwargs and callable(args[0]):
        return decorate(args[0], {})
    if args:
        raise TypeError("@remote takes keyword arguments only, e.g. @remote(num_cpus=2)")
    _check_options(kwargs)
    return lambda target: decorate(target, kwargs)


def method(**opts):
    """ray parity: ray.method — annotate num_returns / concurrency_group
    on actor methods."""

    def decorator(m):
        m.__ray_num_returns__ = opts.get("num_returns", 1)
        if "concurrency_group" in opts:
            m.__ray_concurrency_group__ = opts["concurrency_group"]
        return m

    return decorator
