"""Node providers: the cloud-side of the autoscaler.

ray parity: python/ray/autoscaler/node_provider.py:13 NodeProvider
(create_node/terminate_node/non_terminated_nodes) + the fake local
provider (autoscaler/_private/fake_multi_node/node_provider.py:237) that
backs autoscaler tests without a cloud.
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Minimal provider contract. ``node_type`` names an entry of the
    cluster config's available_node_types."""

    def create_node(self, node_type: str, count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """node_id -> node_type."""
        raise NotImplementedError


class MockProvider(NodeProvider):
    """In-memory provider for unit tests (ray: autoscaler_test_utils
    MockProvider)."""

    def __init__(self):
        self._nodes: Dict[str, str] = {}
        self.create_calls: List[tuple] = []
        self.terminate_calls: List[str] = []

    def create_node(self, node_type: str, count: int) -> List[str]:
        self.create_calls.append((node_type, count))
        out = []
        for _ in range(count):
            nid = f"mock-{uuid.uuid4().hex[:8]}"
            self._nodes[nid] = node_type
            out.append(nid)
        return out

    def terminate_node(self, node_id: str) -> None:
        self.terminate_calls.append(node_id)
        self._nodes.pop(node_id, None)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._nodes)


class FakeTpuPodProvider(NodeProvider):
    """Launches real local raylet processes advertising TPU-slice
    resources — autoscaler end-to-end without hardware or cloud APIs.

    Each created node is a NodeProcesses worker joining the given GCS,
    with the node type's resources (e.g. {"TPU": 8, "CPU": 8} for a
    v5e-8 slice) and a tpu-slice label carrying the type name.
    """

    def __init__(self, gcs_host: str, gcs_port: int, session_dir: str,
                 node_types: Dict[str, dict]):
        self.gcs_host = gcs_host
        self.gcs_port = gcs_port
        self.session_dir = session_dir
        self.node_types = node_types
        self._nodes: Dict[str, tuple] = {}  # provider_id -> (type, NodeProcesses)
        self._lock = threading.Lock()

    def create_node(self, node_type: str, count: int) -> List[str]:
        from ray_tpu._private.node import NodeProcesses

        spec = self.node_types[node_type]
        out = []
        for _ in range(count):
            node = NodeProcesses(
                head=False,
                gcs_host=self.gcs_host,
                gcs_port=self.gcs_port,
                session_dir=self.session_dir,
                resources=dict(spec.get("resources", {})),
                labels={"tpu-slice": node_type},
            )
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
            with self._lock:
                self._nodes[pid] = (node_type, node)
            out.append(pid)
        return out

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(node_id, None)
        if entry is not None:
            entry[1].shutdown()

    def non_terminated_nodes(self) -> Dict[str, str]:
        with self._lock:
            return {nid: t for nid, (t, _) in self._nodes.items()}

    def raylet_node_id(self, provider_id: str) -> Optional[str]:
        entry = self._nodes.get(provider_id)
        return entry[1].node_id if entry else None

    def shutdown(self):
        for nid in list(self._nodes):
            self.terminate_node(nid)


# ---------------------------------------------------------------------------
# TPU-pod provider: slice-granular provisioning over a Queued-Resources API
# ---------------------------------------------------------------------------

class QueuedResourceAPI:
    """Contract for the TPU Queued-Resources control plane (the GCP
    ``queuedResources`` surface GKE/Cloud-TPU provisioning goes through).
    One request provisions a WHOLE slice (accelerator_type + topology);
    hosts come up together and are deleted together.

    ray parity: python/ray/autoscaler/batching_node_provider.py — the
    provider batches by slice because the API has no smaller granularity.
    """

    def create(self, name: str, accelerator_type: str, topology: str,
               num_hosts: int) -> str:
        """Submit a provisioning request; returns a request id."""
        raise NotImplementedError

    def status(self, request_id: str) -> dict:
        """{"state": "PROVISIONING"|"ACTIVE"|"FAILED", "hosts": [...]}
        where hosts are opaque per-host handles once ACTIVE."""
        raise NotImplementedError

    def delete(self, request_id: str) -> None:
        raise NotImplementedError


class FakeQueuedResourceAPI(QueuedResourceAPI):
    """Backs TpuPodProvider without a cloud: 'provisioning' a slice
    launches one local raylet per host advertising that host's TPU
    resources, so autoscaler + placement tests run the real multi-host
    join path (analog of ray's fake_multi_node provider)."""

    def __init__(self, gcs_host: str, gcs_port: int, session_dir: str,
                 resources_per_host: Optional[Dict[str, dict]] = None):
        self.gcs_host = gcs_host
        self.gcs_port = gcs_port
        self.session_dir = session_dir
        # accelerator_type -> per-host resources override
        self.resources_per_host = resources_per_host or {}
        self._requests: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def create(self, name, accelerator_type, topology, num_hosts):
        from ray_tpu._private.node import NodeProcesses

        rid = f"qr-{uuid.uuid4().hex[:8]}"
        res = dict(self.resources_per_host.get(
            accelerator_type, {"TPU": 4.0, "CPU": 8.0}
        ))
        hosts = []
        for i in range(num_hosts):
            node = NodeProcesses(
                head=False,
                gcs_host=self.gcs_host,
                gcs_port=self.gcs_port,
                session_dir=self.session_dir,
                resources=res,
                labels={
                    "tpu-slice": name,
                    "tpu-accelerator": accelerator_type,
                    "tpu-topology": topology,
                    "tpu-worker-index": str(i),
                },
            )
            hosts.append(node)
        with self._lock:
            self._requests[rid] = {"state": "ACTIVE", "hosts": hosts,
                                   "name": name}
        return rid

    def status(self, request_id):
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                return {"state": "FAILED", "hosts": []}
            return {"state": req["state"], "hosts": list(req["hosts"])}

    def delete(self, request_id):
        with self._lock:
            req = self._requests.pop(request_id, None)
        for node in (req or {}).get("hosts", []):
            try:
                node.shutdown()
            except Exception:
                pass


class GkeQueuedResourceAPI(QueuedResourceAPI):
    """Real Cloud-TPU Queued-Resources REST surface
    (``https://tpu.googleapis.com/v2/.../queuedResources``). This image
    has no network egress, so calls construct the request and raise a
    clear error instead of silently hanging; deployments with egress and
    application-default credentials get working slice provisioning."""

    def __init__(self, project: str, zone: str, runtime_version: str =
                 "tpu-ubuntu2204-base", token_provider=None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self.token_provider = token_provider
        self.base = (f"https://tpu.googleapis.com/v2/projects/{project}"
                     f"/locations/{zone}/queuedResources")

    def _call(self, method: str, url: str, body: Optional[dict] = None):
        import json as _json
        import urllib.request

        if self.token_provider is None:
            raise RuntimeError(
                "GkeQueuedResourceAPI needs a token_provider (e.g. "
                "google.auth default credentials) and network egress; "
                "use FakeQueuedResourceAPI for offline clusters"
            )
        req = urllib.request.Request(
            url, method=method,
            data=_json.dumps(body).encode() if body is not None else None,
            headers={"Authorization": f"Bearer {self.token_provider()}",
                     "Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return _json.loads(resp.read() or b"{}")

    _ACCEL_GEN = {"v5litepod": "V5LITE_POD", "v5p": "V5P", "v4": "V4",
                  "v6e": "V6E", "v3": "V3", "v2": "V2"}

    def create(self, name, accelerator_type, topology, num_hosts):
        # acceleratorType and acceleratorConfig are mutually exclusive in
        # the v2 API; a topology request must go through acceleratorConfig
        # (with its required generation enum), otherwise name the type.
        node = {"runtimeVersion": self.runtime_version}
        gen = self._ACCEL_GEN.get(accelerator_type.split("-")[0])
        if topology and gen:
            node["acceleratorConfig"] = {"type": gen, "topology": topology}
        else:
            node["acceleratorType"] = accelerator_type
        body = {
            "tpu": {"nodeSpec": [{
                "parent": f"projects/{self.project}/locations/{self.zone}",
                "nodeId": name,
                "node": node,
            }]},
        }
        self._call("POST", f"{self.base}?queuedResourceId={name}", body)
        return name

    def status(self, request_id):
        out = self._call("GET", f"{self.base}/{request_id}")
        state = (out.get("state") or {}).get("state", "PROVISIONING")
        mapped = {"ACTIVE": "ACTIVE", "FAILED": "FAILED",
                  "SUSPENDED": "FAILED"}.get(state, "PROVISIONING")
        return {"state": mapped, "hosts": out.get("tpu", {}).get(
            "nodeSpec", [])}

    def delete(self, request_id):
        self._call("DELETE", f"{self.base}/{request_id}")


class TpuPodProvider(NodeProvider):
    """Slice-aware TPU-pod provider (SURVEY §7 stage 12): one provider
    node == one WHOLE slice provisioned through a Queued-Resources API.
    ``node_types`` entries describe slices:

        {"tpu_v5e_16": {"accelerator_type": "v5litepod-16",
                        "topology": "4x4", "hosts": 4,
                        "resources": {"TPU": 4.0, "CPU": 8.0},  # per host
                        "min_workers": 0, "max_workers": 2}}

    Pair with StandardAutoscaler (which bin-packs per host but launches
    per slice) and any QueuedResourceAPI implementation.
    """

    def __init__(self, api: QueuedResourceAPI, node_types: Dict[str, dict],
                 status_ttl_s: float = 2.0):
        self.api = api
        self.node_types = node_types
        self._slices: Dict[str, dict] = {}  # provider id -> {type, request}
        # status cache: one autoscaler update() touches each slice up to
        # 3 times (non_terminated_nodes, registration check, scale-down);
        # against a real REST API each uncached call is a blocking GET
        self._status_ttl_s = status_ttl_s
        self._status_cache: Dict[str, tuple] = {}  # id -> (ts, status)
        self._lock = threading.Lock()

    def _status(self, provider_id: str, request_id: str) -> dict:
        import time as _time

        now = _time.monotonic()
        with self._lock:
            hit = self._status_cache.get(provider_id)
            if hit is not None and now - hit[0] <= self._status_ttl_s:
                return hit[1]
        st = self.api.status(request_id)
        with self._lock:
            self._status_cache[provider_id] = (now, st)
        return st

    def create_node(self, node_type: str, count: int) -> List[str]:
        spec = self.node_types[node_type]
        out = []
        for _ in range(count):
            name = f"{node_type}-{uuid.uuid4().hex[:6]}"
            rid = self.api.create(
                name,
                spec.get("accelerator_type", node_type),
                spec.get("topology", ""),
                int(spec.get("hosts", 1)),
            )
            with self._lock:
                self._slices[name] = {"type": node_type, "request": rid}
            out.append(name)
        return out

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            entry = self._slices.pop(node_id, None)
            self._status_cache.pop(node_id, None)
        if entry is not None:
            self.api.delete(entry["request"])

    def non_terminated_nodes(self) -> Dict[str, str]:
        with self._lock:
            entries = dict(self._slices)
        out = {}
        for name, entry in entries.items():
            st = self._status(name, entry["request"])
            if st["state"] == "FAILED":
                with self._lock:
                    self._slices.pop(name, None)
                    self._status_cache.pop(name, None)
                continue
            out[name] = entry["type"]
        return out

    def raylet_node_ids(self, provider_id: str) -> List[str]:
        with self._lock:
            entry = self._slices.get(provider_id)
        if entry is None:
            return []
        st = self._status(provider_id, entry["request"])
        out = []
        for h in st.get("hosts", []):
            # FakeQueuedResourceAPI hosts are NodeProcesses objects; real
            # QR APIs return plain dicts
            nid = (h.get("node_id") if isinstance(h, dict)
                   else getattr(h, "node_id", None))
            out.append(nid)
        return out

    def shutdown(self):
        for nid in list(self._slices):
            self.terminate_node(nid)
