"""Node providers: the cloud-side of the autoscaler.

ray parity: python/ray/autoscaler/node_provider.py:13 NodeProvider
(create_node/terminate_node/non_terminated_nodes) + the fake local
provider (autoscaler/_private/fake_multi_node/node_provider.py:237) that
backs autoscaler tests without a cloud.
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Minimal provider contract. ``node_type`` names an entry of the
    cluster config's available_node_types."""

    def create_node(self, node_type: str, count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """node_id -> node_type."""
        raise NotImplementedError


class MockProvider(NodeProvider):
    """In-memory provider for unit tests (ray: autoscaler_test_utils
    MockProvider)."""

    def __init__(self):
        self._nodes: Dict[str, str] = {}
        self.create_calls: List[tuple] = []
        self.terminate_calls: List[str] = []

    def create_node(self, node_type: str, count: int) -> List[str]:
        self.create_calls.append((node_type, count))
        out = []
        for _ in range(count):
            nid = f"mock-{uuid.uuid4().hex[:8]}"
            self._nodes[nid] = node_type
            out.append(nid)
        return out

    def terminate_node(self, node_id: str) -> None:
        self.terminate_calls.append(node_id)
        self._nodes.pop(node_id, None)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._nodes)


class FakeTpuPodProvider(NodeProvider):
    """Launches real local raylet processes advertising TPU-slice
    resources — autoscaler end-to-end without hardware or cloud APIs.

    Each created node is a NodeProcesses worker joining the given GCS,
    with the node type's resources (e.g. {"TPU": 8, "CPU": 8} for a
    v5e-8 slice) and a tpu-slice label carrying the type name.
    """

    def __init__(self, gcs_host: str, gcs_port: int, session_dir: str,
                 node_types: Dict[str, dict]):
        self.gcs_host = gcs_host
        self.gcs_port = gcs_port
        self.session_dir = session_dir
        self.node_types = node_types
        self._nodes: Dict[str, tuple] = {}  # provider_id -> (type, NodeProcesses)
        self._lock = threading.Lock()

    def create_node(self, node_type: str, count: int) -> List[str]:
        from ray_tpu._private.node import NodeProcesses

        spec = self.node_types[node_type]
        out = []
        for _ in range(count):
            node = NodeProcesses(
                head=False,
                gcs_host=self.gcs_host,
                gcs_port=self.gcs_port,
                session_dir=self.session_dir,
                resources=dict(spec.get("resources", {})),
                labels={"tpu-slice": node_type},
            )
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
            with self._lock:
                self._nodes[pid] = (node_type, node)
            out.append(pid)
        return out

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(node_id, None)
        if entry is not None:
            entry[1].shutdown()

    def non_terminated_nodes(self) -> Dict[str, str]:
        with self._lock:
            return {nid: t for nid, (t, _) in self._nodes.items()}

    def raylet_node_id(self, provider_id: str) -> Optional[str]:
        entry = self._nodes.get(provider_id)
        return entry[1].node_id if entry else None

    def shutdown(self):
        for nid in list(self._nodes):
            self.terminate_node(nid)
