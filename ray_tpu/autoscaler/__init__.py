"""Autoscaler: demand-driven node launch/teardown.

ray parity: python/ray/autoscaler/_private/{autoscaler.py:166
StandardAutoscaler, resource_demand_scheduler.py:101, load_metrics.py:63,
monitor.py:126} with pluggable NodeProvider (node_provider.py:13). The
TPU-native delta: node types are TPU pod slices (a whole slice is the
scaling granularity — you can't add half a v5e-8), and the included
FakeTpuPodProvider launches local raylet processes advertising slice
resources so autoscaler end-to-end runs without cloud APIs (analog of
fake_multi_node/node_provider.py:237).
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import (
    FakeQueuedResourceAPI,
    FakeTpuPodProvider,
    GkeQueuedResourceAPI,
    MockProvider,
    NodeProvider,
    QueuedResourceAPI,
    TpuPodProvider,
)

__all__ = [
    "StandardAutoscaler",
    "NodeProvider",
    "MockProvider",
    "FakeTpuPodProvider",
    "QueuedResourceAPI",
    "FakeQueuedResourceAPI",
    "GkeQueuedResourceAPI",
    "TpuPodProvider",
]
