"""StandardAutoscaler: bin-pack pending demand onto node types.

ray parity: autoscaler/_private/autoscaler.py:166 StandardAutoscaler +
resource_demand_scheduler.py:101 (bin-packing of task/actor/PG demand
onto available_node_types) + load_metrics.py. One `update()` is one
reconciliation: read load from the GCS, launch nodes for unmet demand
(respecting per-type max_workers and min_workers floors), and terminate
nodes idle longer than idle_timeout_s.

Config shape (the available_node_types subset of ray's cluster YAML):

    {
      "tpu_v5e_8": {"resources": {"TPU": 8, "CPU": 8},
                     "min_workers": 0, "max_workers": 4},
      ...
    }

TPU-pod slice types add ``"hosts": N``: one launched unit is a WHOLE
slice of N hosts, each advertising ``resources`` (scale-up granularity
is the slice topology — you cannot ask a Queued-Resources API for half
a v5e-16). Bundles bin-pack per HOST: a {"TPU": 4} bundle fits one
v5e-16 host, but {"TPU": 16} fits no single host and is infeasible on
that type even though the slice aggregate is 16.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


def _fits(bundle: Dict[str, float], capacity: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in bundle.items())


def _consume(bundle: Dict[str, float], capacity: Dict[str, float]):
    for k, v in bundle.items():
        capacity[k] = capacity.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(
        self,
        provider,
        node_types: Dict[str, dict],
        *,
        gcs_address: Optional[str] = None,
        idle_timeout_s: float = 60.0,
        node_boot_grace_s: float = 120.0,
    ):
        self.provider = provider
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        # How long a launched node's config capacity counts toward demand
        # before it must have registered a raylet (prevents both relaunch
        # storms while booting AND permanent phantom capacity from nodes
        # the provider cannot correlate to raylets).
        self.node_boot_grace_s = node_boot_grace_s
        self._gcs_address = gcs_address
        self._launch_times: Dict[str, float] = {}
        self._io = None
        self._conn = None

    # -- load source ---------------------------------------------------
    def _load_metrics(self) -> dict:
        if self._gcs_address is None:
            return {"nodes": [], "pending_demand": []}
        from ray_tpu._private.rpcio import EventLoopThread, connect

        if self._io is None:
            self._io = EventLoopThread("autoscaler-io")
        if self._conn is None or self._conn.closed:
            host, port = self._gcs_address.rsplit(":", 1)
            self._conn = self._io.run(connect(host, int(port)))
        return self._io.run(self._conn.request("get_load_metrics", {}))

    # -- reconciliation ------------------------------------------------
    def update(self, load: Optional[dict] = None) -> dict:
        """One reconciliation pass; returns {"launched": {type: n},
        "terminated": [ids]} for observability/tests."""
        load = load if load is not None else self._load_metrics()
        running = self.provider.non_terminated_nodes()  # id -> type
        counts: Dict[str, int] = {}
        for t in running.values():
            counts[t] = counts.get(t, 0) + 1

        launched: Dict[str, int] = {}
        now = time.monotonic()
        # Provider nodes we did not launch this process-lifetime (restart,
        # min-floor races) must not default to age 0 forever: stamp unseen
        # ids ONCE at first sight so their grace window actually elapses.
        for nid in running:
            self._launch_times.setdefault(nid, now)
        # min_workers floors first.
        for node_type, spec in self.node_types.items():
            floor = spec.get("min_workers", 0)
            have = counts.get(node_type, 0)
            if have < floor:
                n = floor - have
                for new_id in self.provider.create_node(node_type, n):
                    self._launch_times[new_id] = now
                counts[node_type] = floor
                launched[node_type] = launched.get(node_type, 0) + n

        # Unmet demand: subtract what live nodes can still absorb, then
        # bin-pack the remainder onto node types (first-fit by type order).
        free: List[Dict[str, float]] = [
            dict(n["resources_available"]) for n in load.get("nodes", [])
        ]
        # Capacity of launched-but-not-yet-registered nodes counts too
        # (else every update re-launches for the same demand) — but only
        # within the boot grace window, so unmatched nodes don't become
        # permanent phantom capacity.
        for nid, t in running.items():
            if t not in self.node_types:
                continue
            spec = self.node_types[t]
            expected = int(spec.get("hosts", 1))
            matched = len(self._find_load_nodes(nid, load))
            age = now - self._launch_times.get(nid, now)
            if matched < expected and age <= self.node_boot_grace_s:
                # multi-host slices boot staggered: count bins only for
                # the hosts still missing, or one early-registering host
                # would erase its siblings' capacity and trigger a
                # duplicate (billed!) slice launch
                for _ in range(expected - matched):
                    free.append(dict(spec.get("resources", {})))

        # First-fit each bundle onto existing/just-launched capacity;
        # launch a new node only when nothing absorbs it. Demand arrives
        # aggregated by shape with counts.
        for shaped in load.get("pending_demand", []):
            bundle0 = shaped.get("bundle", shaped)
            count = int(shaped.get("count", 1)) if isinstance(shaped, dict) \
                and "bundle" in shaped else 1
            for _ in range(min(count, 1000)):
                bundle = dict(bundle0)
                placed = False
                for cap in free:
                    if _fits(bundle, cap):
                        _consume(bundle, cap)
                        placed = True
                        break
                if placed:
                    continue
                chosen = None
                for node_type, spec in self.node_types.items():
                    if counts.get(node_type, 0) >= spec.get("max_workers", 2**31):
                        continue
                    if _fits(bundle, dict(spec.get("resources", {}))):
                        chosen = node_type
                        break
                if chosen is None:
                    logger.warning(
                        "demand %s fits no launchable node type", bundle
                    )
                    break  # same shape won't fit on later iterations either
                for new_id in self.provider.create_node(chosen, 1):
                    self._launch_times[new_id] = now
                counts[chosen] = counts.get(chosen, 0) + 1
                launched[chosen] = launched.get(chosen, 0) + 1
                # The new unit absorbs this and possibly later bundles.
                # A slice type contributes one capacity bin PER HOST.
                spec = self.node_types[chosen]
                hosts = [dict(spec.get("resources", {}))
                         for _ in range(int(spec.get("hosts", 1)))]
                _consume(bundle, hosts[0])
                free.extend(hosts)

        # Scale down: provider nodes whose raylet has been idle past the
        # timeout, never below min_workers. Requires the provider to
        # correlate its nodes to raylets (raylet_node_id); providers that
        # can't are never scaled down from here.
        terminated: List[str] = []
        for nid, node_type in list(running.items()):
            spec = self.node_types.get(node_type, {})
            if counts.get(node_type, 0) <= spec.get("min_workers", 0):
                continue
            nodes = self._find_load_nodes(nid, load)
            # a multi-host slice terminates whole: only when every
            # EXPECTED host has registered AND been idle past the timeout
            # (a partially-booted slice's early host idling while its
            # gang peers provision must not kill the slice mid-boot)
            if nodes and len(nodes) >= int(spec.get("hosts", 1)) and all(
                n.get("idle_s", 0.0) > self.idle_timeout_s for n in nodes
            ):
                self.provider.terminate_node(nid)
                self._launch_times.pop(nid, None)
                counts[node_type] -= 1
                terminated.append(nid)
        return {"launched": launched, "terminated": terminated}

    def _registered(self, provider_id: str, load: dict) -> bool:
        return bool(self._find_load_nodes(provider_id, load))

    def _find_load_nodes(self, provider_id: str, load: dict) -> List[dict]:
        """Match a provider unit to its registered raylet(s). Providers
        implementing ``raylet_node_ids`` (slices) or ``raylet_node_id``
        match exactly; others return [] — such nodes count as booting
        only within the grace window and are never auto-terminated."""
        many = getattr(self.provider, "raylet_node_ids", None)
        if many is not None:
            ids = [i for i in (many(provider_id) or []) if i]
        else:
            one = getattr(self.provider, "raylet_node_id",
                          lambda _: None)(provider_id)
            ids = [one] if one else []
        by_id = {n["node_id"]: n for n in load.get("nodes", [])}
        return [by_id[i] for i in ids if i in by_id]

    def run_loop(self, interval_s: float = 5.0, stop_event=None):
        """Monitor loop (ray: monitor.py Monitor)."""
        while stop_event is None or not stop_event.is_set():
            try:
                self.update()
            except Exception:
                logger.exception("autoscaler update failed")
            time.sleep(interval_s)
