"""Autoscaler monitor process (ray parity:
autoscaler/_private/monitor.py — the process on the head node that runs
the StandardAutoscaler loop against the cluster's load metrics).

Launched by ``ray_tpu up``; SIGTERM tears down every provider node this
monitor launched (the launcher's ``down`` depends on that), then exits.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

logger = logging.getLogger("ray_tpu.autoscaler.monitor")


def _build_provider(cfg: dict, gcs_address: str, session_dir: str):
    from ray_tpu.autoscaler.node_provider import (
        FakeTpuPodProvider,
        MockProvider,
    )

    provider_cfg = cfg["provider"]
    kind = provider_cfg["type"]
    node_types = cfg.get("available_node_types") or {}
    host, port = gcs_address.rsplit(":", 1)
    if kind == "fake_tpu_pod":
        return FakeTpuPodProvider(host, int(port), session_dir, node_types)
    if kind == "mock":
        return MockProvider()
    if kind == "tpu_pod":
        from ray_tpu.autoscaler.node_provider import (
            GkeQueuedResourceAPI,
            TpuPodProvider,
        )

        api = GkeQueuedResourceAPI(
            project=provider_cfg["project"],
            zone=provider_cfg["zone"],
            runtime_version=provider_cfg.get(
                "runtime_version", "tpu-ubuntu2204-base"
            ),
            token_provider=_adc_token_provider(),
        )
        return TpuPodProvider(api, node_types)
    raise ValueError(f"unknown provider type {kind!r}")


def _adc_token_provider():
    """Application-default-credentials bearer tokens for the real GCP
    Queued-Resources API. Lazy: google-auth may be absent in offline
    images — the clear error then surfaces at the first API call (where
    GkeQueuedResourceAPI already raises with guidance), not at monitor
    boot."""
    try:
        import google.auth
        import google.auth.transport.requests
    except ImportError:
        return None

    creds, _project = google.auth.default(
        scopes=["https://www.googleapis.com/auth/cloud-platform"]
    )
    request = google.auth.transport.requests.Request()

    def token():
        if not creds.valid:
            creds.refresh(request)
        return creds.token

    return token


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--interval-s", type=float, default=5.0)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[monitor] %(levelname)s %(name)s: %(message)s",
    )

    from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
    from ray_tpu.autoscaler.commands import load_config

    cfg = load_config(args.config)
    provider = _build_provider(cfg, args.gcs_address, args.session_dir)
    idle_s = float(cfg.get("idle_timeout_minutes", 1)) * 60.0
    autoscaler = StandardAutoscaler(
        provider,
        cfg.get("available_node_types") or {},
        gcs_address=args.gcs_address,
        idle_timeout_s=idle_s,
    )

    stop = threading.Event()

    def _terminate(_sig, _frm):
        logger.info("SIGTERM: terminating provider nodes")
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    logger.info("monitor up: cluster=%s provider=%s interval=%.1fs",
                cfg["cluster_name"], cfg["provider"]["type"],
                args.interval_s)
    try:
        autoscaler.run_loop(interval_s=args.interval_s, stop_event=stop)
    finally:
        # down-path contract: this monitor owns the worker nodes it
        # launched; take them with us so `down` leaves nothing behind
        shutdown = getattr(provider, "shutdown", None)
        if shutdown is not None:
            try:
                shutdown()
            except Exception:
                logger.exception("provider shutdown failed")
        logger.info("monitor exit")


if __name__ == "__main__":
    main()
