"""Cluster launcher: ``ray_tpu up / down / status`` from a YAML config.

Reference parity: ray python/ray/autoscaler/_private/commands.py
(create_or_update_cluster / teardown_cluster) + the YAML schema in
python/ray/autoscaler/ray-schema.json, re-shaped TPU-first: instead of
SSH/docker node updaters (updater.py:39), workers are whole TPU slices
joining through a NodeProvider (FakeTpuPodProvider locally,
TpuPodProvider via the Queued-Resources API on GCP), and the autoscaler
runs as a monitor process next to the head (ray parity:
autoscaler/_private/monitor.py).

YAML schema (validated by ``validate_config``)::

    cluster_name: demo            # required
    max_workers: 8                # optional global cap
    idle_timeout_minutes: 1       # scale-down idle window
    provider:
      type: fake_tpu_pod          # fake_tpu_pod | tpu_pod | mock
      # tpu_pod only:
      #   project: my-proj
      #   zone: us-central2-b
      #   accelerator_type: v5litepod-8
      #   runtime_version: tpu-ubuntu2204-base
    head_node:
      resources: {CPU: 4}
    available_node_types:
      v5e_8:
        resources: {TPU: 8, CPU: 8}
        min_workers: 1
        max_workers: 4
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, Optional

_STATE_DIR = os.path.expanduser("~/.ray_tpu/clusters")


class ClusterConfigError(ValueError):
    pass


def load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    validate_config(cfg)
    return cfg


def validate_config(cfg: Dict[str, Any]) -> None:
    """Hand-rolled schema check (ray parity: ray-schema.json via
    jsonschema; same intent, no jsonschema dependency)."""
    if not isinstance(cfg, dict):
        raise ClusterConfigError("cluster config must be a mapping")
    name = cfg.get("cluster_name")
    if not name or not isinstance(name, str):
        raise ClusterConfigError("cluster_name (string) is required")
    provider = cfg.get("provider")
    if not isinstance(provider, dict) or "type" not in provider:
        raise ClusterConfigError("provider.type is required")
    if provider["type"] not in ("fake_tpu_pod", "tpu_pod", "mock"):
        raise ClusterConfigError(
            f"unknown provider.type {provider['type']!r} "
            f"(expected fake_tpu_pod | tpu_pod | mock)"
        )
    if provider["type"] == "tpu_pod":
        # accelerator_type/topology live PER NODE TYPE (a cluster mixes
        # slice shapes); only the project/zone routing is provider-level
        for key in ("project", "zone"):
            if key not in provider:
                raise ClusterConfigError(
                    f"provider.{key} is required for tpu_pod"
                )
    types = cfg.get("available_node_types") or {}
    if not isinstance(types, dict):
        raise ClusterConfigError("available_node_types must be a mapping")
    for tname, spec in types.items():
        if not isinstance(spec, dict) or "resources" not in spec:
            raise ClusterConfigError(
                f"available_node_types.{tname}.resources is required"
            )
        mn = int(spec.get("min_workers", 0))
        mx = int(spec.get("max_workers", max(mn, 1)))
        if mn < 0 or mx < mn:
            raise ClusterConfigError(
                f"available_node_types.{tname}: need 0 <= min_workers "
                f"<= max_workers (got {mn}, {mx})"
            )
    for key in ("max_workers",):
        if key in cfg and int(cfg[key]) < 0:
            raise ClusterConfigError(f"{key} must be >= 0")


def _state_path(name: str) -> str:
    os.makedirs(_STATE_DIR, exist_ok=True)
    return os.path.join(_STATE_DIR, f"{name}.json")


def _load_state(name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _save_state(name: str, state: Dict[str, Any]) -> None:
    with open(_state_path(name), "w") as f:
        json.dump(state, f, indent=1)


def _pid_start_time(pid: Optional[int]) -> Optional[int]:
    """Kernel start time (clock ticks since boot) of a pid — the identity
    check that makes persisted pids safe across reboots/recycling: a
    recycled pid has a different start time, so up/down never adopts or
    kills an unrelated process."""
    if not pid:
        return None
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
        # field 22, counted after the parenthesized comm (which may
        # itself contain spaces/parens)
        return int(stat.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


def _pid_alive(pid: Optional[int], start_time: Optional[int] = None) -> bool:
    if not pid:
        return False
    try:
        # reap first if it's our zombie child: kill(pid, 0) SUCCEEDS on
        # zombies, so a killed-but-unreaped monitor would read as alive
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass  # not our child (different launcher process): signal 0 is it
    except OSError:
        pass
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    if start_time is not None:
        now_start = _pid_start_time(pid)
        if now_start is not None and now_start != start_time:
            return False  # pid recycled by an unrelated process
    return True


def _stop_pid(pid: Optional[int], timeout_s: float,
              start_time: Optional[int] = None) -> None:
    """SIGTERM -> wait (reaping zombies) -> SIGKILL -> reap. With a
    recorded start_time, a recycled pid is never signalled."""
    if not _pid_alive(pid, start_time):
        return
    try:
        os.kill(pid, signal.SIGTERM)
    except OSError:
        return
    deadline = time.time() + timeout_s
    while _pid_alive(pid) and time.time() < deadline:
        time.sleep(0.2)
    if _pid_alive(pid):
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
        deadline = time.time() + 5.0
        while _pid_alive(pid) and time.time() < deadline:
            time.sleep(0.1)


def create_or_update_cluster(config_path: str,
                             no_monitor: bool = False) -> Dict[str, Any]:
    """``ray_tpu up``: start the head (idempotent — a live head is
    adopted, not replaced), then (re)start the autoscaler monitor that
    satisfies min_workers floors and scales with demand. Returns the
    cluster state dict."""
    cfg = load_config(config_path)
    name = cfg["cluster_name"]
    state = _load_state(name) or {}

    if state and _pid_alive(state.get("head_pid"),
                            state.get("head_pid_start")):
        print(f"cluster {name!r}: head already running at "
              f"{state['address']} (re-up reconciles the monitor only)")
    else:
        # dead head: an old monitor (and its provider nodes) would keep
        # running against the dead address forever — stop it before the
        # fresh state dict drops its pid
        if state and _pid_alive(state.get("monitor_pid"),
                                state.get("monitor_pid_start")):
            print(f"cluster {name!r}: stopping stale monitor "
                  f"(pid {state['monitor_pid']}) of the dead head")
            _stop_pid(state["monitor_pid"], 30.0,
                      state.get("monitor_pid_start"))
        from ray_tpu._private.node import NodeProcesses

        head_res = (cfg.get("head_node") or {}).get("resources")
        node = NodeProcesses(head=True, resources=head_res)
        state = {
            "cluster_name": name,
            "address": node.address,
            "session_dir": node.session_dir,
            "token_file": node.token_file,
            "head_pid": node.gcs_proc.pid,
            "head_pid_start": _pid_start_time(node.gcs_proc.pid),
            "head_pids": [node.gcs_proc.pid, node.raylet_proc.pid],
            "head_pid_starts": [
                _pid_start_time(node.gcs_proc.pid),
                _pid_start_time(node.raylet_proc.pid),
            ],
            "started_at": time.time(),
        }
        print(f"cluster {name!r}: head started at {node.address}")

    # (re)start the monitor: one per cluster; a live one is adopted
    if not no_monitor:
        if _pid_alive(state.get("monitor_pid"),
                      state.get("monitor_pid_start")):
            print(f"cluster {name!r}: monitor already running "
                  f"(pid {state['monitor_pid']})")
        else:
            log_path = os.path.join(state["session_dir"], "logs",
                                    "monitor.log")
            env = dict(os.environ)
            if state.get("token_file"):
                try:
                    with open(state["token_file"]) as f:
                        env["RAY_TPU_CLUSTER_TOKEN"] = f.read().strip()
                except OSError:
                    pass
            with open(log_path, "ab") as log:
                proc = subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu.autoscaler.monitor",
                     "--config", os.path.abspath(config_path),
                     "--gcs-address", state["address"],
                     "--session-dir", state["session_dir"]],
                    stdout=log, stderr=log, env=env,
                    start_new_session=True,
                )
            state["monitor_pid"] = proc.pid
            state["monitor_pid_start"] = _pid_start_time(proc.pid)
            print(f"cluster {name!r}: autoscaler monitor started "
                  f"(pid {proc.pid}, log {log_path})")
    state["config_path"] = os.path.abspath(config_path)
    _save_state(name, state)
    print(f"connect drivers with ray_tpu.init(address=\"{state['address']}\")")
    return state


def teardown_cluster(config_path: str, timeout_s: float = 30.0) -> None:
    """``ray_tpu down``: stop the monitor (it terminates provider nodes
    on SIGTERM), then the head processes, then drop the state file."""
    cfg = load_config(config_path)
    name = cfg["cluster_name"]
    state = _load_state(name)
    if state is None:
        print(f"cluster {name!r}: no recorded state — nothing to do")
        return
    mpid = state.get("monitor_pid")
    if _pid_alive(mpid, state.get("monitor_pid_start")):
        _stop_pid(mpid, timeout_s, state.get("monitor_pid_start"))
        print(f"cluster {name!r}: monitor stopped")
    starts = state.get("head_pid_starts") or [None] * len(
        state.get("head_pids", [])
    )
    for pid, st in zip(state.get("head_pids", []), starts):
        _stop_pid(pid, timeout_s, st)
    # Straggler sweep: if the monitor died (or was SIGKILLed past its
    # provider-shutdown finally), its worker raylets survive it — kill
    # anything still attached to this cluster's session dir so `down`
    # never leaks processes the state file is about to forget.
    session = state.get("session_dir", "")
    if session:
        subprocess.run(
            ["pkill", "-f",
             f"ray_tpu._private.*{os.path.basename(session)}"],
            check=False,
        )
    try:
        os.unlink(_state_path(name))
    except FileNotFoundError:
        pass
    print(f"cluster {name!r}: down")


def cluster_status(config_path: str, timeout_s: float = 15.0) -> Dict:
    """``ray_tpu status <yaml>``: live node table from the cluster's GCS
    plus launcher-side process state."""
    cfg = load_config(config_path)
    name = cfg["cluster_name"]
    state = _load_state(name)
    out: Dict[str, Any] = {"cluster_name": name, "up": False, "nodes": []}
    if state is None:
        print(f"cluster {name!r}: not started")
        return out
    out["address"] = state.get("address")
    out["head_alive"] = _pid_alive(state.get("head_pid"),
                                   state.get("head_pid_start"))
    out["monitor_alive"] = _pid_alive(state.get("monitor_pid"),
                                      state.get("monitor_pid_start"))
    out["up"] = out["head_alive"]
    if out["head_alive"]:
        from ray_tpu._private.rpcio import EventLoopThread, connect

        # THIS cluster's token, restored afterwards: caching the first
        # cluster's token into the process env would authenticate a later
        # status query against cluster B with cluster A's token
        token_file = state.get("token_file")
        prev_token = os.environ.get("RAY_TPU_CLUSTER_TOKEN")
        if token_file:
            try:
                with open(token_file) as f:
                    os.environ["RAY_TPU_CLUSTER_TOKEN"] = f.read().strip()
            except OSError:
                pass
        io = EventLoopThread("status-io")
        try:
            host, port = state["address"].rsplit(":", 1)
            conn = io.run(connect(host, int(port)), timeout=timeout_s)
            nodes = io.run(conn.request("get_nodes", {}),
                           timeout=timeout_s)
            out["nodes"] = nodes.get("nodes", nodes) \
                if isinstance(nodes, dict) else nodes
        except Exception as e:
            # head pid alive but GCS unreachable (hung, port gone): still
            # report what we know instead of dumping a traceback
            out["gcs_error"] = f"{type(e).__name__}: {e}"
        finally:
            io.stop()
            if prev_token is None:
                os.environ.pop("RAY_TPU_CLUSTER_TOKEN", None)
            else:
                os.environ["RAY_TPU_CLUSTER_TOKEN"] = prev_token
    print(f"cluster {name!r}: head={'UP' if out['head_alive'] else 'DOWN'} "
          f"monitor={'UP' if out['monitor_alive'] else 'DOWN'} "
          f"address={out.get('address')}")
    for n in out["nodes"]:
        nid = (n.get("node_id") or "")[:12]
        res = n.get("resources_total") or n.get("resources") or {}
        labels = n.get("labels") or {}
        slice_label = labels.get("tpu-slice", "")
        print(f"  node {nid}  alive={n.get('alive', n.get('state'))}  "
              f"resources={res}  {('slice=' + slice_label) if slice_label else ''}")
    return out
