"""Prometheus text exposition for the metrics surface.

ray parity: python/ray/_private/metrics_agent.py (OpenCensus → Prometheus
exporter on each node, scraped on :8080/metrics) — here one exposition
endpoint on the dashboard renders every published metric record plus the
cluster built-ins, so a stock Prometheus scrape_config pointed at the
dashboard works with no extra agent.
"""

from __future__ import annotations

import logging
import re
from typing import Dict, List

logger = logging.getLogger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt_labels(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(
        f'{_sanitize(k)}="{str(v).replace(chr(92), chr(92)*2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(tags.items())
    )
    return "{" + inner + "}"


def render_metrics(records: Dict[str, List[dict]]) -> str:
    """records: ``util.metrics.list_metrics()`` output — name -> list of
    per-process dumps. Counter/gauge series sum across processes;
    histograms merge bucket counts."""
    lines: List[str] = []
    for name, dumps in sorted(records.items()):
        pname = _sanitize(name)
        mtype = dumps[0].get("type", "gauge")
        help_text = (dumps[0].get("description") or "").replace("\n", " ")
        lines.append(f"# HELP {pname} {help_text}")
        lines.append(f"# TYPE {pname} {mtype}")
        if mtype in ("counter", "gauge"):
            agg: Dict[tuple, float] = {}
            for d in dumps:
                for s in d.get("series", []):
                    key = tuple(sorted(s["tags"].items()))
                    agg[key] = agg.get(key, 0.0) + float(s["value"])
            for key, v in sorted(agg.items()):
                lines.append(f"{pname}{_fmt_labels(dict(key))} {v}")
        elif mtype == "histogram":
            merged: Dict[tuple, dict] = {}
            for d in dumps:
                for s in d.get("series", []):
                    key = tuple(sorted(s["tags"].items()))
                    m = merged.setdefault(key, {
                        "boundaries": s["boundaries"],
                        "buckets": [0] * len(s["buckets"]),
                        "sum": 0.0, "count": 0,
                    })
                    if tuple(s["boundaries"]) != tuple(m["boundaries"]):
                        # summing bucket counts by index across differently
                        # bucketed declarations silently corrupts the merge,
                        # and emitting both would duplicate the labelset and
                        # invalidate the whole exposition — drop the
                        # mismatched dump and keep the endpoint scrapeable
                        logger.warning(
                            "histogram %s: conflicting bucket boundaries "
                            "across processes; dropping one dump", name)
                        continue
                    for i, c in enumerate(s["buckets"]):
                        m["buckets"][i] += c
                    m["sum"] += s["sum"]
                    m["count"] += s["count"]
            for key, m in sorted(merged.items()):
                tags = dict(key)
                cum = 0
                for bound, c in zip(m["boundaries"], m["buckets"]):
                    cum += c
                    lines.append(
                        f"{pname}_bucket"
                        f"{_fmt_labels({**tags, 'le': repr(float(bound))})} {cum}"
                    )
                cum += m["buckets"][-1]
                lines.append(
                    f"{pname}_bucket{_fmt_labels({**tags, 'le': '+Inf'})} {cum}"
                )
                lines.append(f"{pname}_sum{_fmt_labels(tags)} {m['sum']}")
                lines.append(f"{pname}_count{_fmt_labels(tags)} {m['count']}")
    return "\n".join(lines) + "\n"


def cluster_builtin_metrics() -> Dict[str, List[dict]]:
    """Synthesized cluster gauges (ray parity: metric_defs.h node/resource
    gauges the C++ core exports without user code)."""
    import time

    import ray_tpu
    from ray_tpu.util import state

    records: Dict[str, List[dict]] = {}

    def gauge(name, desc, series):
        records[name] = [{
            "name": name, "type": "gauge", "description": desc,
            "series": series, "ts": time.time(),
        }]

    nodes = ray_tpu.nodes()
    gauge("ray_tpu_node_count", "Cluster nodes by liveness", [
        {"tags": {"state": "alive"},
         "value": float(sum(1 for n in nodes if n["alive"]))},
        {"tags": {"state": "dead"},
         "value": float(sum(1 for n in nodes if not n["alive"]))},
    ])
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    gauge("ray_tpu_resources_total", "Cluster resource capacity", [
        {"tags": {"resource": k}, "value": float(v)} for k, v in total.items()
    ])
    gauge("ray_tpu_resources_available", "Cluster resources available", [
        {"tags": {"resource": k}, "value": float(v)} for k, v in avail.items()
    ])
    try:
        summary = state.summarize_tasks()  # name -> {state: count}
        by_state: Dict[str, float] = {}
        for entry in summary.values():
            for k, v in entry.items():
                if k != "total":
                    by_state[k] = by_state.get(k, 0.0) + v
        gauge("ray_tpu_tasks", "Task events by state", [
            {"tags": {"state": k}, "value": float(v)}
            for k, v in by_state.items()
        ])
    except Exception:
        pass
    try:
        actors = state.list_actors(limit=10_000)
        by_state: Dict[str, int] = {}
        for a in actors:
            by_state[a.get("state", "?")] = by_state.get(a.get("state", "?"), 0) + 1
        gauge("ray_tpu_actors", "Actors by state", [
            {"tags": {"state": k}, "value": float(v)}
            for k, v in by_state.items()
        ])
    except Exception:
        pass
    return records
