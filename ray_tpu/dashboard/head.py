"""Dashboard head: aiohttp JSON API over the state/metrics surfaces.

ray parity: dashboard/head.py:81 DashboardHead with the per-domain module
routes collapsed onto ray_tpu.util.state + util.metrics + the job
submission KV. Runs inside the driver process on its own thread (no
separate head process needed — the GCS connection is shared).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from collections import deque
from typing import Optional

_server = None

# In-head metrics history ring: one compact summary of the merged cluster
# scrape per metrics_history_interval_s tick, metrics_history_len deep
# (~10 min at the defaults). The SPA Metrics tab draws its sparkline
# time-series from this — the head is the one process with a stable
# vantage point, so reloading the page doesn't lose the series.
_metrics_history: deque = deque(maxlen=240)


def _json_response(payload, status: int = 200):
    from aiohttp import web

    return web.Response(
        text=json.dumps(payload, default=str),
        content_type="application/json",
        status=status,
    )


def _build_app():
    from aiohttp import web

    from ray_tpu.util import state

    routes = web.RouteTableDef()

    @routes.get("/")
    async def index(request):
        """Single-file UI over this JSON API (stands in for the
        reference's React client without a build toolchain)."""
        import os

        path = os.path.join(os.path.dirname(__file__), "static",
                            "index.html")
        with open(path) as f:
            return web.Response(text=f.read(), content_type="text/html")

    @routes.get("/api/v0/healthz")
    async def healthz(request):
        return _json_response({"status": "ok"})

    def _listing(fn):
        async def handler(request):
            limit = request.query.get("limit")
            rows = await asyncio.get_running_loop().run_in_executor(
                None, lambda: fn(limit=int(limit) if limit else None)
            )
            return _json_response(rows)

        return handler

    routes.get("/api/v0/nodes")(_listing(state.list_nodes))
    routes.get("/api/v0/actors")(_listing(state.list_actors))
    routes.get("/api/v0/tasks")(_listing(state.list_tasks))
    routes.get("/api/v0/placement_groups")(
        _listing(state.list_placement_groups)
    )
    routes.get("/api/v0/jobs")(_listing(state.list_jobs))

    # One memview_cluster scrape is a cluster-wide fan-out (every
    # raylet, worker, and driver): the objects and memory tabs polling
    # every 5s must share ONE recent scrape, not trigger one each. The
    # lock serializes concurrent misses (handlers run on executor
    # threads) so two viewers share a single fan-out.
    _memview_cache = {"ts": 0.0, "data": None}
    _memview_cache_lock = threading.Lock()

    def _object_summary_cached() -> dict:
        with _memview_cache_lock:
            now = time.monotonic()
            if _memview_cache["data"] is not None \
                    and now - _memview_cache["ts"] < 4.0:
                return _memview_cache["data"]
            data = state.object_summary()
            _memview_cache["ts"] = time.monotonic()
            _memview_cache["data"] = data
            return data

    @routes.get("/api/v0/objects")
    async def objects(request):
        """Object lifecycle rows from the memory observatory (state,
        size, owner, refs, locations, creation callsite). The bare GCS
        directory is the fallback BOTH when the memview scrape fails
        and when it has no rows — a native-store cluster
        (slab_arena=0) reports workers but no store ledger, and an
        empty lifecycle listing must not mask live directory entries."""
        limit = request.query.get("limit")
        limit = int(limit) if limit else 500

        def run():
            try:
                rows = (_object_summary_cached().get("objects")
                        or [])[:limit]
            except Exception:
                logging.getLogger(__name__).warning(
                    "memview scrape failed; serving the bare object "
                    "directory", exc_info=True)
                rows = []
            return rows or state.list_objects(limit=limit)

        out = await asyncio.get_running_loop().run_in_executor(None, run)
        return _json_response(out)

    @routes.get("/api/v0/memory")
    async def memory(request):
        """Memory observatory for the Memory tab: object lifecycle rows,
        per-node arena introspection (dead ranges, fragmentation, pool),
        the flow log, and leak/pressure verdicts — one memview_cluster
        scrape (what `ray_tpu memory` prints)."""
        group_by = request.query.get("group_by") or None

        def run():
            from ray_tpu._private import memview

            merged = dict(_object_summary_cached())
            if group_by:
                merged["groups"] = memview.group_objects(
                    merged.get("objects") or [], group_by)
            return merged

        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, run)
        except ValueError as e:
            return _json_response({"error": str(e)}, status=400)
        return _json_response(out)

    @routes.get("/api/v0/tasks/summarize")
    async def summarize(request):
        out = await asyncio.get_running_loop().run_in_executor(
            None, state.summarize_tasks
        )
        return _json_response(out)

    @routes.get("/api/v0/timeline")
    async def timeline(request):
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.timeline(None)
        )
        return _json_response(out)

    @routes.get("/api/v0/train")
    async def train_summary(request):
        """Step observatory summary for the Train tab: merged collectives
        with skew attribution, per-rank straggler scores, step phases,
        compile events (one steptrace_cluster scrape). This is a POLLING
        surface (5s SPA auto-refresh rendering only the top slices), so
        the merge is capped to the newest records by default; ?limit=0
        uncaps it."""
        try:
            limit = int(request.query.get("limit", "20000"))
        except ValueError:
            return _json_response({"error": "limit must be an integer"},
                                  status=400)
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.steptrace_summary(limit=limit or None)
        )
        return _json_response(out)

    @routes.get("/api/v0/train_timeline")
    async def train_timeline(request):
        """Merged multi-rank step timeline as Chrome-trace JSON
        (Perfetto-loadable; what `ray_tpu train timeline` writes)."""
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.train_timeline(None)
        )
        return _json_response(out)

    @routes.get("/api/v0/serve_requests")
    async def serve_requests(request):
        """Request observatory for the Serve tab: per-request phase
        rows joined by request id, per-deployment p50/p95/p99 + TTFT,
        per-replica phase profiles, and slow-replica skew verdicts (one
        reqtrace_cluster scrape — what `ray_tpu serve requests` prints).
        A POLLING surface (5s SPA auto-refresh), so the merge is capped
        to the newest records by default; ?limit=0 uncaps it."""
        try:
            limit = int(request.query.get("limit", "20000"))
        except ValueError:
            return _json_response({"error": "limit must be an integer"},
                                  status=400)
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.serve_summary(limit=limit or None)
        )
        return _json_response(out)

    @routes.get("/api/v0/serve_timeline")
    async def serve_timeline(request):
        """Merged per-request serve timeline as Chrome-trace JSON
        (Perfetto-loadable; what `ray_tpu serve timeline` writes)."""
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.request_timeline(None)
        )
        return _json_response(out)

    @routes.get("/api/v0/metrics")
    async def metrics(request):
        from ray_tpu.util import metrics as m

        out = await asyncio.get_running_loop().run_in_executor(
            None, m.list_metrics
        )
        return _json_response(out)

    @routes.get("/api/v0/serve_llm")
    async def serve_llm(request):
        """LLM serving slice of the cluster metrics scrape: KV page-state
        gauges, per-replica prefix hit rate, batch occupancy, token/shed
        counters — the same numbers `ray_tpu serve llm` prints."""
        from ray_tpu.util import metrics as m

        def _slice():
            return {name: entry.get("series", [])
                    for name, entry in m.metrics_summary().items()
                    if name.startswith(("kv_cache", "serve_llm"))}

        out = await asyncio.get_running_loop().run_in_executor(None, _slice)
        return _json_response(out)

    def _prom_text() -> str:
        """Merged cluster scrape (runtime + user metrics via the GCS
        fan-out) + synthesized cluster built-ins, as one exposition."""
        from ray_tpu._private import metrics_core
        from ray_tpu.dashboard.prometheus import (
            cluster_builtin_metrics,
            render_metrics,
        )
        from ray_tpu.util import metrics as m

        merged = m.cluster_snapshot().get("merged", {})
        records = metrics_core.snapshot_records(merged)
        records.update(cluster_builtin_metrics())
        return render_metrics(records)

    @routes.get("/metrics")
    async def prometheus_metrics(request):
        """Prometheus text exposition: runtime + user metrics from ONE
        cluster-wide scrape, plus cluster built-ins (ray parity: the
        per-node metrics agent's scrape endpoint, lifted cluster-wide)."""
        text = await asyncio.get_running_loop().run_in_executor(
            None, _prom_text)
        return web.Response(
            text=text, content_type="text/plain", charset="utf-8"
        )

    @routes.get("/api/metrics")
    async def api_metrics(request):
        """The same scrape as /metrics; ?format=json returns the compact
        summary (counters/gauges -> value, histograms -> p50/p95/p99)."""
        if request.query.get("format") == "json":
            from ray_tpu.util import metrics as m

            out = await asyncio.get_running_loop().run_in_executor(
                None, m.metrics_summary)
            return _json_response(out)
        text = await asyncio.get_running_loop().run_in_executor(
            None, _prom_text)
        return web.Response(text=text, content_type="text/plain",
                            charset="utf-8")

    @routes.get("/api/v0/metrics_history")
    async def metrics_history(request):
        """The in-head snapshot ring (see _metrics_history): a list of
        {ts, metrics} summaries the SPA renders as sparklines."""
        return _json_response(list(_metrics_history))

    @routes.get("/api/v0/logs")
    async def logs_listing(request):
        """Cluster log listing: head fans to every node agent
        (?node_id= narrows, prefix ok)."""
        node_id = request.query.get("node_id")
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.list_logs(node_id=node_id)
        )
        return _json_response(out)

    @routes.get("/api/v0/logs/tail")
    async def logs_tail(request):
        """Tail one log file anywhere in the cluster:
        ?node_id=&file=&lines=N."""
        q = request.query
        if not q.get("file"):
            return _json_response({"error": "file required"}, status=400)
        try:
            lines = int(q.get("lines", "100"))
        except ValueError:
            return _json_response({"error": "lines must be an integer"},
                                  status=400)
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: state.get_log(
                    filename=q["file"], node_id=q.get("node_id") or None,
                    tail=lines,
                )
            )
        except Exception as e:
            return _json_response({"error": str(e)}, status=404)
        return _json_response({"file": q["file"], "lines": out})

    @routes.get("/api/v0/logs/task")
    async def logs_task(request):
        """A task's exact output via its attribution span:
        ?task_id=<hex> (or ?actor_id=<hex> for the actor's worker log)."""
        q = request.query
        task_id = q.get("task_id") or None
        actor_id = q.get("actor_id") or None
        if not task_id and not actor_id:
            return _json_response({"error": "task_id or actor_id required"},
                                  status=400)
        try:
            tail = int(q["tail"]) if q.get("tail") else None
        except ValueError:
            return _json_response({"error": "tail must be an integer"},
                                  status=400)
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: state.get_log(task_id=task_id,
                                            actor_id=actor_id, tail=tail)
            )
        except Exception as e:
            return _json_response({"error": str(e)}, status=404)
        return _json_response({"task_id": task_id, "actor_id": actor_id,
                               "lines": out})

    @routes.get("/api/v0/stacks")
    async def stacks(request):
        node_id = request.query.get("node_id")
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.get_stacks(node_id=node_id)
        )
        return _json_response(out)

    @routes.get("/api/v0/events")
    async def events(request):
        from ray_tpu.util import events as ev

        limit = request.query.get("limit")
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: ev.list_events(limit=int(limit) if limit else 100)
        )
        return _json_response(out)

    @routes.get("/api/profile/cpu")
    async def profile_cpu(request):
        """On-demand cluster CPU flamegraph (ray parity: the dashboard's
        py-spy attach). ?duration=&hz=&node_id=&actor_id=&format=
        json|speedscope|collapsed."""
        from ray_tpu.util import profiling

        q = request.query
        try:
            duration = min(float(q.get("duration", 2.0)), 60.0)
            hz = float(q["hz"]) if q.get("hz") else None
        except ValueError:
            return _json_response(
                {"error": "duration and hz must be numbers"}, status=400)
        fmt = q.get("format", "json")

        def run():
            return profiling.profile_cpu(
                duration=duration,
                hz=hz,
                node_id=q.get("node_id") or None,
                actor_id=q.get("actor_id") or None,
                include_gcs=q.get("include_gcs") in ("1", "true"),
            )

        prof = await asyncio.get_running_loop().run_in_executor(None, run)
        if fmt == "speedscope":
            return _json_response(prof.speedscope())
        if fmt == "collapsed":
            return web.Response(text=prof.collapsed(),
                                content_type="text/plain")
        return _json_response(prof.raw)

    @routes.get("/api/profile/memory")
    async def profile_memory(request):
        """On-demand cluster memory diff (tracemalloc top-N sites).
        ?duration=&node_id=&actor_id=."""
        from ray_tpu.util import profiling

        q = request.query
        try:
            duration = min(float(q.get("duration", 2.0)), 60.0)
        except ValueError:
            return _json_response(
                {"error": "duration must be a number"}, status=400)

        def run():
            return profiling.profile_memory(
                duration=duration,
                node_id=q.get("node_id") or None,
                actor_id=q.get("actor_id") or None,
                include_gcs=q.get("include_gcs") in ("1", "true"),
            )

        prof = await asyncio.get_running_loop().run_in_executor(None, run)
        return _json_response(prof.raw)

    @routes.get("/api/v0/cluster_resources")
    async def cluster_resources(request):
        import ray_tpu

        loop = asyncio.get_running_loop()
        total = await loop.run_in_executor(None, ray_tpu.cluster_resources)
        avail = await loop.run_in_executor(None, ray_tpu.available_resources)
        return _json_response({"total": total, "available": avail})

    app = web.Application()
    app.add_routes(routes)
    return app


class _DashboardServer:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._loop = None
        self._error: Optional[BaseException] = None
        self._history_task = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="dashboard-head", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=30) or self._error is not None:
            raise RuntimeError(
                f"dashboard failed to start on {host}:{port}: "
                f"{self._error or 'timed out'}"
            )

    def _run(self):
        from aiohttp import web

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def serve():
            runner = web.AppRunner(_build_app())
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.port = site._server.sockets[0].getsockname()[1]
            self._started.set()
            self._history_task = asyncio.get_running_loop().create_task(
                self._history_loop())

        try:
            self._loop.run_until_complete(serve())
        except BaseException as e:  # surface bind/setup errors to __init__
            self._error = e
            self._started.set()
            return
        self._loop.run_forever()

    async def _history_loop(self):
        """Periodically fold one merged cluster scrape into the in-head
        ring (sparkline time-series source). Scrape failures (GCS
        restarting, teardown races) skip the tick — the ring must only
        ever hold real snapshots."""
        global _metrics_history

        from ray_tpu._private import metrics_core
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg

        # deque maxlen is fixed at construction: rebuild the ring to the
        # configured depth (the route reads the module global each call)
        keep = max(2, int(cfg.metrics_history_len))
        _metrics_history = deque(maxlen=keep)

        def scrape():
            from ray_tpu.util import metrics as m

            snap = m.cluster_snapshot()
            return {
                "ts": time.time(),
                "processes": sum(
                    1 for p in snap.get("processes", ())
                    if not p.get("error")),
                "metrics": metrics_core.summarize(snap.get("merged", {})),
            }

        loop = asyncio.get_running_loop()
        while True:
            # the master switch gates the recurring fan-out too — a
            # disabled plane must not keep paying the cluster scrape
            if cfg.metrics_enabled:
                try:
                    entry = await loop.run_in_executor(None, scrape)
                    _metrics_history.append(entry)
                except Exception:
                    pass
            await asyncio.sleep(cfg.metrics_history_interval_s)

    def _shutdown(self):
        # runs ON the loop: cancel the history task first so it unwinds
        # (its wakeup is queued ahead of the stop callback), then stop
        if self._history_task is not None:
            self._history_task.cancel()
        self._loop.call_soon(self._loop.stop)

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._shutdown)


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the JSON API server; returns the bound port. Requires a
    connected driver (ray_tpu.init first)."""
    global _server
    if _server is not None:
        return _server.port
    from ray_tpu._private.worker import global_worker

    global_worker.check_connected()
    server = _DashboardServer(host, port)  # raises on bind/setup failure
    _server = server
    return _server.port


def stop_dashboard():
    global _server
    if _server is not None:
        _server.stop()
        _server = None
