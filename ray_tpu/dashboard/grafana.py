"""Grafana dashboard factory.

ray parity: dashboard/modules/metrics/grafana_dashboard_factory.py — emit
a ready-to-import Grafana dashboard JSON wired to a Prometheus datasource
scraping this framework's ``/metrics`` endpoint (see
dashboard/prometheus.py). Panels cover the cluster built-ins plus any
user metric names passed in.
"""

from __future__ import annotations

import json
from typing import List, Optional


def _panel(panel_id: int, title: str, expr: str, y: int, x: int = 0,
           w: int = 12, h: int = 8, legend: str = "{{instance}}") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": h, "w": w, "x": x, "y": y},
        "targets": [{
            "expr": expr,
            "legendFormat": legend,
            "refId": "A",
        }],
        "fieldConfig": {"defaults": {"custom": {"fillOpacity": 10}}},
    }


def generate_dashboard(user_metrics: Optional[List[str]] = None) -> dict:
    """Dashboard dict; json.dump it and import into Grafana."""
    panels = [
        _panel(1, "Nodes by state", "ray_tpu_node_count", 0, 0,
               legend="{{state}}"),
        _panel(2, "Tasks by state", "ray_tpu_tasks", 0, 12,
               legend="{{state}}"),
        _panel(3, "Actors by state", "ray_tpu_actors", 8, 0,
               legend="{{state}}"),
        _panel(4, "Resources available vs total",
               "ray_tpu_resources_available", 8, 12,
               legend="{{resource}} available"),
    ]
    next_id, y = 5, 16
    for name in user_metrics or []:
        panels.append(_panel(next_id, name, name, y, (next_id % 2) * 12,
                             legend="{{__name__}}"))
        if next_id % 2:
            y += 8
        next_id += 1
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-cluster",
        "editable": True,
        "timezone": "browser",
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
        }]},
        "panels": panels,
        "schemaVersion": 39,
    }


def write_dashboard(path: str,
                    user_metrics: Optional[List[str]] = None) -> str:
    """Write the dashboard JSON next to a scrape config snippet; returns
    the dashboard path."""
    dash = generate_dashboard(user_metrics)
    with open(path, "w") as f:
        json.dump(dash, f, indent=1)
    return path
