"""Dashboard: cluster-state HTTP JSON API.

ray parity: dashboard/head.py DashboardHead + its module routes — per
SURVEY §7 the TS UI is deliberately out of scope; the dashboard starts as
the JSON API the reference modules serve (nodes, actors, tasks, jobs,
objects, placement groups, metrics, timeline, healthz). Any HTTP client
(or a Grafana JSON datasource) consumes it.

    from ray_tpu.dashboard import start_dashboard
    port = start_dashboard(port=8265)          # after ray_tpu.init()
    GET /api/v0/nodes  /api/v0/actors  /api/v0/tasks ...
"""

from ray_tpu.dashboard.head import start_dashboard, stop_dashboard

__all__ = ["start_dashboard", "stop_dashboard"]
