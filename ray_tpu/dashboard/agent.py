"""Per-node dashboard agent.

ray parity: dashboard/agent.py (the per-node agent process serving
node-local HTTP: stats, log listing/tailing, profiling) — one agent
subprocess per raylet, spawned and owned by it. Node-local data never
transits the head: operators (or the dashboard head acting as a proxy)
hit the agent directly.

Routes:
  GET /api/v0/node    — node stats (via the local raylet's node_stats RPC)
  GET /api/v0/stacks  — local workers' thread dumps
  GET /api/v0/profile?kind=cpu|mem&duration=N — node-local profiling
      window (raylet + its workers; see _private/profiler.py)
  GET /metrics        — node-local Prometheus scrape (raylet + workers,
      merged; also at /api/v0/metrics, ?format=json for raw snapshots)
  GET /api/v0/logs    — session log files (name, size)
  GET /api/v0/logs/tail?file=<name>&lines=N — tail one log file
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
from typing import Optional


def _json(payload, status=200):
    from aiohttp import web

    return web.Response(
        text=json.dumps(payload, default=str),
        content_type="application/json", status=status,
    )


class Agent:
    def __init__(self, raylet_port: int, session_dir: str):
        self.raylet_port = raylet_port
        self.session_dir = session_dir
        self._conn = None

    async def _raylet(self):
        if self._conn is None or self._conn.closed:
            from ray_tpu._private.rpcio import connect

            self._conn = await connect("127.0.0.1", self.raylet_port)
        return self._conn

    async def node(self, request):
        conn = await self._raylet()
        return _json(await conn.request("node_stats", {}, timeout=30))

    async def stacks(self, request):
        conn = await self._raylet()
        return _json(await conn.request("node_stacks", {}, timeout=30))

    async def profile(self, request):
        """Node-local profiling window (this raylet + its workers) —
        the per-node analog of the head's /api/profile/*:
        ?kind=cpu|mem&duration=&hz=."""
        q = request.query
        kind = q.get("kind", "cpu")
        if kind not in ("cpu", "mem"):
            return _json({"error": "kind must be cpu or mem"}, status=400)
        try:
            duration = min(float(q.get("duration", "2")), 60.0)
            hz = float(q["hz"]) if q.get("hz") else None
        except ValueError:
            return _json({"error": "duration and hz must be numbers"},
                         status=400)
        payload = {"kind": kind, "duration": duration}
        if hz is not None:
            payload["hz"] = hz
        conn = await self._raylet()
        reply = await conn.request("profile_node", payload,
                                   timeout=duration + 45)
        return _json(reply)

    async def metrics(self, request):
        """Node-local Prometheus scrape: this raylet + its workers,
        merged (the per-node analog of the head's /metrics — a stock
        Prometheus scrape_config can target every node agent directly).
        ?format=json returns the raw per-process snapshots."""
        from aiohttp import web

        from ray_tpu._private import metrics_core
        from ray_tpu.dashboard.prometheus import render_metrics

        conn = await self._raylet()
        reply = await conn.request("metrics_node", {}, timeout=30)
        processes = reply.get("processes") or []
        if request.query.get("format") == "json":
            return _json(reply)
        merged = metrics_core.merge_snapshots(
            [p.get("metrics") or {} for p in processes
             if not p.get("error")])
        text = render_metrics(metrics_core.snapshot_records(merged))
        return web.Response(text=text, content_type="text/plain",
                            charset="utf-8")

    async def logs(self, request):
        log_dir = os.path.join(self.session_dir, "logs")
        out = []
        try:
            for name in sorted(os.listdir(log_dir)):
                full = os.path.join(log_dir, name)
                if os.path.isfile(full):
                    out.append({"file": name, "bytes": os.path.getsize(full)})
        except OSError:
            pass
        return _json(out)

    async def tail(self, request):
        name = request.query.get("file", "")
        try:
            lines = int(request.query.get("lines", "100"))
        except ValueError:
            return _json({"error": "lines must be an integer"}, status=400)
        if "/" in name or name.startswith("."):
            return _json({"error": "bad file name"}, status=400)
        path = os.path.join(self.session_dir, "logs", name)
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 256 * 1024))
                text = f.read().decode("utf-8", "replace")
        except OSError:
            return _json({"error": "no such log"}, status=404)
        return _json({"file": name,
                      "lines": text.splitlines()[-lines:]})


async def amain(args) -> None:
    # Capture the owner's pid FIRST: if the raylet is SIGKILLed during our
    # startup window, a later getppid() would already read the reparented
    # value (1) and the orphan check below would never fire.
    ppid = os.getppid()

    from aiohttp import web

    agent = Agent(args.raylet_port, args.session_dir)
    app = web.Application()
    app.router.add_get("/api/v0/node", agent.node)
    app.router.add_get("/api/v0/stacks", agent.stacks)
    app.router.add_get("/api/v0/profile", agent.profile)
    app.router.add_get("/metrics", agent.metrics)
    app.router.add_get("/api/v0/metrics", agent.metrics)
    app.router.add_get("/api/v0/logs", agent.logs)
    app.router.add_get("/api/v0/logs/tail", agent.tail)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", args.port)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, args.port_file)
    # Park until the owning raylet goes away. Normal shutdown kills us
    # explicitly, but a SIGKILLed raylet (chaos tests, OOM killer) cannot —
    # detect that by watching for reparenting: the raylet spawns the agent
    # as a direct child, so a PPID change means the owner is gone. Without
    # this, every killed node leaks an agent process that lingers and
    # re-dials its old raylet port after the port number is recycled.
    while os.getppid() == ppid:
        await asyncio.sleep(2.0)


def main(argv: Optional[list] = None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--port-file", default="")
    args = parser.parse_args(argv)
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
