"""Per-node dashboard agent.

ray parity: dashboard/agent.py (the per-node agent process serving
node-local HTTP: stats, log listing/tailing, profiling) — one agent
subprocess per raylet, spawned and owned by it. Node-local data never
transits the head: operators (or the dashboard head acting as a proxy)
hit the agent directly.

Routes:
  GET /api/v0/node    — node stats (via the local raylet's node_stats RPC)
  GET /api/v0/stacks  — local workers' thread dumps
  GET /api/v0/profile?kind=cpu|mem&duration=N — node-local profiling
      window (raylet + its workers; see _private/profiler.py)
  GET /metrics        — node-local Prometheus scrape (raylet + workers,
      merged; also at /api/v0/metrics, ?format=json for raw snapshots)
  GET /api/v0/steptrace — node-local step-observatory rings (this
      raylet's workers; cross-rank skew merges at the GCS)
  GET /api/v0/memview — node-local memory observatory (this raylet's
      store ledger + arena introspection + its workers' owner tables;
      cluster-wide leak verdicts merge at the GCS)
  GET /api/v0/reqtrace — node-local request observatory (this raylet's
      workers' serve trace rings; cross-process request-id joins merge
      at the GCS)
  GET /api/v0/logs    — session log files (name, size)
  GET /api/v0/logs/tail?file=<name>&lines=N — tail one log file
  GET /api/v0/logs/range?file=<name>&start=A&end=B — exact byte range
      (the log plane's per-task attribution spans resolve through this)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
from typing import List, Optional, Tuple

# hard ceiling on one range/tail read (a bad span or a huge `lines`
# must not buffer an entire multi-GB log into one HTTP response)
MAX_READ_BYTES = 8 * 1024 * 1024


def _json(payload, status=200):
    from aiohttp import web

    return web.Response(
        text=json.dumps(payload, default=str),
        content_type="application/json", status=status,
    )


def safe_log_name(name: str) -> bool:
    """Session-log filenames only: no traversal, no absolute paths, no
    dotfiles (the token file lives one directory up)."""
    return bool(name) and "/" not in name and "\\" not in name \
        and not name.startswith(".")


def tail_file(path: str, lines: int) -> Tuple[List[str], int]:
    """Last ``lines`` full lines of ``path``. The read window SCALES with
    the request (doubling until enough newlines are in view or BOF) —
    the old fixed 256 KiB window silently truncated large requests — and
    a window that starts mid-file drops its torn leading partial line.
    Returns (lines, start_offset_of_first_returned_byte, end_offset)."""
    lines = max(1, min(int(lines), 100_000))
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        window = min(max(64 * 1024, lines * 256), MAX_READ_BYTES)
        while True:
            start = max(0, size - window)
            f.seek(start)
            data = f.read(size - start)
            # need lines+1 newlines so `lines` COMPLETE lines survive
            # dropping the torn head; at BOF or the byte ceiling, take
            # what is there
            if start == 0 or data.count(b"\n") > lines \
                    or window >= MAX_READ_BYTES:
                break
            window *= 2
    raw = data.split(b"\n")
    if start > 0:
        start += len(raw[0]) + 1
        raw = raw[1:]  # torn leading partial line
    if raw and not raw[-1]:
        raw.pop()  # trailing newline artifact
    cut = raw[-lines:]
    start += sum(len(r) + 1 for r in raw[: len(raw) - len(cut)])
    return [r.decode("utf-8", "replace") for r in cut], start, size


def read_range(path: str, start: int, end: int) -> bytes:
    """Exact byte range [start, end) of a log file, ceiling-capped."""
    start = max(0, int(start))
    end = max(start, int(end))
    with open(path, "rb") as f:
        f.seek(start)
        return f.read(min(end - start, MAX_READ_BYTES))


class Agent:
    def __init__(self, raylet_port: int, session_dir: str):
        self.raylet_port = raylet_port
        self.session_dir = session_dir
        self._conn = None

    async def _raylet(self):
        if self._conn is None or self._conn.closed:
            from ray_tpu._private.rpcio import connect

            self._conn = await connect("127.0.0.1", self.raylet_port)
        return self._conn

    async def node(self, request):
        conn = await self._raylet()
        return _json(await conn.request("node_stats", {}, timeout=30))

    async def stacks(self, request):
        conn = await self._raylet()
        return _json(await conn.request("node_stacks", {}, timeout=30))

    async def profile(self, request):
        """Node-local profiling window (this raylet + its workers) —
        the per-node analog of the head's /api/profile/*:
        ?kind=cpu|mem&duration=&hz=."""
        q = request.query
        kind = q.get("kind", "cpu")
        if kind not in ("cpu", "mem"):
            return _json({"error": "kind must be cpu or mem"}, status=400)
        try:
            duration = min(float(q.get("duration", "2")), 60.0)
            hz = float(q["hz"]) if q.get("hz") else None
        except ValueError:
            return _json({"error": "duration and hz must be numbers"},
                         status=400)
        payload = {"kind": kind, "duration": duration}
        if hz is not None:
            payload["hz"] = hz
        conn = await self._raylet()
        reply = await conn.request("profile_node", payload,
                                   timeout=duration + 45)
        return _json(reply)

    async def metrics(self, request):
        """Node-local Prometheus scrape: this raylet + its workers,
        merged (the per-node analog of the head's /metrics — a stock
        Prometheus scrape_config can target every node agent directly).
        ?format=json returns the raw per-process snapshots."""
        from aiohttp import web

        from ray_tpu._private import metrics_core
        from ray_tpu.dashboard.prometheus import render_metrics

        conn = await self._raylet()
        reply = await conn.request("metrics_node", {}, timeout=30)
        processes = reply.get("processes") or []
        if request.query.get("format") == "json":
            return _json(reply)
        merged = metrics_core.merge_snapshots(
            [p.get("metrics") or {} for p in processes
             if not p.get("error")])
        text = render_metrics(metrics_core.snapshot_records(merged))
        return web.Response(text=text, content_type="text/plain",
                            charset="utf-8")

    async def steptrace(self, request):
        """Node-local step-observatory snapshot: this raylet's workers'
        telemetry rings (collective ops, step phases, compile events) —
        the per-node analog of the head's /api/v0/train. Cross-rank skew
        needs the GCS merge; this surface is for poking one node."""
        conn = await self._raylet()
        return _json(await conn.request("steptrace_node", {}, timeout=30))

    async def memview(self, request):
        """Node-local memory observatory: the store ledger's object
        rows, arena segment introspection (dead ranges, pool, per-client
        charge), and this node's workers' owner tables — the per-node
        analog of the head's /api/v0/memory. Cluster-wide leak verdicts
        need the GCS merge; this surface is for poking one node."""
        conn = await self._raylet()
        return _json(await conn.request("memview_node", {}, timeout=30))

    async def reqtrace(self, request):
        """Node-local request-observatory snapshot: this raylet's
        workers' serve trace rings (proxies and replicas are actors in
        worker processes) — the per-node analog of the head's
        /api/v0/serve_requests. Cross-process request joins need the
        GCS merge; this surface is for poking one node."""
        conn = await self._raylet()
        return _json(await conn.request("reqtrace_node", {}, timeout=30))

    async def logs(self, request):
        log_dir = os.path.join(self.session_dir, "logs")
        out = []
        try:
            for name in sorted(os.listdir(log_dir)):
                full = os.path.join(log_dir, name)
                if os.path.isfile(full):
                    out.append({"file": name, "bytes": os.path.getsize(full)})
        except OSError:
            pass
        return _json(out)

    def _log_path(self, request):
        name = request.query.get("file", "")
        if not safe_log_name(name):
            return None, _json({"error": "bad file name"}, status=400)
        return os.path.join(self.session_dir, "logs", name), None

    async def tail(self, request):
        path, err = self._log_path(request)
        if err is not None:
            return err
        try:
            lines = int(request.query.get("lines", "100"))
        except ValueError:
            return _json({"error": "lines must be an integer"}, status=400)
        try:
            out, start, end = tail_file(path, lines)
        except OSError:
            return _json({"error": "no such log"}, status=404)
        return _json({"file": request.query["file"], "lines": out,
                      "start": start, "end": end})

    async def range(self, request):
        """Exact byte range of one log file — how per-task attribution
        spans (log_file, log_start, log_end on task events) resolve to
        the task's actual output."""
        path, err = self._log_path(request)
        if err is not None:
            return err
        try:
            start = int(request.query.get("start", "0"))
            end = int(request.query.get("end", "0"))
        except ValueError:
            return _json({"error": "start/end must be integers"}, status=400)
        try:
            data = read_range(path, start, end)
        except OSError:
            return _json({"error": "no such log"}, status=404)
        text = data.decode("utf-8", "replace")
        out = text.split("\n")
        if out and not out[-1]:
            out.pop()
        # end_complete: offset just past the last NEWLINE in the range —
        # followers resume there so a line caught mid-write is never
        # yielded as two torn halves
        last_nl = data.rfind(b"\n")
        end_complete = start + (last_nl + 1 if last_nl >= 0 else 0)
        return _json({"file": request.query["file"], "start": start,
                      "bytes": len(data), "end_complete": end_complete,
                      "lines": out})


async def amain(args) -> None:
    # Capture the owner's pid FIRST: if the raylet is SIGKILLed during our
    # startup window, a later getppid() would already read the reparented
    # value (1) and the orphan check below would never fire.
    ppid = os.getppid()

    from aiohttp import web

    agent = Agent(args.raylet_port, args.session_dir)
    app = web.Application()
    app.router.add_get("/api/v0/node", agent.node)
    app.router.add_get("/api/v0/stacks", agent.stacks)
    app.router.add_get("/api/v0/profile", agent.profile)
    app.router.add_get("/metrics", agent.metrics)
    app.router.add_get("/api/v0/metrics", agent.metrics)
    app.router.add_get("/api/v0/steptrace", agent.steptrace)
    app.router.add_get("/api/v0/memview", agent.memview)
    app.router.add_get("/api/v0/reqtrace", agent.reqtrace)
    app.router.add_get("/api/v0/logs", agent.logs)
    app.router.add_get("/api/v0/logs/tail", agent.tail)
    app.router.add_get("/api/v0/logs/range", agent.range)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", args.port)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, args.port_file)
    # Park until the owning raylet goes away. Normal shutdown kills us
    # explicitly, but a SIGKILLed raylet (chaos tests, OOM killer) cannot —
    # detect that by watching for reparenting: the raylet spawns the agent
    # as a direct child, so a PPID change means the owner is gone. Without
    # this, every killed node leaks an agent process that lingers and
    # re-dials its old raylet port after the port number is recycled.
    while os.getppid() == ppid:
        await asyncio.sleep(2.0)


def main(argv: Optional[list] = None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--port-file", default="")
    args = parser.parse_args(argv)
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
