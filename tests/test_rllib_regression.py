"""The tuned-examples regression harness itself (ray parity:
rllib/tests/run_regression_tests.py driven in CI)."""

import subprocess
import sys

import pytest


def test_all_configs_load_and_declare_thresholds():
    """Every tuned example must parse, name a known algorithm config,
    and declare a pass bar — catching registry rot without paying the
    full training cost here (the complete run is the release-
    qualification command: `python -m ray_tpu.rllib.run_regression`;
    each entry was validated green when added)."""
    import ray_tpu.rllib as rllib
    from ray_tpu.rllib.run_regression import (
        TUNED_EXAMPLES_DIR,
        load_experiments,
    )

    experiments = load_experiments(TUNED_EXAMPLES_DIR)
    assert len(experiments) >= 17, sorted(experiments)
    for name, spec in experiments.items():
        assert getattr(rllib, f"{spec['algorithm']}Config", None), name
        stop = spec.get("stop") or {}
        assert ("episode_return_mean" in stop
                or "evaluation_return_mean" in stop), name
        assert "training_iteration" in stop, name


@pytest.mark.slow
def test_run_regression_single_config_end_to_end():
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.rllib.run_regression",
         "--select", "cartpole-ppo"],
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "1/1 regression configs passed" in out.stdout, out.stdout


def test_select_filter_and_missing():
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.rllib.run_regression",
         "--select", "no-such-config"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 2
    assert "no experiments matched" in out.stdout
