"""The tuned-examples regression harness itself (ray parity:
rllib/tests/run_regression_tests.py driven in CI)."""

import subprocess
import sys


def test_run_regression_all_configs():
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.rllib.run_regression"],
        capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # count-agnostic: configs get added over time; all must pass
    import re

    m = re.search(r"(\d+)/(\d+) regression configs passed", out.stdout)
    assert m is not None, out.stdout
    assert m.group(1) == m.group(2) and int(m.group(2)) >= 3, out.stdout


def test_select_filter_and_missing():
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.rllib.run_regression",
         "--select", "no-such-config"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 2
    assert "no experiments matched" in out.stdout
