"""Core API tests (analog of ray: python/ray/tests/test_basic.py)."""

import time

import numpy as np
import pytest

import ray_tpu


def test_put_get(ray_start_regular):
    ref = ray_tpu.put(41)
    assert ray_tpu.get(ref) == 41
    arr = np.arange(1_000_000, dtype=np.float32)  # large -> plasma
    ref2 = ray_tpu.put(arr)
    out = ray_tpu.get(ref2)
    np.testing.assert_array_equal(out, arr)
    # zero-copy read: buffer should not be writable (mmap-backed view)
    assert not out.flags.writeable


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2


def test_task_with_ref_args(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    x = ray_tpu.put(10)
    y = add.remote(x, 5)
    z = add.remote(y, y)
    assert ray_tpu.get(z) == 30


def test_large_args_and_returns(ray_start_regular):
    @ray_tpu.remote
    def double(a):
        return a * 2

    arr = np.ones((512, 1024), dtype=np.float32)  # 2MB -> plasma
    ref = double.remote(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr * 2)


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kaput")

    with pytest.raises(ray_tpu.TaskError) as exc_info:
        ray_tpu.get(boom.remote())
    assert "kaput" in str(exc_info.value)
    assert isinstance(exc_info.value.cause, ValueError)


def test_dependent_task_inherits_error(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("upstream")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(consume.remote(boom.remote()))


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return "slow"

    a, b = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([a, b], num_returns=1, timeout=4)
    assert ready == [a]
    assert not_ready == [b]


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x * 10

    @ray_tpu.remote
    def outer(x):
        import ray_tpu as rt

        return rt.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(4)) == 41


def test_nested_object_refs(ray_start_regular):
    @ray_tpu.remote
    def make_refs():
        import ray_tpu as rt

        return [rt.put(1), rt.put(2)]

    refs = ray_tpu.get(make_refs.remote())
    assert ray_tpu.get(refs) == [1, 2]


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def sleepy():
        time.sleep(10)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(sleepy.remote(), timeout=0.5)


def test_many_small_tasks(ray_start_regular):
    @ray_tpu.remote
    def sq(i):
        return i * i

    refs = [sq.remote(i) for i in range(50)]
    assert ray_tpu.get(refs) == [i * i for i in range(50)]


def test_options_override(ray_start_regular):
    @ray_tpu.remote(num_cpus=1)
    def f():
        return "ok"

    assert ray_tpu.get(f.options(num_cpus=2).remote()) == "ok"


def test_custom_resources(ray_start_regular):
    @ray_tpu.remote(resources={"custom": 1})
    def f():
        return 1

    assert ray_tpu.get(f.remote()) == 1


def test_runtime_context(ray_start_regular):
    ctx = ray_tpu.get_runtime_context()
    assert ctx.node_id
    assert ctx.get_job_id()

    @ray_tpu.remote
    def get_task_id():
        return ray_tpu.get_runtime_context().get_task_id()

    assert ray_tpu.get(get_task_id.remote()) is not None
