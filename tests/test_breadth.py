"""Breadth-item tests: TensorBoard logger, GBDT gating, stack dumps.

Analog of ray: tune/tests/test_logger.py (TBX event files),
train gbdt trainer construction errors, and `ray stack` (worker thread
dumps via the dashboard reporter).
"""

import glob
import os

import pytest

import ray_tpu


def test_tbx_logger_writes_event_files(ray_start_regular, tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    from ray_tpu import tune
    from ray_tpu.air import RunConfig

    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * i})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(storage_path=str(tmp_path), name="tbx"),
    )
    results = tuner.fit()
    assert results.num_errors == 0
    events = glob.glob(
        os.path.join(str(tmp_path), "tbx", "**", "events.out.tfevents.*"),
        recursive=True,
    )
    assert len(events) >= 2, "expected one event file per trial"
    # event files have content (scalars were written + flushed)
    assert all(os.path.getsize(e) > 0 for e in events)


def test_gbdt_trainers_gate_without_libs():
    from ray_tpu.train import LightGBMTrainer, XGBoostTrainer

    have_xgb = True
    try:
        import xgboost  # noqa: F401
    except ImportError:
        have_xgb = False
    if not have_xgb:
        with pytest.raises(ImportError, match="xgboost"):
            XGBoostTrainer(params={}, datasets={}, label_column="y")
    have_lgbm = True
    try:
        import lightgbm  # noqa: F401
    except ImportError:
        have_lgbm = False
    if not have_lgbm:
        with pytest.raises(ImportError, match="lightgbm"):
            LightGBMTrainer(params={}, datasets={}, label_column="y")


@pytest.mark.skipif(
    not os.environ.get("RAY_TPU_TEST_XGB"),
    reason="xgboost not bundled in this image",
)
def test_xgboost_trainer_fits():  # pragma: no cover - gated
    import numpy as np
    import pandas as pd

    from ray_tpu import data as rd
    from ray_tpu.train import XGBoostTrainer

    df = pd.DataFrame({"a": np.arange(100.0), "y": np.arange(100.0) * 2})
    trainer = XGBoostTrainer(
        params={"objective": "reg:squarederror"},
        datasets={"train": rd.from_pandas(df)},
        label_column="y",
        num_boost_round=3,
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None


def test_stack_dump(ray_start_regular):
    import time

    from ray_tpu.util import state

    @ray_tpu.remote
    def slow():
        time.sleep(5.0)
        return 1

    ref = slow.remote()
    # poll until the worker is up and mid-task (cold spawn takes a moment)
    deadline = time.time() + 20
    workers = []
    while time.time() < deadline:
        stacks = state.get_stacks()
        assert stacks and not stacks[0].get("error")
        workers = stacks[0]["workers"]
        if workers and any(w.get("current_task") for w in workers):
            break
        time.sleep(0.5)
    assert workers, "no worker dumps returned"
    text = "\n".join(
        s for w in workers for s in w.get("threads", {}).values()
    )
    assert "time.sleep" in text or "sleep" in text
    assert any(w.get("current_task") for w in workers)
    assert ray_tpu.get(ref, timeout=60) == 1
