"""Native TPE searcher: convergence vs random search, domain handling, and
end-to-end Tuner integration (ray parity: hyperopt/optuna search_alg role)."""

import random
import statistics

from ray_tpu import tune
from ray_tpu.tune.search import TPESearcher
from ray_tpu.tune.search.tpe import _flatten, _unflatten


def _run_searcher(searcher, objective, space, n_trials, seed=0):
    searcher.set_search_properties("loss", "min", space)
    best = float("inf")
    for i in range(n_trials):
        tid = f"t{i}"
        config = searcher.suggest(tid)
        loss = objective(config)
        best = min(best, loss)
        searcher.on_trial_complete(tid, result={"loss": loss})
    return best


def test_flatten_roundtrip():
    space = {"a": 1, "b": {"c": 2, "d": {"e": 3}}}
    assert _unflatten(_flatten(space)) == space


def test_tpe_beats_random_on_quadratic():
    """Same budget, same objective: TPE's best-found should beat random
    search on average — the searcher actually models the observations."""

    def objective(cfg):
        return (cfg["x"] - 1.7) ** 2 + (cfg["y"] + 0.4) ** 2

    space = {"x": tune.uniform(-5, 5), "y": tune.uniform(-5, 5)}

    tpe_bests, rand_bests = [], []
    for seed in range(5):
        tpe = TPESearcher(n_initial_points=8, seed=seed)
        tpe_bests.append(_run_searcher(tpe, objective, space, 60))

        rng = random.Random(seed + 1000)
        best = float("inf")
        for _ in range(60):
            cfg = {k: d.sample(rng) for k, d in space.items()}
            best = min(best, objective(cfg))
        rand_bests.append(best)

    assert statistics.fmean(tpe_bests) < statistics.fmean(rand_bests), (
        tpe_bests, rand_bests,
    )


def test_tpe_handles_all_domain_kinds():
    space = {
        "lr": tune.loguniform(1e-5, 1e-1),
        "layers": tune.randint(1, 8),
        "opt": tune.choice(["adam", "sgd", "lamb"]),
        "drop": tune.quniform(0.0, 0.5, 0.1),
        "noise": tune.randn(0.0, 1.0),
        "fixed": 42,
        "derived": tune.sample_from(lambda spec: spec["fixed"] * 2),
        "nested": {"width": tune.lograndint(16, 1024)},
    }

    def objective(cfg):
        assert 1e-5 <= cfg["lr"] <= 1e-1
        assert 1 <= cfg["layers"] < 8 and isinstance(cfg["layers"], int)
        assert cfg["opt"] in ("adam", "sgd", "lamb")
        assert abs(cfg["drop"] * 10 - round(cfg["drop"] * 10)) < 1e-9
        assert cfg["fixed"] == 42
        assert cfg["derived"] == 84
        assert 16 <= cfg["nested"]["width"] <= 1024
        return cfg["lr"] * cfg["layers"]

    tpe = TPESearcher(n_initial_points=5, seed=3)
    best = _run_searcher(tpe, objective, space, 25)
    assert best < 1.0


def test_tpe_respects_mode_max():
    def objective(cfg):
        return -((cfg["x"] - 2.0) ** 2)  # maximum at x=2

    space = {"x": tune.uniform(-5, 5)}
    tpe = TPESearcher(n_initial_points=6, seed=7)
    tpe.set_search_properties("score", "max", space)
    xs = []
    for i in range(40):
        config = tpe.suggest(f"t{i}")
        tpe.on_trial_complete(f"t{i}", result={"score": objective(config)})
        xs.append(config["x"])
    # late suggestions should cluster near the optimum
    late = xs[-10:]
    assert abs(statistics.fmean(late) - 2.0) < 1.5, late


def test_tpe_in_tuner(ray_start_regular):
    def objective(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(-2, 2)},
        tune_config=tune.TuneConfig(
            num_samples=12, metric="loss", mode="min",
            search_alg=TPESearcher(n_initial_points=4, seed=0),
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 12 and grid.num_errors == 0
    assert grid.get_best_result().metrics["loss"] < 1.0
