"""Data tests (analog of ray: python/ray/data/tests/)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data


def test_range_count_take(ray_start_regular):
    d = data.range(100, parallelism=4)
    assert d.count() == 100
    assert d.num_blocks() == 4
    assert d.take(5) == [0, 1, 2, 3, 4]
    assert d.sum() == 4950.0
    assert d.min() == 0 and d.max() == 99
    assert d.mean() == 49.5


def test_from_items_rows(ray_start_regular):
    d = data.from_items([{"a": i} for i in range(10)], parallelism=2)
    assert d.count() == 10
    assert d.columns() == ["a"]
    assert d.take(2) == [{"a": 0}, {"a": 1}]


def test_map_filter_flatmap_fusion(ray_start_regular):
    d = (
        data.range(20, parallelism=2)
        .map(lambda x: x + 1)
        .filter(lambda x: x % 2 == 0)
        .flat_map(lambda x: [x, -x])
    )
    # map chain fuses into a single stage
    plan = d._plan().optimized()
    assert "->" in plan.dag.name
    rows = d.take_all()
    assert rows[:4] == [2, -2, 4, -4]
    assert len(rows) == 20


def test_map_batches_formats(ray_start_regular):
    d = data.range(10, parallelism=2)
    out = d.map_batches(lambda b: {"x": b * 2}, batch_format="numpy")
    assert out.take(3) == [{"x": 0}, {"x": 2}, {"x": 4}]

    out2 = d.map_batches(lambda df: df, batch_format="pandas")
    assert out2.count() == 10

    out3 = d.map_batches(lambda t: t, batch_format="pyarrow")
    assert out3.count() == 10


def test_map_batches_actor_pool(ray_start_regular):
    class Doubler:
        def __init__(self, k=2):
            self.k = k

        def __call__(self, batch):
            return {"x": batch * self.k}

    d = data.range(12, parallelism=3).map_batches(
        Doubler, concurrency=2, fn_constructor_kwargs={"k": 3},
        batch_format="numpy",
    )
    rows = d.take_all()
    assert sorted(r["x"] for r in rows) == [i * 3 for i in range(12)]


def test_groupby_aggregations(ray_start_regular):
    d = data.from_items(
        [{"k": i % 3, "v": float(i)} for i in range(30)], parallelism=3
    )
    counts = {r["k"]: r["count()"] for r in d.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}
    means = {r["k"]: r["mean(v)"] for r in d.groupby("k").mean("v").take_all()}
    assert means[0] == np.mean([i for i in range(30) if i % 3 == 0])


def test_groupby_map_groups(ray_start_regular):
    d = data.from_items([{"k": i % 2, "v": i} for i in range(10)], parallelism=2)
    out = d.groupby("k").map_groups(
        lambda t: [{"k": t.column("k")[0].as_py(), "n": t.num_rows}]
    )
    rows = sorted(out.take_all(), key=lambda r: r["k"])
    assert rows == [{"k": 0, "n": 5}, {"k": 1, "n": 5}]


def test_sort(ray_start_regular):
    d = data.from_items([{"a": (7 * i) % 20} for i in range(20)], parallelism=4)
    asc = [r["a"] for r in d.sort("a").take_all()]
    assert asc == sorted(asc)
    desc = [r["a"] for r in d.sort("a", descending=True).take_all()]
    assert desc == sorted(desc, reverse=True)


def test_random_shuffle_and_repartition(ray_start_regular):
    d = data.range(50, parallelism=5)
    sh = d.random_shuffle(seed=7)
    vals = sh.take_all()
    assert sorted(vals) == list(range(50))
    assert vals != list(range(50))
    rep = d.repartition(2)
    assert rep.num_blocks() == 2
    assert rep.count() == 50


def test_limit_union_zip(ray_start_regular):
    d = data.range(100, parallelism=4)
    assert d.limit(7).take_all() == list(range(7))
    assert d.union(data.range(5)).count() == 105
    z = data.range(5).zip(data.range(5).map(lambda x: x * 10))
    assert z.take_all() == [
        {"item": i, "item_1": i * 10} for i in range(5)
    ]


def test_iter_batches_rebatching(ray_start_regular):
    d = data.range(100, parallelism=7)  # uneven blocks
    sizes = [len(b) for b in d.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [
        len(b) for b in d.iter_batches(batch_size=32, drop_last=True)
    ]
    assert sizes == [32, 32, 32]


def test_split_and_streaming_split(ray_start_regular):
    d = data.range(30, parallelism=6)
    parts = d.split(3)
    assert sum(p.count() for p in parts) == 30
    eq = d.split(3, equal=True)
    assert [p.count() for p in eq] == [10, 10, 10]

    its = d.streaming_split(2)
    got = []
    for it in its:
        for batch in it.iter_batches(batch_size=None):
            got.extend(np.asarray(batch).tolist())
    assert sorted(got) == list(range(30))


def test_parquet_roundtrip(ray_start_regular, tmp_path):
    d = data.from_items([{"a": i, "b": str(i)} for i in range(25)],
                        parallelism=3)
    out = str(tmp_path / "pq")
    d.write_parquet(out)
    back = data.read_parquet(out)
    assert back.count() == 25
    assert sorted(r["a"] for r in back.take_all()) == list(range(25))


def test_csv_json_roundtrip(ray_start_regular, tmp_path):
    d = data.from_items([{"a": i, "b": i * 0.5} for i in range(10)],
                        parallelism=2)
    csv_dir = str(tmp_path / "csv")
    d.write_csv(csv_dir)
    assert data.read_csv(csv_dir).count() == 10

    json_dir = str(tmp_path / "json")
    d.write_json(json_dir)
    back = data.read_json(json_dir)
    assert back.count() == 10
    assert {r["a"] for r in back.take_all()} == set(range(10))


def test_from_pandas_numpy_arrow(ray_start_regular):
    import pandas as pd
    import pyarrow as pa

    df = pd.DataFrame({"x": [1, 2, 3]})
    assert data.from_pandas(df).count() == 3
    assert data.from_numpy(np.arange(5)).count() == 5
    assert data.from_arrow(pa.table({"y": [1, 2]})).count() == 2
    assert data.from_pandas(df).to_pandas()["x"].tolist() == [1, 2, 3]


def test_column_ops(ray_start_regular):
    d = data.from_items([{"a": i, "b": i * 2} for i in range(5)])
    out = d.add_column("c", lambda df: df["a"] + df["b"])
    assert out.take(1) == [{"a": 0, "b": 0, "c": 0}]
    assert out.select_columns(["c"]).columns() == ["c"]
    assert out.drop_columns(["c"]).columns() == ["a", "b"]
    assert set(out.rename_columns({"a": "z"}).columns()) == {"z", "b", "c"}


def test_unique_and_stats(ray_start_regular):
    d = data.from_items([{"a": i % 4} for i in range(16)])
    assert d.unique("a") == [0, 1, 2, 3]
    mat = d.materialize()
    assert "rows" in mat.stats()


def test_train_test_split(ray_start_regular):
    tr, te = data.range(100).train_test_split(test_size=0.25)
    assert tr.count() == 75 and te.count() == 25


def test_dataset_with_trainer(ray_start_regular):
    """datasets= flows into workers via train.get_dataset_shard."""
    from ray_tpu import train

    def loop(config):
        shard = train.get_dataset_shard("train")
        total = 0
        for batch in shard.iter_batches(batch_size=8):
            total += len(batch)
        train.report({"rows": total})

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ray_tpu.air.ScalingConfig(num_workers=2),
        datasets={"train": data.range(64, parallelism=4)},
    )
    result = trainer.fit()
    assert result.error is None
    # each worker saw a disjoint share; the LAST report is kept per worker —
    # both workers' rows sum to the dataset size
    assert result.metrics["rows"] * 2 == 64


def test_streaming_split_multi_epoch(ray_start_regular):
    """Each iter() over a split is one epoch; the coordinator re-executes
    (regression: second epoch silently yielded nothing)."""
    its = data.range(12, parallelism=4).streaming_split(2, equal=True)
    # epoch advance is a barrier: every consumer must drain its share
    # before the coordinator re-executes (lockstep train workers do).
    for epoch in range(3):
        for it in its:
            rows = []
            for b in it.iter_batches(batch_size=None):
                rows.extend(np.asarray(b).tolist())
            assert len(rows) == 6, (epoch, rows)


def test_streaming_split_equal_rows(ray_start_regular):
    """equal=True slices boundary blocks so every consumer sees the same
    row count (regression: flag was ignored)."""
    # 3 uneven blocks: 7, 2, 1 rows
    d = data.from_items(list(range(7))).union(
        data.from_items([7, 8]), data.from_items([9])
    ).materialize()
    its = d.streaming_split(2, equal=True)
    counts = []
    for it in its:
        n = 0
        for b in it.iter_batches(batch_size=None):
            n += len(b)
        counts.append(n)
    assert counts == [5, 5], counts


def test_tensor_columns_preserve_shape(ray_start_regular):
    """Multi-dim ndarray columns round-trip with shape (regression: was
    flattened to (N, prod))."""
    d = data.range(8, parallelism=2).map_batches(
        lambda b: {"img": np.ones((len(b), 4, 4), np.float32)},
        batch_format="numpy",
    )
    batch = d.take_batch(8)
    assert batch["img"].shape == (8, 4, 4)
    t = data.range_tensor(6, shape=(2, 3))
    assert t.take_batch(6)["data"].shape == (6, 2, 3)


def test_groupby_string_keys_across_processes(ray_start_regular):
    """Hash partitioning must be deterministic across worker processes
    (regression: builtin hash() salting split string-key groups)."""
    d = data.from_items(
        [{"k": f"key-{i % 3}", "v": i} for i in range(30)], parallelism=3
    )
    rows = d.groupby("k").count().take_all()
    assert len(rows) == 3, rows
    assert {r["count()"] for r in rows} == {10}, rows


def test_streaming_split_abandoned_epoch(ray_start_regular):
    """Breaking out of an epoch early must not deadlock the next epoch
    (regression: leftover items blocked the epoch barrier)."""
    its = data.range(12, parallelism=4).streaming_split(1)
    it = iter(its[0]._source)
    next(it)  # consume one block, abandon the rest
    rows = []
    for b in its[0].iter_batches(batch_size=None):  # epoch 2
        rows.extend(np.asarray(b).tolist())
    assert sorted(rows) == list(range(12)), rows


def test_iter_torch_batches(ray_start_regular):
    import torch

    from ray_tpu import data

    ds = data.from_items([{"x": float(i), "y": i} for i in range(10)])
    batches = list(ds.iterator().iter_torch_batches(
        batch_size=4, dtypes={"x": torch.float32}
    ))
    assert len(batches) == 3
    assert isinstance(batches[0]["x"], torch.Tensor)
    assert batches[0]["x"].dtype == torch.float32
    total = sum(int(b["y"].sum()) for b in batches)
    assert total == sum(range(10))


def test_iter_jax_batches_with_sharding(ray_start_regular):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from ray_tpu import data, parallel

    mesh = parallel.create_mesh({"data": 8})
    sh = NamedSharding(mesh, PartitionSpec("data"))
    ds = data.from_items([{"x": float(i)} for i in range(16)])
    batches = list(ds.iterator().iter_jax_batches(batch_size=8, sharding=sh))
    assert len(batches) == 2
    b = batches[0]["x"]
    assert isinstance(b, jax.Array) and b.sharding == sh
    total = sum(float(np.asarray(jax.device_get(bt["x"])).sum())
                for bt in batches)
    assert total == float(sum(range(16)))

    # partial final batch: with a sharding, drop_last defaults True so the
    # non-divisible remainder is dropped instead of crashing device_put
    ds10 = data.from_items([{"x": float(i)} for i in range(10)])
    b10 = list(ds10.iterator().iter_jax_batches(batch_size=8, sharding=sh))
    assert len(b10) == 1 and b10[0]["x"].shape == (8,)
