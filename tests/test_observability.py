"""Observability export tests.

Analog of ray: python/ray/tests/test_metrics_agent.py (Prometheus scrape),
test_logging.py (worker stdout reaches the driver), and the event
aggregator tests — Prometheus text endpoint on the dashboard, structured
cluster events, and raylet log tailing to driver-subscribed pubsub.
"""

import time

import pytest
import requests

import ray_tpu


@pytest.fixture(scope="module")
def obs_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_prometheus_endpoint(obs_cluster):
    from ray_tpu.dashboard.head import start_dashboard, stop_dashboard
    from ray_tpu.util.metrics import Counter

    c = Counter("test_requests_total", "test counter", tag_keys=("route",))
    c.inc(3, tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    from ray_tpu.util import metrics as m

    m.flush()
    port = start_dashboard()
    try:
        text = requests.get(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ).text
        assert "# TYPE test_requests_total counter" in text
        assert 'test_requests_total{route="/a"} 3.0' in text
        # cluster built-ins render without user code
        assert 'ray_tpu_node_count{state="alive"} 1.0' in text
        assert "ray_tpu_resources_total" in text
    finally:
        stop_dashboard()


def test_structured_events(obs_cluster):
    from ray_tpu.util import events as ev

    ev.record_event("deploy finished", severity="INFO", label="DEPLOY",
                    version="1.2.3")
    rows = ev.list_events(limit=50)
    labels = [r["label"] for r in rows]
    assert "DEPLOY" in labels
    mine = next(r for r in rows if r["label"] == "DEPLOY")
    assert mine["fields"]["version"] == "1.2.3"
    # the GCS recorded the node joining as an event
    assert any(r["label"] == "NODE_ADDED" for r in ev.list_events(
        source="gcs", limit=50
    ))
    with pytest.raises(ValueError):
        ev.record_event("bad", severity="LOUD")


def test_oom_kill_records_event(obs_cluster, tmp_path):
    """The memory-monitor kill path emits a WORKER_OOM_KILLED event."""
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg
    from ray_tpu.util import events as ev

    # (covered end-to-end in test_object_plane's monitor test; here just
    # assert the query path filters correctly on an empty result)
    rows = ev.list_events(severity="FATAL", limit=10)
    assert rows == []


def test_worker_logs_reach_driver(obs_cluster, capfd):
    @ray_tpu.remote
    def shouty():
        print("HELLO-FROM-WORKER-STDOUT-12321")
        return 1

    assert ray_tpu.get(shouty.remote(), timeout=60) == 1
    # the raylet tails the worker log on log_tail_interval_s; wait for the
    # pubsub line to arrive and be printed by the driver's subscriber
    deadline = time.time() + 10
    seen = ""
    while time.time() < deadline:
        out = capfd.readouterr()
        seen += out.out + out.err
        if "HELLO-FROM-WORKER-STDOUT-12321" in seen:
            break
        time.sleep(0.2)
    assert "HELLO-FROM-WORKER-STDOUT-12321" in seen
    assert "pid=" in seen  # prefixed with worker identity
