"""ResourceChangingScheduler (ray parity:
tune/schedulers/resource_changing_scheduler.py)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers.resource_changing import (
    DistributeResources,
    ResourceChangingScheduler,
)
from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


@pytest.fixture(scope="module")
def ray_start_regular():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_reallocation_unit():
    """Direct scheduler-interface drive: after the interval, the policy's
    allocation is applied through controller.change_trial_resources."""

    class _Trial:
        def __init__(self, tid):
            self.trial_id = tid
            self.resources = {"CPU": 1.0}
            self.status = "RUNNING"

    class _Controller:
        def __init__(self, trials):
            self.trials = trials
            self.changes = []

        def change_trial_resources(self, trial, resources):
            self.changes.append((trial.trial_id, dict(resources)))
            trial.resources = dict(resources)
            return True

    t = _Trial("a")
    ctl = _Controller([t])

    def alloc(controller, trial, base):
        return {"CPU": 3.0}

    sched = ResourceChangingScheduler(
        resources_allocation_function=alloc, reallocate_interval=2,
        metric="m", mode="max",
    )
    sched.on_trial_add(ctl, t)
    assert sched.on_trial_result(ctl, t, {"m": 1}) == TrialScheduler.CONTINUE
    assert not ctl.changes  # below the interval
    sched.on_trial_result(ctl, t, {"m": 2})
    assert ctl.changes == [("a", {"CPU": 3.0})]
    assert sched.num_resource_changes == 1
    # no further change while the allocation is already in effect
    sched.on_trial_result(ctl, t, {"m": 3})
    sched.on_trial_result(ctl, t, {"m": 4})
    assert sched.num_resource_changes == 1


def test_distribute_resources_floor():
    class _Trial:
        def __init__(self, tid):
            self.trial_id = tid
            self.resources = {"CPU": 1.0}
            self.status = "RUNNING"

    class _Controller:
        def __init__(self, trials):
            self.trials = trials

    # 2 live trials over a 4-CPU cluster -> 2 CPUs each (floor 1)
    a, b = _Trial("a"), _Trial("b")
    ray_tpu.init(num_cpus=4)
    try:
        out = DistributeResources()(_Controller([a, b]), a, {"CPU": 1.0})
        assert out == {"CPU": 2.0}
        # a single survivor absorbs the whole cluster
        out = DistributeResources()(_Controller([a]), a, {"CPU": 1.0})
        assert out == {"CPU": 4.0}
    finally:
        ray_tpu.shutdown()


def test_e2e_survivor_absorbs_capacity(ray_start_regular):
    """Two trials on a 4-CPU cluster: once the short trial finishes, the
    survivor's next reallocation bumps it past its base request, and the
    trial keeps training through the checkpoint/restart."""

    def objective(config):
        ck = tune.get_checkpoint()
        start = ck.to_dict()["i"] if ck else 0
        for i in range(start, config["steps"]):
            tune.report(
                {"step": i + 1},
                checkpoint=ray_tpu.air.Checkpoint.from_dict({"i": i + 1}),
            )

    sched = ResourceChangingScheduler(
        reallocate_interval=3, metric="step", mode="max",
    )
    grid = tune.Tuner(
        objective,
        param_space={"steps": tune.grid_search([3, 25])},
        tune_config=tune.TuneConfig(
            scheduler=sched, metric="step", mode="max",
            max_concurrent_trials=2,
        ),
    ).fit()
    # both trials ran to completion despite mid-run restarts; a trial
    # restored right at its end still ends with its real last metrics
    # (persisted through the function-trainable checkpoint)
    assert all(r.error is None for r in grid)
    assert sorted(r.metrics["step"] for r in grid) == [3, 25]
    assert sched.num_resource_changes >= 1
