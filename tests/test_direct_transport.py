"""Direct task push over worker leases (ray parity:
src/ray/core_worker/transport/direct_task_transport.cc)."""

import time

import ray_tpu


def _stats(port):
    from ray_tpu._private.rpcio import EventLoopThread, connect

    io = EventLoopThread("probe")
    try:
        c = io.run(connect("127.0.0.1", port, retries=2))
        st = io.run(c.request("node_stats", {}))
        io.run(c.close())
        return st
    finally:
        io.stop()


def test_lease_lifecycle_and_resource_return(ray_start_regular_fn):
    """A task burst leases workers (reserving CPUs); after the linger
    expires the leases return — resources and idle workers come back."""
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get([f.remote(i) for i in range(40)], timeout=60) == [
        i * 2 for i in range(40)
    ]
    port = global_worker.node.raylet_port

    # linger (0.5s default) holds the lease briefly, then it returns
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = _stats(port)
        avail = st["resources_available"].get("CPU", 0)
        total = st["resources_total"].get("CPU", 0)
        if avail == total and st["num_idle_workers"] >= 1:
            break
        time.sleep(0.5)
    else:
        raise AssertionError(f"leases never returned: {st}")


def test_direct_falls_back_for_special_strategies(ray_start_regular_fn):
    """SPREAD / affinity / PG strategies stay raylet-routed (placement
    decisions are the raylet's), while DEFAULT tasks push direct —
    results must be identical either way."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    @ray_tpu.remote
    def whoami():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    me = ray_tpu.get(whoami.remote(), timeout=60)  # direct path
    pinned = ray_tpu.get(
        whoami.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=me, soft=False
            )
        ).remote(),
        timeout=60,
    )  # raylet path
    spread = ray_tpu.get(
        whoami.options(scheduling_strategy="SPREAD").remote(), timeout=60
    )
    assert me == pinned == spread


def test_direct_disabled_flag(ray_start_cluster, monkeypatch):
    """RAY_TPU_direct_task_leases=0 forces the legacy raylet path for
    everything — the compatibility escape hatch must keep working."""
    monkeypatch.setenv("RAY_TPU_direct_task_leases", "0")
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(10)], timeout=60) == [
        i + 1 for i in range(10)
    ]
