"""External-env sampling (ray parity: rllib/env/policy_server_input.py +
policy_client.py): a client-owned env loop drives episodes over HTTP
against policy-server runners; the algorithm trains from that traffic."""

import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import DQNConfig, PolicyClient
from ray_tpu.rllib.env import make_env


def _client_env_loop(address: str, episodes: int, out: dict):
    """The application side: owns a real CartPole, asks the server for
    every action, reports rewards — no algorithm imports."""
    client = PolicyClient(address)
    env = make_env("CartPole-native")
    returns = []
    for _ in range(episodes):
        eid = client.start_episode()
        obs, _ = env.reset()
        total, done, trunc, steps = 0.0, False, False, 0
        while not (done or trunc) and steps < 200:
            a = client.get_action(eid, obs)
            obs, r, done, trunc, _ = env.step(a)
            client.log_returns(eid, r)
            total += r
            steps += 1
        client.end_episode(eid, obs)
        returns.append(total)
    out["returns"] = returns


@pytest.mark.slow
def test_policy_server_end_to_end(ray_start_regular):
    algo = (
        DQNConfig()
        .environment("CartPole-native")  # spaces only; never stepped
        .env_runners(num_env_runners=1, rollout_fragment_length=64,
                     policy_server_port=0)
        .training(minibatch_size=32,
                  num_steps_sampled_before_learning=64)
        .debugging(seed=0)
        .build()
    )
    try:
        host, port = ray_tpu.get(algo.runners[0].address.remote(),
                                 timeout=60)
        out = {}
        t = threading.Thread(
            target=_client_env_loop,
            args=(f"http://{host}:{port}", 30, out), daemon=True,
        )
        t.start()
        # train from external traffic: fragments block until the client
        # has produced them
        learned = {}
        saw_return = False
        buffer_peak = 0
        for _ in range(6):
            learned = algo.train()
            saw_return = saw_return or "episode_return_mean" in learned
            buffer_peak = max(buffer_peak, learned.get("buffer_size", 0))
        t.join(timeout=120)
        assert not t.is_alive(), "client loop wedged"
        assert out["returns"], "client never completed an episode"
        # the algorithm really consumed external transitions
        assert buffer_peak >= 64, learned
        assert "loss" in learned or "mean_td_error" in learned, learned
        # episode metrics flowed from client reports on SOME iteration
        # (the client may finish before the last train call)
        assert saw_return
    finally:
        algo.stop()


def test_policy_client_errors_are_http_errors(ray_start_regular):
    import urllib.error

    algo = (
        DQNConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=1, policy_server_port=0)
        .build()
    )
    try:
        host, port = ray_tpu.get(algo.runners[0].address.remote(),
                                 timeout=60)
        client = PolicyClient(f"http://{host}:{port}")
        with pytest.raises(urllib.error.HTTPError):
            client.get_action("no-such-episode", np.zeros(4))
    finally:
        algo.stop()
