"""Pipeline parallelism: ppermute pipeline vs sequential reference
(forward AND gradients), and the GPT-2 pipelined train step.
(SURVEY §2.9: PP is first-class for the TPU build; reference exercises it
only via external Alpa release tests.)"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ray_tpu import parallel
from ray_tpu.parallel.pipeline import (
    build_pipeline_fn,
    pipeline_apply,
    stack_stage_params,
)

S = 4  # stages


def _mesh():
    return parallel.create_mesh({"pipeline": S})


def _stage_params(key, d=16):
    ks = jax.random.split(key, S)
    per_stage = [
        {"w": jax.random.normal(k, (d, d)) / np.sqrt(d),
         "b": jax.random.normal(k, (d,)) * 0.1}
        for k in ks
    ]
    return stack_stage_params(per_stage), per_stage


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def test_pipeline_forward_matches_sequential():
    mesh = _mesh()
    stacked, per_stage = _stage_params(jax.random.PRNGKey(0))
    mb = jax.random.normal(jax.random.PRNGKey(1), (6, 8, 16))  # M=6

    fn = build_pipeline_fn(_stage_fn, mesh)
    got = fn(stacked, mb)

    want = mb
    for p in per_stage:
        want = _stage_fn(p, want)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match_sequential():
    """Reverse-mode through the ppermute schedule must equal sequential
    autodiff — stage grads route back through the reverse rotation."""
    mesh = _mesh()
    stacked, per_stage = _stage_params(jax.random.PRNGKey(2))
    mb = jax.random.normal(jax.random.PRNGKey(3), (4, 8, 16))
    w = jax.random.normal(jax.random.PRNGKey(4), mb.shape)

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    def pp_loss(stacked, mb):
        def local(stacked, mb):
            own = jax.tree.map(lambda p: p[0], stacked)
            return pipeline_apply(_stage_fn, own, mb, axis_name="pipeline")

        y = shard_map(
            local, mesh=mesh,
            in_specs=(PartitionSpec("pipeline"), PartitionSpec()),
            out_specs=PartitionSpec(),
        )(stacked, mb)
        return (y * w).sum()

    def seq_loss(stacked, mb):
        y = mb
        for s in range(S):
            y = _stage_fn(jax.tree.map(lambda p: p[s], stacked), y)
        return (y * w).sum()

    g_pp = jax.grad(pp_loss)(stacked, mb)
    g_seq = jax.grad(seq_loss)(stacked, mb)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_gpt2_pipeline_train_step():
    """PP loss at init matches the plain (non-parallel) model — same
    blocks, same init — and a few pipelined steps reduce it."""
    from ray_tpu.models import gpt2

    mesh = parallel.create_mesh({"data": 2, "pipeline": S})
    config = gpt2.GPT2Config.small_test(n_layer=4)  # 1 block per stage

    model, ref_params, _, _ = gpt2.make_train_state(config, jax.random.PRNGKey(0))
    pp_params, tx, opt_state = gpt2.make_pipeline_train_state(
        config, jax.random.PRNGKey(0), n_stages=S
    )
    pp_params, opt_state = gpt2.shard_pipeline_state(pp_params, opt_state, mesh)
    step = gpt2.build_train_step_pp(config, tx, mesh, n_microbatches=2,
                                    donate=False)

    batch = gpt2.synthetic_batch(jax.random.PRNGKey(1), 4, 32,
                                 config.vocab_size)
    ref_loss = float(gpt2.loss_fn(ref_params, model, batch))

    p, o = pp_params, opt_state
    losses = []
    for _ in range(4):
        p, o, loss = step(p, o, batch)
        losses.append(float(loss))
    assert abs(losses[0] - ref_loss) < 0.05, (losses[0], ref_loss)
    assert losses[-1] < losses[0], losses


def test_gpt2_pipeline_masked_batch():
    """The PP step accepts a padded batch with a mask and matches the
    plain model's masked loss (the batch spec is a pytree prefix)."""
    from ray_tpu.models import gpt2

    mesh = parallel.create_mesh({"data": 2, "pipeline": S})
    config = gpt2.GPT2Config.small_test(n_layer=4)

    model, ref_params, _, _ = gpt2.make_train_state(config, jax.random.PRNGKey(0))
    pp_params, tx, opt_state = gpt2.make_pipeline_train_state(
        config, jax.random.PRNGKey(0), n_stages=S
    )
    pp_params, opt_state = gpt2.shard_pipeline_state(pp_params, opt_state, mesh)
    step = gpt2.build_train_step_pp(config, tx, mesh, n_microbatches=2,
                                    donate=False)
    batch = gpt2.synthetic_batch(jax.random.PRNGKey(5), 4, 32,
                                 config.vocab_size)
    # mask counts DIFFER across data shards (rows 0-1 vs 2-3): the PP loss
    # must be the global token-weighted mean, not a mean of per-shard
    # masked means (which would up-weight the sparser shard)
    mask = np.ones((4, 32), np.float32)
    mask[:2, 8:] = 0.0   # shard 0: 8 valid tokens per row
    mask[2:, 24:] = 0.0  # shard 1: 24 valid tokens per row
    batch["mask"] = jnp.asarray(mask)
    ref_loss = float(gpt2.loss_fn(ref_params, model, batch))
    _, _, loss = step(pp_params, opt_state, batch)
    assert abs(float(loss) - ref_loss) < 0.05, (float(loss), ref_loss)
