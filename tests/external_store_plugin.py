"""Custom external-storage scheme for tests: a content-addressed blob
dir with a manifest — a DIFFERENT layout from plain spill files, so a
passing test proves the driver (not path compatibility) moved the bytes.
Registered by the raylet via RAY_TPU_EXTERNAL_STORAGE_SETUP_MODULE (the
plugin hook), standing in for an s3-style remote object store."""

import hashlib
import json
import os
from urllib.parse import urlparse

from ray_tpu._private.external_storage import (
    ExternalStorage,
    register_external_storage_scheme,
)


class MockS3Storage(ExternalStorage):
    def __init__(self, uri: str):
        parsed = urlparse(uri)
        self.root = parsed.path or parsed.netloc
        os.makedirs(os.path.join(self.root, "blobs"), exist_ok=True)
        self._manifest_path = os.path.join(self.root, "manifest.json")

    def _manifest(self):
        try:
            with open(self._manifest_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write_manifest(self, m):
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(m, f)
        os.replace(tmp, self._manifest_path)

    def spill(self, key, local_path):
        with open(local_path, "rb") as f:
            blob = f.read()
        digest = hashlib.sha256(blob).hexdigest()
        with open(os.path.join(self.root, "blobs", digest), "wb") as f:
            f.write(blob)
        m = self._manifest()
        m[key] = digest
        self._write_manifest(m)

    def restore(self, key, local_path):
        digest = self._manifest().get(key)
        if digest is None:
            return False
        tmp = local_path + ".restoring"
        with open(os.path.join(self.root, "blobs", digest), "rb") as fi, \
                open(tmp, "wb") as fo:
            fo.write(fi.read())
        os.replace(tmp, local_path)
        return True

    def delete(self, key):
        m = self._manifest()
        digest = m.pop(key, None)
        if digest is not None:
            self._write_manifest(m)
            if digest not in m.values():
                try:
                    os.unlink(os.path.join(self.root, "blobs", digest))
                except FileNotFoundError:
                    pass

    def exists(self, key):
        return key in self._manifest()


register_external_storage_scheme(
    "mocks3", lambda uri: MockS3Storage(uri)
)
