"""Collective backend beyond parity: chunked + quantized + straggler levers.

Covers the three config-flagged transport levers end to end:

- Chunked reduce-scatter+allgather correctness against numpy references
  (all ops, uneven shapes, int dtypes) on a threaded fake-KV world.
- The int8 quantization harness: error within the analytic per-block
  bound, bit-identical results on every rank, exact full-precision
  fallback for non-SUM/MEAN, and the wire-vs-logical byte accounting.
- Straggler scheduling units (fetch-order reordering off/on threshold,
  EWMA folding) and the flags-off pin: with all three levers disabled
  the store path is byte-identical to the monolithic exchange.
- PR 17 interplay: abort_group unwedges a mid-chunk wait with
  CollectiveWorldChangedError, epoch re-formation cannot join a dead
  generation's chunk sub-keys, and rank-0 seq GC covers chunk keys.
- Steptrace: a chunked op merges to ONE collective row per (group, seq)
  with chunk records riding alongside; e2e 2-worker JaxTrainer with
  overlap_grads=True shows collective spans interleaved with compute
  phase spans.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import steptrace
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.rpcio import EventLoopThread
from ray_tpu.util.collective import CollectiveWorldChangedError
from ray_tpu.util.collective import collective as colmod

pytestmark = pytest.mark.collective


# ---------------------------------------------------------------------------
# fake core worker: dict-backed KV behind the real async rendezvous path
# ---------------------------------------------------------------------------


class _FakeGcs:
    def __init__(self, kv, lock):
        self.kv, self.lock = kv, lock

    async def request(self, method, p):
        with self.lock:
            if method == "kv_put":
                self.kv[p["key"]] = p["value"]
                return {"added": True}
            if method == "kv_get":
                return self.kv.get(p["key"])
            if method == "kv_del":
                doomed = [k for k in self.kv if k.startswith(p["key"])]
                for k in doomed:
                    del self.kv[k]
                return {"deleted": len(doomed)}
            raise ValueError(method)


class _FakeCw:
    def __init__(self, kv, lock, io):
        self.gcs = _FakeGcs(kv, lock)
        self.io = io


@pytest.fixture
def fake_cw(monkeypatch):
    kv, lock = {}, threading.Lock()
    io = EventLoopThread(name="col-test-io")
    cw = _FakeCw(kv, lock, io)
    monkeypatch.setattr(colmod, "_cw", lambda: cw)
    old = (cfg.collective_chunk_bytes, cfg.collective_quant,
           cfg.collective_straggler_threshold)
    yield kv
    cfg.update({"collective_chunk_bytes": old[0],
                "collective_quant": old[1],
                "collective_straggler_threshold": old[2]})
    io.loop.call_soon_threadsafe(io.loop.stop)


def _run_world(world, arrays, op, quant="", chunk_bytes=1024, name="cb",
               seq=1, timeout=30.0):
    """Run one chunked allreduce across ``world`` threaded ranks; returns
    [(result, tel)] per rank."""
    cfg.update({"collective_chunk_bytes": chunk_bytes})
    results, errs = [None] * world, [None] * world

    def worker(r):
        g = colmod._Group(name, world, r, "store")
        try:
            tel = {"wire": 0, "logical": 0}
            out = colmod._chunked_allreduce(g, arrays[r], op, timeout, seq,
                                            tel, quant)
            results[r] = (out, tel)
        except BaseException as e:  # surfaced to the test thread
            errs[r] = e

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 30)
    for e in errs:
        if e is not None:
            raise e
    return results


_REF = {"sum": lambda s: np.sum(s, axis=0),
        "mean": lambda s: np.mean(s, axis=0),
        "product": lambda s: np.prod(s, axis=0),
        "min": lambda s: np.min(s, axis=0),
        "max": lambda s: np.max(s, axis=0)}


# ---------------------------------------------------------------------------
# chunked transport correctness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [2, 3])
@pytest.mark.parametrize("op", ["sum", "mean", "product", "min", "max"])
def test_chunked_matches_reference(fake_cw, world, op):
    rng = np.random.RandomState(hash((world, op)) % 2**31)
    arrays = [rng.randn(61, 7).astype(np.float32) for _ in range(world)]
    ref = _REF[op](np.stack(arrays))
    for r, (out, tel) in enumerate(
            _run_world(world, arrays, op, chunk_bytes=256,
                       name=f"ref-{world}-{op}")):
        assert out.shape == ref.shape
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        # full precision: every byte on the wire is a logical byte
        assert tel["wire"] == tel["logical"] > 0


@pytest.mark.parametrize("world,depth", [(4, 2), (6, 4)])
def test_deep_world_no_window_deadlock(fake_cw, world, depth):
    """Regression: with world - 1 > pipeline depth, a SHARED in-order
    fetch window fills up with reduced-chunk waits (which only complete
    after their owner finalizes) before all contribution fetches are
    submitted; no owner ever collects its W-1 contributions and every
    rank blocks until the rendezvous timeout. The per-kind windows must
    complete promptly at any world size, including depth 4 (the
    default) at world 6."""
    old_depth = cfg.collective_pipeline_depth
    cfg.update({"collective_pipeline_depth": depth})
    try:
        rng = np.random.RandomState(world * 31 + depth)
        arrays = [rng.randn(257).astype(np.float32) for _ in range(world)]
        t0 = time.monotonic()
        outs = _run_world(world, arrays, "sum", chunk_bytes=64,
                          name=f"deep-{world}-{depth}", timeout=20.0)
        assert time.monotonic() - t0 < 15.0, "chunk windows wedged"
        ref = np.sum(np.stack(arrays), axis=0)
        for out, _ in outs:
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    finally:
        cfg.update({"collective_pipeline_depth": old_depth})


def test_chunked_int_mean_promotes_like_numpy(fake_cw):
    arrays = [np.arange(10, dtype=np.int64),
              np.arange(10, dtype=np.int64) * 3]
    outs = _run_world(2, arrays, "mean", chunk_bytes=32, name="imean")
    np.testing.assert_allclose(outs[0][0], np.mean(np.stack(arrays), axis=0))


def test_chunk_layout_uniform_schedule():
    # shards cover [0, n) exactly once; every rank gets >=1 chunk even
    # when its shard is empty, so the rendezvous key schedule matches
    for n, world, ce in [(100, 4, 7), (3, 8, 2), (0, 2, 4), (64, 2, 0)]:
        plan = colmod._chunk_layout(n, world, ce)
        assert len(plan) == world
        spans = [s for pl in plan for s in pl]
        covered = sorted((a, b) for a, b in spans if a < b)
        pos = 0
        for a, b in covered:
            assert a == pos
            pos = b
        assert pos == n
        assert all(len(pl) >= 1 for pl in plan)
        if ce > 0:
            assert all(b - a <= ce for a, b in spans)


# ---------------------------------------------------------------------------
# int8 quantization harness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [2, 4])
@pytest.mark.parametrize("shape", [(1,), (33,), (257, 3)])
@pytest.mark.parametrize("op", ["sum", "mean"])
def test_quant_error_within_analytic_bound(fake_cw, world, shape, op):
    rng = np.random.RandomState(hash((world, shape, op)) % 2**31)
    arrays = [(rng.randn(*shape) * (r + 1)).astype(np.float32)
              for r in range(world)]
    outs = _run_world(world, arrays, op, quant="int8", chunk_bytes=512,
                      name=f"q-{world}-{len(shape)}-{shape[0]}-{op}")
    ref = _REF[op](np.stack(arrays))
    out0 = outs[0][0]
    # bit-identical on every rank: peers and owner decode the SAME
    # requantized wire form
    for out, _ in outs[1:]:
        assert np.array_equal(out0, out)
    # analytic per-block bound: each contribution rounds within scale/2,
    # plus one rounding of the reduced value (MEAN divides it all by W)
    err = np.abs(out0 - ref).max()
    scales = [np.abs(a).max() / 127.0 for a in arrays]
    red = np.sum(np.stack(arrays), axis=0)
    bound = 0.5 * sum(scales) + 0.5 * np.abs(red).max() / 127.0 + 1e-7
    if op == "mean":
        bound /= world
    assert err <= bound, (err, bound)


def test_quant_zero_block_exact(fake_cw):
    arrays = [np.zeros(100, np.float32), np.zeros(100, np.float32)]
    outs = _run_world(2, arrays, "sum", quant="int8", name="qzero")
    assert np.array_equal(outs[0][0], np.zeros(100, np.float32))


def test_quant_wire_bytes_shrink(fake_cw):
    # big enough that int8 payloads dominate headers: >=70% wire savings
    arrays = [np.random.RandomState(r).randn(65536).astype(np.float32)
              for r in range(2)]
    outs = _run_world(2, arrays, "sum", quant="int8", chunk_bytes=1 << 15,
                      name="qwire")
    for _, tel in outs:
        assert tel["wire"] <= 0.3 * tel["logical"], tel


def test_quant_encode_decode_roundtrip_properties():
    rng = np.random.RandomState(7)
    x = rng.randn(1000).astype(np.float32) * 42
    q, sc = colmod._quant_encode(x)
    assert q.dtype == np.int8 and q.min() >= -127 and q.max() <= 127
    deq = colmod._quant_decode(q, sc)
    assert np.abs(deq - x).max() <= sc / 2 + 1e-7
    # re-encoding an already-quantized grid is lossless
    q2, sc2 = colmod._quant_encode(deq)
    assert np.array_equal(colmod._quant_decode(q2, sc2), deq)


# ---------------------------------------------------------------------------
# straggler scheduling units
# ---------------------------------------------------------------------------


def test_fetch_order_fifo_until_threshold(fake_cw):
    g = colmod._Group("fo", 4, 0, "store")
    peers = [1, 2, 3]
    cfg.update({"collective_straggler_threshold": 0.01})
    assert colmod._fetch_order(g, peers) == ([1, 2, 3], [])  # no lag data
    g.peer_lag = {1: 0.002, 2: 0.009, 3: 0.0}
    assert colmod._fetch_order(g, peers) == ([1, 2, 3], [])  # under thr
    g.peer_lag = {1: 0.002, 2: 0.2, 3: 0.0}
    # the straggler's chunks are deferred globally, not just reordered
    assert colmod._fetch_order(g, peers) == ([1, 3], [2])
    g.peer_lag = {1: 0.3, 2: 0.2, 3: 0.0}
    # multiple stragglers defer least-laggy first
    assert colmod._fetch_order(g, peers) == ([3], [2, 1])
    cfg.update({"collective_straggler_threshold": 0.0})
    assert colmod._fetch_order(g, peers) == ([1, 2, 3], [])  # 0 = FIFO


def test_straggler_ewma_learns_from_local_wait_times(fake_cw):
    """Lag is learned from how long THIS rank sat blocked on a peer's
    contribution chunks (receiver clock only) — a peer entering the op
    late shows up as a long max cc wait, with no cross-host timestamp
    comparison."""
    arrays = [np.random.RandomState(r).randn(4096).astype(np.float32)
              for r in range(2)]
    cfg.update({"collective_straggler_threshold": 0.005})
    results, errs = [None] * 2, [None] * 2
    groups = [colmod._Group("ewma", 2, r, "store") for r in range(2)]

    def worker(r):
        if r == 1:
            time.sleep(0.25)  # rank 1 arrives late: a straggler
        try:
            tel = {"wire": 0, "logical": 0}
            results[r] = colmod._chunked_allreduce(
                groups[r], arrays[r], "sum", 30.0, 1, tel)
        except BaseException as e:
            errs[r] = e

    threads = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
    # shallow window + many chunks: once the late rank publishes its
    # burst, every chunk AFTER the window refills completes instantly —
    # only the max cc wait still carries the arrival-lateness signal
    old_depth = cfg.collective_pipeline_depth
    cfg.update({"collective_chunk_bytes": 1024,
                "collective_pipeline_depth": 2})
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
    finally:
        cfg.update({"collective_pipeline_depth": old_depth})
    assert not any(errs), errs
    np.testing.assert_allclose(results[0], np.sum(np.stack(arrays), axis=0),
                               rtol=1e-5)
    # rank 0 sat blocked on rank 1's chunks for ~the sleep -> learned lag
    assert groups[0].peer_lag.get(1, 0.0) > 0.05
    # ...which flips its next fetch order to straggler-last (trivially
    # [1] at world 2, but the EWMA is now over threshold)
    assert max(groups[0].peer_lag.values()) > \
        cfg.collective_straggler_threshold


# ---------------------------------------------------------------------------
# PR 17 interplay: abort, epoch isolation, seq GC
# ---------------------------------------------------------------------------


def test_abort_unwedges_mid_chunk_wait(fake_cw):
    """A rank blocked mid-chunk (peer never publishes) fails over with
    the typed world-changed error as soon as the abort marker lands —
    not after the full rendezvous timeout."""
    g = colmod._Group("ab", 2, 0, "store")
    err = [None]

    def lone_rank():
        try:
            colmod._chunked_allreduce(
                g, np.ones(4096, np.float32), "sum", 60.0, 1,
                {"wire": 0, "logical": 0})
        except BaseException as e:
            err[0] = e

    cfg.update({"collective_chunk_bytes": 1024})
    t = threading.Thread(target=lone_rank)
    t.start()
    time.sleep(0.3)  # let it wedge on rank 1's first contribution chunk
    abort_key = g.keybase.encode() + colmod._ABORT_SUFFIX
    fake_cw[abort_key] = b"1"
    t.join(10)
    assert not t.is_alive(), "abort marker did not unwedge the chunk wait"
    assert isinstance(err[0], CollectiveWorldChangedError), err[0]


def test_epoch_isolates_chunk_subkeys(fake_cw):
    """A re-formed generation's chunk rendezvous cannot join the dead
    generation's chunk sub-seq keys: the whole chunk keyspace hangs off
    the epoch-qualified keybase."""
    stale = f"{colmod._keybase('eg', 0)}:1:cc:0:0:1".encode()
    fake_cw[stale] = b"dead-generation-chunk"
    g1 = colmod._Group("eg", 2, 0, "store", epoch=1)
    fresh = f"{g1.keybase}:1:cc:0:0:1".encode()
    assert fresh != stale
    with pytest.raises(TimeoutError):
        colmod._cw().io.run(
            colmod._akv_wait(colmod._cw(), fresh, timeout=0.2))


def test_rank0_seq_gc_covers_chunk_keys(fake_cw):
    """Chunk sub-keys live under the op's seq prefix, so the existing
    rank-0 GC of seq-1 reclaims them with no extra bookkeeping."""
    arrays = [np.random.RandomState(r).randn(512).astype(np.float32)
              for r in range(2)]
    _run_world(2, arrays, "sum", chunk_bytes=256, name="gc", seq=1)
    assert any(b":1:" in k for k in fake_cw), "seq-1 chunk keys missing"
    _run_world(2, arrays, "sum", chunk_bytes=256, name="gc", seq=2)
    leaked = [k for k in fake_cw if k.startswith(b"gc@0:1:")]
    assert not leaked, leaked


# ---------------------------------------------------------------------------
# live cluster: routing, flags-off pin, steptrace join
# ---------------------------------------------------------------------------


@ray_tpu.remote
class ChunkWorker:
    def _rt_init_collective(self, world_size, rank, backend, group_name,
                            epoch=0, quant=""):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend, group_name,
                                  epoch=epoch, quant=quant)
        return rank

    def set_cfg(self, updates):
        from ray_tpu._private.config import GLOBAL_CONFIG

        GLOBAL_CONFIG.update(updates)
        return True

    def do_allreduce(self, arr, group_name, op="sum"):
        from ray_tpu.util import collective as col

        return col.allreduce(np.array(arr), group_name, op=op)

    def trace_records(self, group_name):
        from ray_tpu._private import steptrace as st

        return [r for r in st.snapshot() if r.get("group") == group_name]


def _pair(workers, arrays, group, op="sum"):
    return ray_tpu.get(
        [w.do_allreduce.remote(a, group, op)
         for w, a in zip(workers, arrays)], timeout=60)


def test_flags_off_pin_byte_identical(ray_start_regular):
    """chunk=0, quant off, threshold=0 must reproduce the monolithic
    exchange bit for bit: same accumulation order, no chunk records,
    wire == logical."""
    from ray_tpu.util import collective as col

    workers = [ChunkWorker.remote() for _ in range(2)]
    ray_tpu.get([w.set_cfg.remote({"collective_chunk_bytes": 0,
                                   "collective_quant": "",
                                   "collective_straggler_threshold": 0.0})
                 for w in workers], timeout=30)
    col.create_collective_group(workers, 2, [0, 1], backend="store",
                                group_name="pin")
    rng = np.random.RandomState(3)
    arrays = [rng.randn(4096).astype(np.float32) for _ in range(2)]
    outs = _pair(workers, arrays, "pin")
    # the monolithic path stacks rank-ordered contributions and reduces
    # with the numpy ufunc — byte-identical, not merely allclose
    expected = np.sum(np.stack(arrays), axis=0)
    for out in outs:
        assert np.array_equal(out, expected)
    recs = ray_tpu.get(workers[0].trace_records.remote("pin"), timeout=30)
    assert [r for r in recs if r["kind"] == "coll"]
    assert not [r for r in recs if r["kind"] == "chunk"]
    for r in recs:
        if r["kind"] == "coll":
            assert r["wire"] == r["logical"]


def test_chunked_merges_to_one_coll_row(ray_start_regular):
    """A chunked op is still ONE collective on the observability plane:
    per-rank records join by (group, seq) into a single row, with the
    chunk records riding alongside on their own kind."""
    from ray_tpu.util import collective as col

    workers = [ChunkWorker.remote() for _ in range(2)]
    ray_tpu.get([w.set_cfg.remote({"collective_chunk_bytes": 512})
                 for w in workers], timeout=30)
    col.create_collective_group(workers, 2, [0, 1], backend="store",
                                group_name="onerow")
    rng = np.random.RandomState(5)
    arrays = [rng.randn(2048).astype(np.float32) for _ in range(2)]
    outs = _pair(workers, arrays, "onerow")
    np.testing.assert_allclose(outs[0], np.sum(np.stack(arrays), axis=0),
                               rtol=1e-5)
    recs = []
    for w in workers:
        recs.extend(ray_tpu.get(w.trace_records.remote("onerow"),
                                timeout=30))
    rows = steptrace.merge_collectives(recs)
    assert len(rows) == 1, rows
    row = rows[0]
    assert set(row["ranks"]) == {0, 1} and row["missing"] == []
    assert row["skew"] >= 0.0
    # both ranks moved real bytes, and the transport measured them
    for r in row["ranks"].values():
        assert r["wire"] > 0 and r["logical"] >= r["wire"]
    chunk_recs = [r for r in recs if r["kind"] == "chunk"]
    assert len(chunk_recs) >= 2 * 4  # >=4 chunks per rank at 512B/2048el
    assert {r["seq"] for r in chunk_recs} == {row["seq"]}


def test_quant_group_non_sum_mean_stays_exact(ray_start_regular):
    """quant="int8" groups only quantize SUM/MEAN floats; MAX (and int
    dtypes) must come back in exact full precision."""
    from ray_tpu.util import collective as col

    workers = [ChunkWorker.remote() for _ in range(2)]
    col.create_collective_group(workers, 2, [0, 1], backend="store",
                                group_name="qg", quant="int8")
    rng = np.random.RandomState(11)
    arrays = [rng.randn(512).astype(np.float32) for _ in range(2)]
    outs = _pair(workers, arrays, "qg", op="max")
    expected = np.max(np.stack(arrays), axis=0)
    for out in outs:
        assert np.array_equal(out, expected)
    # while SUM on the same group IS quantized: tiny but nonzero error
    souts = _pair(workers, arrays, "qg", op="sum")
    sref = np.sum(np.stack(arrays), axis=0)
    np.testing.assert_allclose(souts[0], sref, atol=0.1)
    assert not np.array_equal(souts[0], sref)


def test_create_group_rejects_unknown_quant(ray_start_regular):
    from ray_tpu.util import collective as col

    with pytest.raises(ValueError):
        col.create_collective_group([], 0, [], backend="store",
                                    group_name="bad", quant="fp4")


# ---------------------------------------------------------------------------
# e2e: JaxTrainer(overlap_grads=True) interleaves collectives w/ compute
# ---------------------------------------------------------------------------


def test_jax_trainer_overlap_grads_e2e(ray_start_regular):
    from ray_tpu import train
    from ray_tpu.util import state

    def loop(config):
        import time as _time

        import numpy as np

        from ray_tpu import train as train_mod
        from ray_tpu._private.config import GLOBAL_CONFIG

        GLOBAL_CONFIG.update({"collective_chunk_bytes": 4096})
        ctx = train_mod.get_context()
        rank = ctx.get_world_rank()
        for step in range(3):
            grad = np.full((8192,), float(rank + step), np.float32)
            with train_mod.GradSync() as gs:
                with train_mod.step_phase("compute"):
                    gs.submit("g", grad)
                    # the rest of the "backward": overlap happens here
                    _time.sleep(0.3)
                reduced = gs.results()["g"]
            train_mod.report({"step": step, "g0": float(reduced[0])})

    trainer = train.JaxTrainer(
        loop,
        jax_config=train.JaxConfig(env_vars={"JAX_PLATFORMS": "cpu"}),
        scaling_config=train.ScalingConfig(num_workers=2),
        overlap_grads=True,
        run_config=train.RunConfig(name="t_overlap",
                                   storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.error is None, result.error

    merged = state.steptrace_summary()
    rows = [c for c in merged["collectives"] if c["group"] == "train_dp"]
    assert rows, merged["collectives"]
    chunk_recs = [c for c in merged.get("chunks", ())
                  if c["group"] == "train_dp"]
    assert chunk_recs, "chunked gradient allreduce left no chunk records"
    compute = [p for p in merged["phases"] if p["phase"] == "compute"]
    assert compute
    # the overlap claim itself: some rank's gradient collective interval
    # overlaps one of ITS OWN compute phase intervals
    overlapped = False
    for row in rows:
        for rank, iv in row["ranks"].items():
            for ph in compute:
                if int(ph["rank"]) != int(rank):
                    continue
                if iv["start"] < ph["end"] and iv["end"] > ph["start"]:
                    overlapped = True
    assert overlapped, (rows, compute)
