"""Object push / broadcast plane tests.

Analog of ray: push_manager tests (src/ray/object_manager/test/) and the
release broadcast benchmark shape — explicit pushes land copies on chosen
nodes, broadcast covers the cluster via tree fan-out, and duplicate
pushes dedup instead of re-sending.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.transfer import broadcast_object, push_object


def _locations(ref):
    from ray_tpu._private.worker import global_worker

    cw = global_worker.core_worker
    return set(cw.io.run(cw.gcs.request(
        "get_object_locations",
        {"object_id": ref.binary(), "wait": False},
    )) or [])


@pytest.fixture
def three_node_cluster(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    yield cluster


def test_push_lands_copy(three_node_cluster):
    nodes = [n["node_id"] for n in ray_tpu.nodes() if n["alive"]]
    assert len(nodes) == 3
    arr = np.random.default_rng(0).bytes(2 * 1024 * 1024)  # multi-chunk
    ref = ray_tpu.put(arr)
    me = ray_tpu.get_runtime_context().get_node_id()
    target = next(n for n in nodes if n != me)
    assert push_object(ref, [target]) == 1
    locs = _locations(ref)
    assert target in locs and me in locs


def test_push_dedup_and_repeat(three_node_cluster):
    nodes = [n["node_id"] for n in ray_tpu.nodes() if n["alive"]]
    me = ray_tpu.get_runtime_context().get_node_id()
    target = next(n for n in nodes if n != me)
    ref = ray_tpu.put(b"y" * 300_000)
    # two pushes of the same object to the same peer: second is a no-op
    # ("have") — both succeed
    assert push_object(ref, [target]) == 1
    assert push_object(ref, [target]) == 1
    assert target in _locations(ref)


def test_broadcast_covers_cluster(three_node_cluster):
    nodes = {n["node_id"] for n in ray_tpu.nodes() if n["alive"]}
    arr = np.arange(500_000, dtype=np.uint8)
    ref = ray_tpu.put(arr.tobytes())
    n = broadcast_object(ref)
    assert n == 2  # two targets beyond the holder
    assert _locations(ref) == nodes
    # consumers on every node read the local copy (no pull needed);
    # the arg ref materializes from each node's own store
    @ray_tpu.remote
    def consume(r):
        return len(r)

    sizes = ray_tpu.get(
        [consume.options(resources={}).remote(ref) for _ in range(3)],
        timeout=60,
    )
    assert sizes == [500_000] * 3
