"""MoE / expert parallelism (SURVEY §2.9 EP).

Checks routing invariants, dense-vs-EP equivalence on the virtual
8-device mesh, and gradient flow through the EP all_to_all path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops import moe

from ray_tpu.parallel.collectives import shard_map_norep


def test_switch_gating_invariants():
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (32, 4))
    dispatch, combine, aux = moe.switch_gating(logits, capacity=8)
    # each token goes to at most one (expert, slot)
    assert float(dispatch.sum(axis=(1, 2)).max()) <= 1.0
    # no expert holds more than capacity tokens
    assert float(dispatch.sum(axis=(0, 2)).max()) <= 8.0
    # each (expert, slot) pair is used at most once
    assert float(dispatch.sum(axis=0).max()) <= 1.0
    assert np.isfinite(float(aux))
    # balanced capacity: with C=T no token drops
    dispatch_full, _, _ = moe.switch_gating(logits, capacity=32)
    assert float(dispatch_full.sum()) == 32.0


def test_moe_dense_forward_and_dropping():
    key = jax.random.PRNGKey(1)
    params = moe.init_moe_params(key, d_model=16, d_hidden=32, num_experts=4)
    x = jax.random.normal(jax.random.PRNGKey(2), (64, 16))
    out, aux = moe.moe_ffn(params, x, capacity_factor=2.0)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all() and np.isfinite(float(aux))


def test_moe_ep_matches_dense():
    """Expert-parallel execution over the 8-device mesh computes the same
    function as the all-local dense path."""
    devices = jax.devices()
    assert len(devices) == 8
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", "ep"))
    E, d, h = 8, 16, 32
    params = moe.init_moe_params(jax.random.PRNGKey(3), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(4), (128, d))

    dense_out, dense_aux = moe.moe_ffn(params, x, capacity_factor=8.0)

    ep_specs = {"router": P(), "wi": P("ep"), "wo": P("ep")}

    def body(params, x):
        out, aux = moe.moe_ffn_ep(params, x, axis="ep", capacity_factor=8.0)
        return out, jax.lax.pmean(jax.lax.pmean(aux, "data"), "ep")

    fn = jax.jit(shard_map_norep(
        body, mesh=mesh,
        in_specs=({k: ep_specs[k] for k in params}, P("data")),
        out_specs=(P("data"), P()),
    ))
    params_sharded = {
        k: jax.device_put(v, NamedSharding(mesh, ep_specs[k]))
        for k, v in params.items()
    }
    x_sharded = jax.device_put(x, NamedSharding(mesh, P("data")))
    ep_out, ep_aux = fn(params_sharded, x_sharded)

    # Gating runs per data shard (capacity per shard), so with a capacity
    # factor large enough that nothing drops, outputs match exactly.
    np.testing.assert_allclose(
        np.asarray(ep_out), np.asarray(dense_out), rtol=2e-4, atol=2e-5
    )
    assert np.isfinite(float(ep_aux))


def test_moe_ep_gradients_flow():
    devices = jax.devices()
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", "ep"))
    E, d, h = 8, 8, 16
    params = moe.init_moe_params(jax.random.PRNGKey(5), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(6), (64, d))
    ep_specs = {"router": P(), "wi": P("ep"), "wo": P("ep")}

    def loss_body(params, x):
        def loss_fn(p):
            out, aux = moe.moe_ffn_ep(p, x, axis="ep", capacity_factor=4.0)
            return (out ** 2).mean() + 0.01 * aux  # aux exercises router grad

        return moe.ep_loss_and_grads(loss_fn, params, "data", "ep")

    fn = jax.jit(shard_map_norep(
        loss_body, mesh=mesh,
        in_specs=({k: ep_specs[k] for k in params}, P(("data", "ep"))),
        out_specs=(P(), {k: ep_specs[k] for k in params}),
    ))
    params_sharded = {
        k: jax.device_put(v, NamedSharding(mesh, ep_specs[k]))
        for k, v in params.items()
    }
    x_sharded = jax.device_put(x, NamedSharding(mesh, P(("data", "ep"))))
    loss, grads = fn(params_sharded, x_sharded)
    assert np.isfinite(float(loss))
    for k, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), k
    assert float(jnp.abs(grads["wi"]).sum()) > 0.0
    assert float(jnp.abs(grads["router"]).sum()) > 0.0


def test_moe_ep_gradients_match_dense():
    """The EP step's reduced gradients equal the dense single-device
    gradients of the same global-mean objective."""
    devices = jax.devices()
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", "ep"))
    E, d, h = 8, 8, 16
    params = moe.init_moe_params(jax.random.PRNGKey(7), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(8), (64, d))
    ep_specs = {"router": P(), "wi": P("ep"), "wo": P("ep")}

    # aux is intentionally shard-local (per-shard load stats), so exact
    # parity holds for the data term; aux grad flow is covered above.
    def dense_loss(p):
        out, _ = moe.moe_ffn(p, x, capacity_factor=8.0)
        return (out ** 2).mean()

    dense_grads = jax.grad(dense_loss)(params)

    def loss_body(p, xs):
        def local_loss(pp):
            out, _ = moe.moe_ffn_ep(pp, xs, axis="ep", capacity_factor=8.0)
            return (out ** 2).mean()

        _, grads = moe.ep_loss_and_grads(local_loss, p, "data", "ep")
        return grads

    fn = jax.jit(shard_map_norep(
        loss_body, mesh=mesh,
        in_specs=({k: ep_specs[k] for k in params}, P(("data", "ep"))),
        out_specs={k: ep_specs[k] for k in params},
    ))
    params_sharded = {
        k: jax.device_put(v, NamedSharding(mesh, ep_specs[k]))
        for k, v in params.items()
    }
    x_sharded = jax.device_put(x, NamedSharding(mesh, P(("data", "ep"))))
    ep_grads = fn(params_sharded, x_sharded)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(ep_grads[k]), np.asarray(dense_grads[k]),
            rtol=5e-4, atol=1e-6,
        )
