"""Node-death chaos: kill a raylet mid-workload, the job survives.

Reference parity: ray python/ray/tests/test_chaos.py + NodeKillerActor
(_private/test_utils.py:1400 kills raylets, graceful or not) — here the
Cluster fixture's remove_node(graceful=False) is the killer.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private.rpcio import RpcError
from ray_tpu._private.worker import (ActorDiedError, GetTimeoutError,
                                     WorkerDiedError)

# Errors a caller may legitimately see while the cluster heals: transport
# loss/deadline (RpcError covers ConnectionLost + RpcTimeoutError), the
# actor's death/restart window, get() deadlines, and the worker's generic
# task-failure surface (RuntimeError — "task submission failed: ...",
# which ActorDiedError/WorkerDiedError also subclass). Anything else — an
# AssertionError, a TypeError in the test body — must propagate instead of
# being swallowed by the retry loop.
TRANSIENT_CHAOS_ERRORS = (RpcError, GetTimeoutError, ActorDiedError,
                          WorkerDiedError, TimeoutError, RuntimeError)


@ray_tpu.remote(max_retries=4)
def slow_echo(x, delay=0.2):
    time.sleep(delay)
    return x


@pytest.mark.chaos
def test_node_death_tasks_retry_elsewhere(ray_start_cluster):
    """Tasks in flight on a killed node are retried on survivors."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)  # head
    node_b = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    refs = [slow_echo.options(scheduling_strategy="SPREAD").remote(i)
            for i in range(16)]
    time.sleep(0.4)  # let some tasks land on node B
    cluster.remove_node(node_b, graceful=False)
    got = ray_tpu.get(refs, timeout=120)
    assert got == list(range(16))


@pytest.mark.chaos
def test_node_death_actor_restarts_elsewhere(ray_start_cluster):
    """A restartable actor on a killed node comes back on another node and
    serves calls again (max_restarts + max_task_retries)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)  # head: no "spot" resource
    node_b = cluster.add_node(num_cpus=2, resources={"spot": 1.0})
    node_c = cluster.add_node(num_cpus=2, resources={"spot": 1.0})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote(max_restarts=2, max_task_retries=4, num_cpus=1,
                    resources={"spot": 1})
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def where(self):
            return ray_tpu.get_runtime_context().get_node_id()

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1
    home = ray_tpu.get(c.where.remote(), timeout=60)
    victim = node_b if home == node_b.node_id else node_c
    assert home == victim.node_id
    cluster.remove_node(victim, graceful=False)

    # calls retry while the GCS restarts the actor on the surviving
    # spot-capable node; state is fresh (restart, not migration)
    deadline = time.monotonic() + 90
    value = None
    while time.monotonic() < deadline:
        try:
            value = ray_tpu.get(c.bump.remote(), timeout=30)
            break
        except TRANSIENT_CHAOS_ERRORS:
            time.sleep(1.0)
    assert value is not None and value >= 1, value
    new_home = ray_tpu.get(c.where.remote(), timeout=30)
    assert new_home != victim.node_id
