"""Actor fault-tolerance tests (restart, kill) — fresh cluster per test."""

import os

import pytest

import ray_tpu
from tests.conftest import wait_for_condition

# cluster-state-mutating module: always gets (and leaves behind) a
# fresh cluster instead of joining the shared fast-lane one
RAY_REUSE_CLUSTER = False


def test_actor_restart(ray_start_regular_fn):
    @ray_tpu.remote(max_restarts=1)
    class Dying:
        def __init__(self):
            self.pid = os.getpid()

        def get_pid(self):
            return os.getpid()

        def die(self):
            os._exit(1)

    d = Dying.remote()
    pid1 = ray_tpu.get(d.get_pid.remote(), timeout=30)
    d.die.remote()

    def restarted_in_new_process():
        # calls during the restart window raise; keep probing until the
        # replacement process answers (awaited, not guessed via sleep)
        return ray_tpu.get(d.get_pid.remote(), timeout=15) != pid1

    wait_for_condition(restarted_in_new_process, timeout=60)


def test_kill_actor(ray_start_regular_fn):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=30) == "pong"
    ray_tpu.kill(v)

    def ping_fails():
        try:
            ray_tpu.get(v.ping.remote(), timeout=10)
            return False
        except Exception:
            return True

    wait_for_condition(ping_fails, timeout=30)
    with pytest.raises(Exception):
        ray_tpu.get(v.ping.remote(), timeout=10)
