"""Actor fault-tolerance tests (restart, kill) — fresh cluster per test."""

import os
import time

import pytest

import ray_tpu

# cluster-state-mutating module: always gets (and leaves behind) a
# fresh cluster instead of joining the shared fast-lane one
RAY_REUSE_CLUSTER = False


def test_actor_restart(ray_start_regular_fn):
    @ray_tpu.remote(max_restarts=1)
    class Dying:
        def __init__(self):
            self.pid = os.getpid()

        def get_pid(self):
            return os.getpid()

        def die(self):
            os._exit(1)

    d = Dying.remote()
    pid1 = ray_tpu.get(d.get_pid.remote(), timeout=30)
    d.die.remote()
    time.sleep(2)
    pid2 = ray_tpu.get(d.get_pid.remote(), timeout=60)
    assert pid2 != pid1  # restarted in a fresh process


def test_kill_actor(ray_start_regular_fn):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=30) == "pong"
    ray_tpu.kill(v)
    time.sleep(1)
    with pytest.raises(Exception):
        ray_tpu.get(v.ping.remote(), timeout=15)
