"""Train tests (analog of ray: python/ray/train/tests/test_data_parallel_trainer.py)."""

import os

import numpy as np
import pytest

import ray_tpu


# Workers must run JAX on CPU (tests never grab the TPU chip).
_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
}


def test_worker_group_basic(ray_start_regular):
    from ray_tpu.train import WorkerGroup

    wg = WorkerGroup(2, {"CPU": 1})
    outs = wg.execute(lambda: os.getpid())
    assert len(outs) == 2 and outs[0] != outs[1]
    wg.shutdown()


def test_data_parallel_trainer_reports(ray_start_regular):
    from ray_tpu import train

    def loop(config):
        ctx = train.get_context()
        for step in range(3):
            train.report({
                "step": step,
                "rank": ctx.get_world_rank(),
                "world_size": ctx.get_world_size(),
                "loss": 1.0 / (step + 1),
            })

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="t_basic", storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["world_size"] == 2
    assert result.metrics["rank"] == 0


def test_trainer_checkpointing(ray_start_regular):
    from ray_tpu import train
    from ray_tpu.air import Checkpoint

    def loop(config):
        start = 0
        ck = train.get_checkpoint()
        if ck is not None:
            start = ck.to_dict()["step"] + 1
        for step in range(start, start + 2):
            train.report(
                {"step": step},
                checkpoint=Checkpoint.from_dict({"step": step}),
            )

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="t_ckpt", storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 1
    assert result.checkpoint is not None
    # resume
    trainer2 = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="t_ckpt2", storage_path="/tmp/rt_test_results"),
        resume_from_checkpoint=result.checkpoint,
    )
    result2 = trainer2.fit()
    assert result2.metrics["step"] == 3


def test_trainer_failure(ray_start_regular):
    from ray_tpu import train

    def loop(config):
        raise ValueError("train loop exploded")

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="t_fail", storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "exploded" in str(result.error)


def test_jax_trainer_dp_sync(ray_start_regular):
    """Two JAX CPU workers train a tiny model data-parallel; gradients sync
    via the host collective; losses match across workers each step."""
    from ray_tpu import train

    def loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.util import collective as col

        ctx = train.get_context()
        rank = ctx.get_world_rank()
        world = ctx.get_world_size()

        # deterministic per-rank data shard
        rng = np.random.default_rng(42 + rank)
        X = rng.normal(size=(32, 4)).astype(np.float32)
        w_true = np.array([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
        y = X @ w_true

        w = jnp.zeros((4,))

        @jax.jit
        def grad_fn(w, X, y):
            def loss(w):
                return jnp.mean((X @ w - y) ** 2)

            return jax.value_and_grad(loss)(w)

        if world > 1:
            col.init_collective_group(world, rank, backend="store",
                                      group_name="dp_test")
        for step in range(5):
            loss, g = grad_fn(w, X, y)
            g = np.asarray(g)
            if world > 1:
                g = col.allreduce(g, "dp_test", op=col.ReduceOp.MEAN)
            w = w - 0.1 * jnp.asarray(g)
            train.report({"step": step, "loss": float(loss), "rank": rank})

    trainer = train.JaxTrainer(
        loop,
        jax_config=train.JaxConfig(env_vars=_CPU_ENV),
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="t_jaxdp", storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["step"] == 4
    assert result.metrics["loss"] < 15.0


def test_jax_trainer_mesh_in_worker(ray_start_regular):
    """A worker builds a 4-device virtual mesh and runs a sharded train step
    (validates the in-graph psum path without TPU hardware)."""
    from ray_tpu import train

    def loop(config):
        import jax
        import jax.numpy as jnp

        from ray_tpu import parallel
        from ray_tpu.models import gpt2

        assert len(jax.devices()) == 4
        mesh = parallel.create_mesh({"data": 4})
        cfg = gpt2.GPT2Config.small_test()
        model, params, tx, opt_state = gpt2.make_train_state(
            cfg, jax.random.PRNGKey(0)
        )
        params, opt_state = gpt2.shard_train_state(params, opt_state, mesh)
        step_fn = gpt2.build_train_step(model, tx, donate=False)
        batch = gpt2.synthetic_batch(jax.random.PRNGKey(1), 8, 32, cfg.vocab_size)
        batch = gpt2.shard_batch(batch, mesh)
        losses = []
        for i in range(3):
            params, opt_state, loss = step_fn(params, opt_state, batch)
            losses.append(float(loss))
        train.report({"losses": losses})

    trainer = train.JaxTrainer(
        loop,
        jax_config=train.JaxConfig(env_vars=_CPU_ENV),
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="t_mesh", storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    losses = result.metrics["losses"]
    assert losses[2] < losses[0]  # it learns


def test_torch_trainer_gloo(ray_start_regular):
    """ray parity: TorchTrainer with a real torch.distributed gloo group."""
    from ray_tpu import train

    def loop(config):
        import torch
        import torch.distributed as dist

        rank = dist.get_rank()
        world = dist.get_world_size()
        t = torch.ones(4) * (rank + 1)
        dist.all_reduce(t, op=dist.ReduceOp.SUM)
        train.report({"sum": t.tolist(), "world": world})

    trainer = train.TorchTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="t_torch", storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["sum"] == [3.0, 3.0, 3.0, 3.0]
    assert result.metrics["world"] == 2


def test_tensorflow_trainer_tf_config_and_fit(ray_start_regular):
    """TF_CONFIG is wired per worker (cluster spec + task index); a
    single-worker keras fit runs under MultiWorkerMirroredStrategy
    (ray parity: tensorflow_trainer.py)."""
    import json as _json

    from ray_tpu import train
    from ray_tpu.train import TensorflowTrainer

    def probe_loop():
        import os

        from ray_tpu import train as train_mod

        cfg = _json.loads(os.environ["TF_CONFIG"])
        ctx = train_mod.get_context()
        train_mod.report({
            "task_index": cfg["task"]["index"],
            "world": len(cfg["cluster"]["worker"]),
            "rank": ctx.get_world_rank(),
        })

    trainer = TensorflowTrainer(
        probe_loop, scaling_config=train.ScalingConfig(num_workers=2)
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 2
    assert result.metrics["task_index"] == result.metrics["rank"]

    def keras_loop():
        import numpy as np
        import tensorflow as tf

        from ray_tpu import train as train_mod

        strategy = tf.distribute.MultiWorkerMirroredStrategy()
        with strategy.scope():
            model = tf.keras.Sequential([
                tf.keras.layers.Dense(4, activation="relu",
                                      input_shape=(2,)),
                tf.keras.layers.Dense(1),
            ])
            model.compile(optimizer="sgd", loss="mse")
        X = np.random.rand(64, 2).astype("float32")
        y = (X.sum(axis=1, keepdims=True)).astype("float32")
        hist = model.fit(X, y, epochs=2, verbose=0)
        train_mod.report({"loss": float(hist.history["loss"][-1])})

    trainer = TensorflowTrainer(
        keras_loop, scaling_config=train.ScalingConfig(num_workers=1)
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] >= 0.0


def test_sklearn_trainer(ray_start_regular):
    import numpy as np
    import pandas as pd

    from ray_tpu import data as rdata
    from ray_tpu.train import SklearnTrainer
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    df = pd.DataFrame({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2], "label": y})
    trainer = SklearnTrainer(
        estimator=LogisticRegression(max_iter=200),
        datasets={"train": rdata.from_pandas(df)},
        label_column="label",
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["train_score"] > 0.9
    import cloudpickle

    model = cloudpickle.loads(result.checkpoint.to_dict()["model"])
    assert model.predict(np.array([[2.0, 2.0, 0.0]]))[0] == 1


@pytest.mark.slow
def test_torch_trainer_ddp_convergence(ray_start_regular):
    """Convergence (not just collectives): a 2-worker DDP regression run
    must actually minimize the loss, with gradient averaging across the
    gloo group keeping replicas identical (ray parity: the torch
    benchmark workloads assert learning, release/air_tests)."""
    from ray_tpu import train

    def loop(config):
        import torch
        import torch.distributed as dist
        from torch.nn.parallel import DistributedDataParallel as DDP

        torch.manual_seed(0)
        rank = dist.get_rank()
        # y = 3x - 1 with per-worker data shards
        g = torch.Generator().manual_seed(100 + rank)
        x = torch.rand(256, 1, generator=g) * 4 - 2
        y = 3.0 * x - 1.0

        model = DDP(torch.nn.Linear(1, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        first = last = None
        for _ in range(60):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()  # DDP averages grads across the group
            opt.step()
            first = first if first is not None else loss.item()
            last = loss.item()
        w = model.module.weight.item()
        b = model.module.bias.item()
        train.report({"first": first, "last": last, "w": w, "b": b})

    trainer = train.TorchTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="t_torch_conv",
                                   storage_path="/tmp/rt_test_results"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    m = result.metrics
    assert m["last"] < m["first"] * 0.05, m  # loss actually minimized
    assert abs(m["w"] - 3.0) < 0.2 and abs(m["b"] + 1.0) < 0.2, m
