"""CLI + job submission end-to-end (no pytest cluster fixtures: the CLI
starts its own cluster from the shell, the way a user would).

ray parity: `ray start --head` / `ray status` / `ray job submit`
(python/ray/scripts/scripts.py, dashboard/modules/job/job_manager.py:516).
"""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.job_submission import JobSubmissionClient


def _cli(args, env, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_cli_start_status_submit_stop(tmp_path):
    env = dict(os.environ)
    env["HOME"] = str(tmp_path)  # isolate ~/.ray_tpu state
    from ray_tpu._private.node import package_env

    env = package_env(env)

    out = _cli(["start", "--head", "--num-cpus", "2"], env)
    assert out.returncode == 0, out.stderr
    assert "started head node" in out.stdout
    address = out.stdout.split("address=")[1].splitlines()[0].strip()

    try:
        out = _cli(["status"], env)
        assert out.returncode == 0, out.stderr
        assert "1/1 nodes alive" in out.stdout

        out = _cli(
            ["submit", "--timeout", "120", "--",
             "python", "-c", "print('job says hello')"],
            env,
        )
        assert out.returncode == 0, out.stderr + out.stdout
        assert "job says hello" in out.stdout
        assert "SUCCEEDED" in out.stdout

        # failing entrypoint -> nonzero exit + FAILED
        out = _cli(
            ["submit", "--timeout", "120", "--",
             "python", "-c", "raise SystemExit(3)"],
            env,
        )
        assert out.returncode == 1
        assert "FAILED" in out.stdout

        out = _cli(["job", "list"], env)
        assert out.returncode == 0, out.stderr
        assert out.stdout.count("raysubmit_") == 2
    finally:
        out = _cli(["stop"], env)
    assert out.returncode == 0, out.stderr
    assert "stopped" in out.stdout


def test_job_client_python_api(ray_start_cluster):
    """JobSubmissionClient against a cluster_utils cluster: submit, poll,
    logs, stop — including a job that connects back into the cluster with
    address='auto'."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    client = JobSubmissionClient(cluster.address)
    script = (
        "import ray_tpu; ray_tpu.init(address='auto');"
        "print('cpus', int(ray_tpu.cluster_resources()['CPU']))"
    )
    sid = client.submit_job(entrypoint=f"{sys.executable} -c \"{script}\"")
    status = client.wait_until_finished(sid, timeout=180)
    logs = client.get_job_logs(sid)
    assert status == "SUCCEEDED", logs
    assert "cpus 3" in logs

    # stop a long-running job
    sid2 = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(300)'"
    )
    deadline = time.monotonic() + 60
    while client.get_job_status(sid2) == "PENDING":
        assert time.monotonic() < deadline
        time.sleep(0.2)
    assert client.stop_job(sid2)
    assert client.wait_until_finished(sid2, timeout=60) == "STOPPED"
    jobs = client.list_jobs()
    assert {j["submission_id"] for j in jobs} >= {sid, sid2}
