"""External object-spill backends (ray parity:
python/ray/_private/external_storage.py + local_object_manager.h:40):
URI-pluggable spill, restart recovery from an external URI, and the
chaos path through a real cluster with the plugin hook."""

import numpy as np
import pytest

import tests.external_store_plugin  # registers mocks3:// in this process
from ray_tpu._private.external_storage import (
    FileSystemStorage,
    make_external_storage,
)
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import LocalObjectStore


def test_filesystem_storage_roundtrip(tmp_path):
    st = make_external_storage(f"file://{tmp_path}/ext")
    assert isinstance(st, FileSystemStorage)
    src = tmp_path / "src.bin"
    src.write_bytes(b"payload" * 1000)
    st.spill("k1", str(src))
    assert st.exists("k1")
    dst = tmp_path / "back.bin"
    assert st.restore("k1", str(dst))
    assert dst.read_bytes() == b"payload" * 1000
    st.delete("k1")
    assert not st.exists("k1")
    assert not st.restore("k1", str(dst))


def test_scheme_routing(tmp_path):
    assert make_external_storage(None) is None
    assert isinstance(make_external_storage(str(tmp_path)),
                      FileSystemStorage)
    assert make_external_storage(f"mocks3://{tmp_path}/m") is not None
    with pytest.raises(ValueError, match="unknown external storage"):
        make_external_storage("azureblob://x")


def _fill_past_capacity(store, n=6, size=64 * 1024):
    oids = []
    for i in range(n):
        oid = ObjectID((bytes([i]) * 28))
        payload = bytes([i]) * size
        store.put(oid, b"meta", [payload], len(payload))
        store.pin(oid)  # pinned primaries spill rather than evict
        oids.append((oid, payload))
    return oids


def test_spill_through_custom_scheme(tmp_path):
    store = LocalObjectStore(
        str(tmp_path / "shm"), capacity_bytes=200 * 1024,
        spill_dir=f"mocks3://{tmp_path}/remote",
    )
    oids = _fill_past_capacity(store)
    stats = store.spilled_stats()
    assert stats["spilled_bytes_total"] > 0
    # the bytes really moved through the driver's layout
    assert (tmp_path / "remote" / "manifest.json").exists()
    # every object still addressable; spilled ones restore on get
    for oid, payload in oids:
        buf = store.get(oid)
        assert buf is not None
        assert bytes(buf.data) == payload
        buf.release()


def test_externally_spilled_objects_survive_store_restart(tmp_path):
    """The raylet-restart contract: a FRESH store (new ledger — the old
    raylet died) restores objects its predecessor spilled to the external
    URI, because spill keys are object-id-derived."""
    uri = f"mocks3://{tmp_path}/remote"
    store = LocalObjectStore(str(tmp_path / "shm1"), 200 * 1024, uri)
    oids = _fill_past_capacity(store)
    spilled = [
        (oid, payload) for oid, payload in oids if oid in store._spilled
    ]
    assert spilled, "nothing spilled; capacity too large for the test"

    store2 = LocalObjectStore(str(tmp_path / "shm2"), 200 * 1024, uri)
    for oid, payload in spilled:
        assert store2.contains(oid)
        buf = store2.get(oid)
        assert buf is not None, f"restart recovery failed for {oid}"
        assert bytes(buf.data) == payload
        buf.release()


class _CountingBackend:
    """exists() counter — the probe-budget contract under test."""

    def __init__(self):
        self.exists_calls = 0
        self.present = set()

    def exists(self, key):
        self.exists_calls += 1
        return key in self.present

    def spill(self, key, local_path):
        self.present.add(key)

    def restore(self, key, local_path):
        return False

    def delete(self, key):
        self.present.discard(key)


def test_contains_probes_external_backend_at_most_once(tmp_path):
    """ADVICE item: contains() for an id the backend doesn't hold must
    cost at most ONE external round trip (the restore path's documented
    contract) — routine containment checks for objects living on other
    nodes were paying a backend head per call."""
    store = LocalObjectStore(str(tmp_path / "shm"), 1024 * 1024,
                             f"mocks3://{tmp_path}/remote")
    backend = _CountingBackend()
    store._external = backend
    oid = ObjectID(b"\x07" * 28)
    for _ in range(5):
        assert not store.contains(oid)
    assert backend.exists_calls == 1  # first miss cached, 4 hits free
    # the object landing locally clears the cached miss: a later spill of
    # THIS id is probeable again
    payload = b"x" * 128
    store.put(oid, b"meta", [payload], len(payload))
    assert store.contains(oid)  # local hit, no probe
    store.delete(oid)
    backend.present.add(oid.hex() + ".obj")
    assert store.contains(oid)  # re-probed and found externally
    assert backend.exists_calls == 2


def test_register_external_clears_cached_probe_miss(tmp_path):
    store = LocalObjectStore(str(tmp_path / "shm"), 1024 * 1024,
                             f"mocks3://{tmp_path}/remote")
    backend = _CountingBackend()
    store._external = backend
    oid = ObjectID(b"\x08" * 28)
    assert not store.contains(oid)
    assert oid in store._probe_missed
    # a worker writes the object directly into shm and registers it
    from ray_tpu._private.object_store import write_object

    write_object(str(tmp_path / "shm"), oid, b"m", [b"data"], 4)
    store.register_external(oid)
    assert oid not in store._probe_missed
    assert store.contains(oid)


def test_cluster_spills_through_plugin_scheme(tmp_path, monkeypatch):
    """e2e: a real cluster configured with the plugin scheme spills under
    memory pressure and restores on get (the IO-worker-style path)."""
    monkeypatch.setenv("RAY_TPU_external_storage_setup_module",
                       "tests.external_store_plugin")
    monkeypatch.setenv("RAY_TPU_object_spill_dir",
                       f"mocks3://{tmp_path}/cluster_remote")
    # small store so a handful of arrays forces spilling
    monkeypatch.setenv("RAY_TPU_object_store_memory", str(8 * 1024 * 1024))

    import ray_tpu

    ray_tpu.init(num_cpus=2)
    try:
        refs = []
        arrays = []
        for i in range(8):
            a = np.full(2 * 1024 * 1024, i, dtype=np.uint8)
            arrays.append(a)
            refs.append(ray_tpu.put(a))
        # everything must still be retrievable (later puts spilled earlier
        # ones); correctness beats placement here
        for i, (r, a) in enumerate(zip(refs, arrays)):
            got = ray_tpu.get(r, timeout=60)
            assert got.nbytes == a.nbytes and got[0] == i
            del got
        assert (tmp_path / "cluster_remote" / "manifest.json").exists()
    finally:
        ray_tpu.shutdown()
