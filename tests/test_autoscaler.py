"""Autoscaler: unit (MockProvider) + e2e (FakeTpuPodProvider launches
real raylets for TPU-slice demand).

ray parity: python/ray/tests/test_autoscaler.py (MockProvider-driven) and
test_autoscaler_fake_multinode.py.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import FakeTpuPodProvider, MockProvider, StandardAutoscaler

NODE_TYPES = {
    "tpu_v5e_8": {"resources": {"TPU": 8.0, "CPU": 8.0},
                  "min_workers": 0, "max_workers": 2},
    "cpu_worker": {"resources": {"CPU": 4.0},
                   "min_workers": 0, "max_workers": 3},
}


def test_scale_up_for_demand_unit():
    provider = MockProvider()
    scaler = StandardAutoscaler(provider, NODE_TYPES)
    # 2 TPU bundles that no live node absorbs -> one v5e-8 slice covers
    # the first, second fits the same slice's remaining capacity.
    out = scaler.update(load={
        "nodes": [],
        "pending_demand": [{"TPU": 4.0}, {"TPU": 4.0}, {"CPU": 2.0}],
    })
    assert out["launched"].get("tpu_v5e_8") == 1
    # the CPU bundle fit the slice's CPUs; no cpu_worker needed
    assert "cpu_worker" not in out["launched"]
    assert len(provider.non_terminated_nodes()) == 1


def test_max_workers_cap_and_min_workers_floor():
    provider = MockProvider()
    types = {
        "tpu_v5e_8": {"resources": {"TPU": 8.0}, "min_workers": 1,
                      "max_workers": 2},
    }
    scaler = StandardAutoscaler(provider, types)
    out = scaler.update(load={"nodes": [], "pending_demand": []})
    assert out["launched"] == {"tpu_v5e_8": 1}  # min_workers floor

    # Demand for 5 full slices: capped at max_workers=2 total.
    out = scaler.update(load={
        "nodes": [],
        "pending_demand": [{"TPU": 8.0} for _ in range(5)],
    })
    assert len(provider.non_terminated_nodes()) == 2


def test_no_relaunch_for_pending_nodes():
    provider = MockProvider()
    scaler = StandardAutoscaler(provider, NODE_TYPES)
    load = {"nodes": [], "pending_demand": [{"TPU": 8.0}]}
    scaler.update(load=load)
    # Same unmet demand again, but the launched node is still booting
    # (absent from load["nodes"]): its capacity must count, no relaunch.
    scaler.update(load=load)
    assert len(provider.non_terminated_nodes()) == 1


def test_autoscaler_e2e_fake_tpu_pod(ray_start_cluster):
    """Infeasible TPU task -> autoscaler launches a fake v5e slice raylet
    -> task runs there; idle slice is torn down after the timeout."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)  # head: no TPUs
    ray_tpu.init(address=cluster.address)

    provider = FakeTpuPodProvider(
        "127.0.0.1", cluster.head.gcs_port, cluster.head.session_dir,
        NODE_TYPES,
    )
    scaler = StandardAutoscaler(
        provider, NODE_TYPES, gcs_address=cluster.address,
        idle_timeout_s=3.0,
    )
    try:
        @ray_tpu.remote(resources={"TPU": 8.0})
        def on_tpu():
            import ray_tpu as rt

            return rt.get_runtime_context().get_node_id()

        ref = on_tpu.remote()
        # Let the demand reach a heartbeat, then reconcile.
        deadline = time.monotonic() + 60
        launched = {}
        while time.monotonic() < deadline and not launched:
            time.sleep(1.0)
            launched = scaler.update()["launched"]
        assert launched.get("tpu_v5e_8") == 1
        tpu_node = ray_tpu.get(ref, timeout=120)
        head_node = ray_tpu.get_runtime_context().get_node_id()
        assert tpu_node != head_node

        # After the task finishes and the slice idles, it is terminated.
        deadline = time.monotonic() + 90
        terminated = []
        while time.monotonic() < deadline and not terminated:
            time.sleep(1.5)
            terminated = scaler.update()["terminated"]
        assert terminated, "idle TPU slice was not scaled down"
        assert provider.non_terminated_nodes() == {}
    finally:
        provider.shutdown()


SLICE_TYPES = {
    # one unit = a v5e-16 slice: 4 hosts x {TPU: 4, CPU: 8}
    "tpu_v5e_16": {"accelerator_type": "v5litepod-16", "topology": "4x4",
                   "hosts": 4, "resources": {"TPU": 4.0, "CPU": 8.0},
                   "min_workers": 0, "max_workers": 2},
}


class _RecordingQR:
    """QueuedResourceAPI double that records calls without provisioning."""

    def __init__(self):
        self.created = []
        self.deleted = []

    def create(self, name, accelerator_type, topology, num_hosts):
        self.created.append((name, accelerator_type, topology, num_hosts))
        return name

    def status(self, request_id):
        return {"state": "ACTIVE", "hosts": []}

    def delete(self, request_id):
        self.deleted.append(request_id)


def test_slice_granularity_unit():
    """Scale-up granularity is a whole slice: 4 x {TPU:4} bundles need
    exactly ONE v5e-16 slice (4 hosts), not 4 independent nodes; a 5th
    bundle tips to a second slice; a {TPU:16} bundle fits no single host
    and is infeasible."""
    from ray_tpu.autoscaler import StandardAutoscaler, TpuPodProvider

    api = _RecordingQR()
    provider = TpuPodProvider(api, SLICE_TYPES)
    scaler = StandardAutoscaler(provider, SLICE_TYPES)

    out = scaler.update(load={
        "nodes": [],
        "pending_demand": [{"bundle": {"TPU": 4.0}, "count": 4}],
    })
    assert out["launched"] == {"tpu_v5e_16": 1}
    assert len(api.created) == 1
    name, acc, topo, hosts = api.created[0]
    assert (acc, topo, hosts) == ("v5litepod-16", "4x4", 4)

    # 5 bundles: one slice absorbs 4, the 5th needs a second slice.
    api2 = _RecordingQR()
    scaler2 = StandardAutoscaler(TpuPodProvider(api2, SLICE_TYPES),
                                 SLICE_TYPES)
    out = scaler2.update(load={
        "nodes": [],
        "pending_demand": [{"bundle": {"TPU": 4.0}, "count": 5}],
    })
    assert out["launched"] == {"tpu_v5e_16": 2}

    # A bundle bigger than one host is infeasible on this type.
    api3 = _RecordingQR()
    scaler3 = StandardAutoscaler(TpuPodProvider(api3, SLICE_TYPES),
                                 SLICE_TYPES)
    out = scaler3.update(load={
        "nodes": [], "pending_demand": [{"TPU": 16.0}],
    })
    assert out["launched"] == {}


def test_autoscaler_e2e_tpu_pod_pg(ray_start_cluster):
    """Pending TPU placement-group demand launches ONE fake v5e-16
    multi-host slice (4 raylets join together) and the PG packs its
    bundles onto the slice's hosts."""
    from ray_tpu.autoscaler import (FakeQueuedResourceAPI,
                                    StandardAutoscaler, TpuPodProvider)
    from ray_tpu.util.placement_group import placement_group

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)  # head: no TPUs
    ray_tpu.init(address=cluster.address)

    api = FakeQueuedResourceAPI(
        "127.0.0.1", cluster.head.gcs_port, cluster.head.session_dir,
        resources_per_host={"v5litepod-16": {"TPU": 4.0, "CPU": 8.0}},
    )
    provider = TpuPodProvider(api, SLICE_TYPES)
    scaler = StandardAutoscaler(
        provider, SLICE_TYPES, gcs_address=cluster.address,
        idle_timeout_s=3600.0,
    )
    try:
        pg = placement_group([{"TPU": 4.0}] * 4, strategy="PACK")

        deadline = time.monotonic() + 60
        launched = {}
        while time.monotonic() < deadline and not launched:
            time.sleep(1.0)
            launched = scaler.update()["launched"]
        assert launched.get("tpu_v5e_16") == 1, launched

        assert pg.wait(timeout_seconds=120), "PG not ready on new slice"

        # Every bundle landed on a host of the ONE slice we launched
        # (committed placement from the PG table; running tasks on all 4
        # cold hosts would just measure worker spawn on this 1-core box).
        from ray_tpu.util import state as state_api

        table = state_api.list_placement_groups()
        mine = [t for t in table if t["placement_group_id"] == pg.id_hex]
        assert mine and mine[0]["state"] == "CREATED"
        bundle_nodes = mine[0]["bundle_nodes"]
        assert len(bundle_nodes) == 4
        labels = {n["node_id"]: n.get("labels", {})
                  for n in ray_tpu.nodes()}
        slices = {labels[nid].get("tpu-slice") for nid in bundle_nodes}
        assert len(slices) == 1 and None not in slices, slices
        assert len(set(bundle_nodes)) == 4  # one bundle per host

        # And a PG-scheduled task actually executes on the slice
        # (num_cpus=0: the bundles reserve only TPU, and a task may not
        # demand resources its bundle never committed — ray semantics).
        @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=0)
        def where():
            import ray_tpu as rt

            return rt.get_runtime_context().get_node_id()

        node = ray_tpu.get(
            where.options(scheduling_strategy=None, placement_group=pg,
                          placement_group_bundle_index=0).remote(),
            timeout=180,
        )
        assert node in bundle_nodes
    finally:
        provider.shutdown()
