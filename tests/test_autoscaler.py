"""Autoscaler: unit (MockProvider) + e2e (FakeTpuPodProvider launches
real raylets for TPU-slice demand).

ray parity: python/ray/tests/test_autoscaler.py (MockProvider-driven) and
test_autoscaler_fake_multinode.py.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import FakeTpuPodProvider, MockProvider, StandardAutoscaler

NODE_TYPES = {
    "tpu_v5e_8": {"resources": {"TPU": 8.0, "CPU": 8.0},
                  "min_workers": 0, "max_workers": 2},
    "cpu_worker": {"resources": {"CPU": 4.0},
                   "min_workers": 0, "max_workers": 3},
}


def test_scale_up_for_demand_unit():
    provider = MockProvider()
    scaler = StandardAutoscaler(provider, NODE_TYPES)
    # 2 TPU bundles that no live node absorbs -> one v5e-8 slice covers
    # the first, second fits the same slice's remaining capacity.
    out = scaler.update(load={
        "nodes": [],
        "pending_demand": [{"TPU": 4.0}, {"TPU": 4.0}, {"CPU": 2.0}],
    })
    assert out["launched"].get("tpu_v5e_8") == 1
    # the CPU bundle fit the slice's CPUs; no cpu_worker needed
    assert "cpu_worker" not in out["launched"]
    assert len(provider.non_terminated_nodes()) == 1


def test_max_workers_cap_and_min_workers_floor():
    provider = MockProvider()
    types = {
        "tpu_v5e_8": {"resources": {"TPU": 8.0}, "min_workers": 1,
                      "max_workers": 2},
    }
    scaler = StandardAutoscaler(provider, types)
    out = scaler.update(load={"nodes": [], "pending_demand": []})
    assert out["launched"] == {"tpu_v5e_8": 1}  # min_workers floor

    # Demand for 5 full slices: capped at max_workers=2 total.
    out = scaler.update(load={
        "nodes": [],
        "pending_demand": [{"TPU": 8.0} for _ in range(5)],
    })
    assert len(provider.non_terminated_nodes()) == 2


def test_no_relaunch_for_pending_nodes():
    provider = MockProvider()
    scaler = StandardAutoscaler(provider, NODE_TYPES)
    load = {"nodes": [], "pending_demand": [{"TPU": 8.0}]}
    scaler.update(load=load)
    # Same unmet demand again, but the launched node is still booting
    # (absent from load["nodes"]): its capacity must count, no relaunch.
    scaler.update(load=load)
    assert len(provider.non_terminated_nodes()) == 1


def test_autoscaler_e2e_fake_tpu_pod(ray_start_cluster):
    """Infeasible TPU task -> autoscaler launches a fake v5e slice raylet
    -> task runs there; idle slice is torn down after the timeout."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)  # head: no TPUs
    ray_tpu.init(address=cluster.address)

    provider = FakeTpuPodProvider(
        "127.0.0.1", cluster.head.gcs_port, cluster.head.session_dir,
        NODE_TYPES,
    )
    scaler = StandardAutoscaler(
        provider, NODE_TYPES, gcs_address=cluster.address,
        idle_timeout_s=3.0,
    )
    try:
        @ray_tpu.remote(resources={"TPU": 8.0})
        def on_tpu():
            import ray_tpu as rt

            return rt.get_runtime_context().get_node_id()

        ref = on_tpu.remote()
        # Let the demand reach a heartbeat, then reconcile.
        deadline = time.monotonic() + 60
        launched = {}
        while time.monotonic() < deadline and not launched:
            time.sleep(1.0)
            launched = scaler.update()["launched"]
        assert launched.get("tpu_v5e_8") == 1
        tpu_node = ray_tpu.get(ref, timeout=120)
        head_node = ray_tpu.get_runtime_context().get_node_id()
        assert tpu_node != head_node

        # After the task finishes and the slice idles, it is terminated.
        deadline = time.monotonic() + 90
        terminated = []
        while time.monotonic() < deadline and not terminated:
            time.sleep(1.5)
            terminated = scaler.update()["terminated"]
        assert terminated, "idle TPU slice was not scaled down"
        assert provider.non_terminated_nodes() == {}
    finally:
        provider.shutdown()
