"""Torus topology model + contention-aware gang placement (topology.py).

Pure-function tests over NodeInfo views: coordinate parsing, ring-link
geometry, compactness, contention scoring, the topology-aware candidate
search (which must inherit strategy semantics from the resource-fit
oracle, never weaken them), and the fragmentation repack planner. The
degrade contract — no coords advertised -> byte-identical to today's
resource-fit path — is tested against place_bundles_py directly.
"""

import random

import pytest

from ray_tpu._private import topology
from ray_tpu._private.common import (
    NodeInfo,
    place_bundles,
    place_bundles_py,
    res_fits,
    res_sub,
)

pytestmark = pytest.mark.schedsim


def make_node(nid, cpu=4.0, avail=None, coord=None, dims=None, labels=None):
    labels = dict(labels or {})
    if coord is not None:
        labels[topology.COORD_LABEL] = topology.format_coord(coord)
    if dims is not None:
        labels[topology.DIMS_LABEL] = topology.format_coord(dims)
    return NodeInfo(
        node_id=nid, host="h", port=0, store_dir="",
        resources_total={"CPU": cpu},
        resources_available={"CPU": cpu if avail is None else avail},
        labels=labels,
    )


def grid(nx, ny, cpu=4.0, prefix="n"):
    return [
        make_node(f"{prefix}{x}_{y}", cpu=cpu, coord=(x, y), dims=(nx, ny))
        for y in range(ny) for x in range(nx)
    ]


def test_parse_and_format_coord():
    assert topology.parse_coord("0x1") == (0, 1)
    assert topology.parse_coord("0,1,2") == (0, 1, 2)  # legacy commas ok
    assert topology.parse_coord("3") == (3,)
    assert topology.parse_coord("") is None
    assert topology.parse_coord("a,b") is None
    assert topology.parse_coord("1x2x3x4") is None  # >3 dims
    assert topology.format_coord((2, 0, 1)) == "2x0x1"
    # the canonical form is wire-safe for the native scheduler
    from ray_tpu._private.native_sched import _clean

    assert _clean(topology.format_coord((1, 2, 3)))


def test_from_nodes_requires_two_coords_and_infers_dims():
    assert topology.Topology.from_nodes([make_node("a")]) is None
    assert topology.Topology.from_nodes(
        [make_node("a", coord=(0, 0)), make_node("b")]) is None
    topo = topology.Topology.from_nodes(
        [make_node("a", coord=(0, 0)), make_node("b", coord=(3, 1))])
    assert topo is not None and topo.dims == (4, 2)  # inferred max+1
    # explicit dims win when larger than observed
    topo = topology.Topology.from_nodes(
        [make_node("a", coord=(0, 0), dims=(8, 8)),
         make_node("b", coord=(1, 0), dims=(8, 8))])
    assert topo.dims == (8, 8)


def test_ring_links_row_is_a_cycle():
    nodes = grid(4, 4)
    topo = topology.Topology.from_nodes(nodes)
    row = [f"n{x}_0" for x in range(4)]
    links = topo.ring_links(row)
    # a full row of a 4-torus rings through the wraparound link
    assert links == frozenset({
        ((0, 0), (1, 0)), ((1, 0), (2, 0)), ((2, 0), (3, 0)),
        ((0, 0), (3, 0)),
    })
    assert topo.ring_links(["n0_0"]) == frozenset()
    assert topo.ring_links([]) == frozenset()


def test_compactness_slice_vs_scatter_and_wraparound():
    topo = topology.Topology.from_nodes(grid(4, 4))
    row = [f"n{x}_0" for x in range(4)]
    assert topo.compactness(row) == 1.0
    scattered = ["n0_0", "n2_0", "n0_2", "n2_2"]
    assert topo.compactness(scattered) > 1.0
    # a block wrapping the torus edge is as compact as an interior one
    interior = ["n1_0", "n2_0"]
    wrapping = ["n0_0", "n3_0"]
    assert topo.compactness(wrapping) == topo.compactness(interior)


def test_contention_score_counts_shared_links():
    topo = topology.Topology.from_nodes(grid(4, 4))
    row0 = [f"n{x}_0" for x in range(4)]
    row1 = [f"n{x}_1" for x in range(4)]
    ring0 = topo.ring_links(row0)
    assert topo.score(row1, {"g0": ring0}).contention == 0
    assert topo.score(row0, {"g0": ring0}).contention == len(ring0)


def test_link_capacity_weights_contention():
    """torus-link-caps: a shared link on a half-capacity dimension
    contends twice as hard; unit capacity degrades to a link count."""
    nodes = [
        make_node(f"n{x}_{y}", coord=(x, y), dims=(4, 4),
                  labels={topology.LINK_CAPS_LABEL: "2x1"})
        for y in range(4) for x in range(4)
    ]
    topo = topology.Topology.from_nodes(nodes)
    assert topo.link_caps == (2.0, 1.0)
    row = [f"n{x}_0" for x in range(4)]  # dim-0 links, capacity 2
    col = [f"n0_{y}" for y in range(4)]  # dim-1 links, capacity 1
    assert topo.score(row, {"g": topo.ring_links(row)}).contention == 2.0
    assert topo.score(col, {"g": topo.ring_links(col)}).contention == 4.0


def test_overlap_ratio_bounds():
    topo = topology.Topology.from_nodes(grid(4, 4))
    r0 = topo.ring_links([f"n{x}_0" for x in range(4)])
    r1 = topo.ring_links([f"n{x}_1" for x in range(4)])
    assert topo.overlap_ratio({}) == 0.0
    assert topo.overlap_ratio({"a": r0}) == 0.0
    assert topo.overlap_ratio({"a": r0, "b": r1}) == 0.0
    assert topo.overlap_ratio({"a": r0, "b": r0}) == 1.0


def test_place_bundles_topo_avoids_committed_ring():
    nodes = grid(4, 4, cpu=4.0)
    topo = topology.Topology.from_nodes(nodes)
    row0 = [f"n{x}_0" for x in range(4)]
    committed = {"g0": topo.ring_links(row0)}
    # occupy row 0 so the oracle can't pick it anyway? No — leave it
    # free: the scorer must avoid it by CHOICE, not by capacity.
    placed = topology.place_bundles_topo(
        nodes, [{"CPU": 4.0}] * 4, "STRICT_SPREAD", topo, committed)
    assert placed is not None
    placement, score = placed
    assert score.contention == 0
    assert not (topo.ring_links(placement) & committed["g0"])


def test_place_bundles_topo_inherits_strategy_semantics():
    rng = random.Random(5)
    nodes = grid(6, 6, cpu=4.0)
    for n in nodes:  # fragment the cluster
        if rng.random() < 0.4:
            n.resources_available = {"CPU": rng.choice([0.0, 1.0, 2.0])}
    topo = topology.Topology.from_nodes(nodes)
    for strategy in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        bundles = [{"CPU": rng.choice([1.0, 2.0])} for _ in range(4)]
        placed = topology.place_bundles_topo(
            nodes, bundles, strategy, topo, {})
        oracle = place_bundles_py(nodes, bundles, strategy)
        assert (placed is None) == (oracle is None), strategy
        if placed is None:
            continue
        placement, _ = placed
        assert_valid_placement(nodes, bundles, strategy, placement)


def assert_valid_placement(nodes, bundles, strategy, placement):
    """A placement honors the strategy and fits: shared validator used
    by the topo tests here and the native-parity property test."""
    by_id = {n.node_id: n for n in nodes}
    assert len(placement) == len(bundles)
    avail = {nid: dict(n.resources_available) for nid, n in by_id.items()}
    for nid, b in zip(placement, bundles):
        assert by_id[nid].alive
        assert res_fits(b, avail[nid]), (nid, b, avail[nid])
        res_sub(avail[nid], b)
    if strategy == "STRICT_SPREAD":
        assert len(set(placement)) == len(placement)
    if strategy == "STRICT_PACK":
        assert len(set(placement)) == 1


def test_no_coords_degrades_to_resource_fit():
    """The degrade contract: a topology-less cluster's place_bundles is
    byte-identical to the oracle path (the wrapper must not even build
    a Topology when none is passed)."""
    nodes = [make_node(f"p{i}", cpu=4.0) for i in range(6)]
    for strategy in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        bundles = [{"CPU": 2.0}] * 3
        assert place_bundles(nodes, bundles, strategy) == \
            place_bundles_py(nodes, bundles, strategy)
    assert topology.Topology.from_nodes(nodes) is None


def test_wrapper_threads_topology():
    """common.place_bundles with topology= dispatches to the scorer."""
    nodes = grid(4, 4, cpu=4.0)
    topo = topology.Topology.from_nodes(nodes)
    row0 = [f"n{x}_0" for x in range(4)]
    committed = {"g0": topo.ring_links(row0)}
    placement = place_bundles(
        nodes, [{"CPU": 4.0}] * 4, "STRICT_SPREAD",
        topology=topo, committed_rings=committed)
    assert placement is not None
    assert not (topo.ring_links(placement) & committed["g0"])


def test_plan_repack_migrates_idle_bundle():
    # n0 full (idle bundle), n1 free, n2 full (running), n3 big with room:
    # strict-spread 3x4CPU needs 3 distinct nodes -> repack parks the
    # idle bundle on the big node and frees n0.
    nodes = [
        make_node("n0", cpu=4.0, avail=0.0),
        make_node("n1", cpu=4.0, avail=4.0),
        make_node("n2", cpu=4.0, avail=0.0),
        make_node("n3", cpu=8.0, avail=8.0),
    ]
    plan = topology.plan_repack(
        nodes, [{"CPU": 4.0}] * 3, "STRICT_SPREAD",
        [("pgA", 0, "n0", {"CPU": 4.0})])
    assert plan is not None
    placement, moves = plan
    assert sorted(placement) == ["n0", "n1", "n3"]
    assert len(moves) == 1 and moves[0].to_node == "n3"


def test_plan_repack_gives_up_when_unsolvable():
    # exact-fit cluster: moving the idle bundle anywhere just relocates
    # the hole — the planner must return None, not livelock
    nodes = [
        make_node("n0", cpu=4.0, avail=0.0),
        make_node("n1", cpu=4.0, avail=4.0),
        make_node("n2", cpu=4.0, avail=0.0),
        make_node("n3", cpu=4.0, avail=4.0),
    ]
    plan = topology.plan_repack(
        nodes, [{"CPU": 4.0}] * 3, "STRICT_SPREAD",
        [("pgA", 0, "n0", {"CPU": 4.0})])
    assert plan is None


def test_pg_table_carries_topology_provenance(ray_start_cluster):
    """End to end on a real cluster advertising coords: the GCS places
    gangs via the contention scorer, stamps node_coords /
    contention_score / sched_strategy on the pg table, and the second
    identical gang (forced onto the same nodes) records the ring overlap
    the first one created."""
    import ray_tpu
    from ray_tpu.util.placement_group import (placement_group,
                                              placement_group_table)

    cluster = ray_start_cluster
    dims = topology.format_coord((2, 2))
    for c in ((0, 0), (1, 0), (0, 1), (1, 1)):
        cluster.add_node(num_cpus=2, labels={
            topology.COORD_LABEL: topology.format_coord(c),
            topology.DIMS_LABEL: dims,
        })
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)

    pg = placement_group([{"CPU": 1.0}] * 4, strategy="STRICT_SPREAD")
    assert pg.wait(60)
    t = placement_group_table(pg)
    assert t["sched_strategy"] == "topology-contention"
    assert t["contention_score"] == 0.0
    assert sorted(t["node_coords"]) == ["0x0", "0x1", "1x0", "1x1"]
    assert t["repack_moves"] == 0

    # same four nodes again: the second ring must overlap the first
    pg2 = placement_group([{"CPU": 1.0}] * 4, strategy="STRICT_SPREAD")
    assert pg2.wait(60)
    t2 = placement_group_table(pg2)
    assert t2["sched_strategy"] == "topology-contention"
    assert t2["contention_score"] > 0.0


def test_pg_return_if_idle_guards_consumed_bundles(ray_start_cluster):
    """The repack pass's safety gate: the raylet releases a bundle only
    when nothing consumes (or queues against) its reservation — the
    GCS's heartbeat view may be a beat stale, so the raylet is the
    authority."""
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.state import _node_request

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)

    pg = placement_group([{"CPU": 1.0}], strategy="PACK")
    assert pg.wait(60)

    @ray_tpu.remote(num_cpus=1)
    class Holder:
        def ping(self):
            return 1

    a = Holder.options(placement_group=pg,
                       placement_group_bundle_index=0).remote()
    assert ray_tpu.get(a.ping.remote()) == 1

    from ray_tpu.util.placement_group import placement_group_table

    t = placement_group_table(pg)
    node = next(n for n in ray_tpu.nodes()
                if n["node_id"] == t["bundle_nodes"][0])
    busy = _node_request(node, "pg_return_if_idle",
                         {"pg_id": pg.id_hex, "bundle_index": 0})
    assert busy == {"ok": False, "reason": "in use"}

    ray_tpu.kill(a)
    import time as _t

    deadline = _t.monotonic() + 20
    while _t.monotonic() < deadline:
        r = _node_request(node, "pg_return_if_idle",
                          {"pg_id": pg.id_hex, "bundle_index": 0})
        if r and r.get("ok"):
            break
        _t.sleep(0.2)
    assert r == {"ok": True}
    # released: a second conditional return finds nothing to release
    r2 = _node_request(node, "pg_return_if_idle",
                       {"pg_id": pg.id_hex, "bundle_index": 0})
    assert r2 == {"ok": False, "reason": "unknown bundle"}


def test_synthesize_coords_unique_and_sized():
    coords = topology.synthesize(10)
    assert len(coords) == len(set(coords)) == 10
    coords = topology.synthesize(8, dims=(2, 2, 2))
    assert len(set(coords)) == 8
    assert all(len(c) == 3 for c in coords)
