"""TransformersTrainer: HF Trainer per worker under the gloo group, with
report/checkpoint bridging (ray parity: train/huggingface/transformers)."""

import numpy as np
import pytest


@pytest.mark.slow  # 27s HF-integration test: slow lane (tier-1 budget)
def test_transformers_trainer_two_workers(ray_start_regular, tmp_path):
    import ray_tpu.train as train
    from ray_tpu.air.config import RunConfig, ScalingConfig

    out_dir = str(tmp_path / "hf_out")

    def trainer_init(config):
        import torch
        from transformers import (
            GPT2Config,
            GPT2LMHeadModel,
            Trainer,
            TrainingArguments,
        )

        model = GPT2LMHeadModel(GPT2Config(
            vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2,
        ))

        class ToyLM(torch.utils.data.Dataset):
            def __len__(self):
                return 32

            def __getitem__(self, i):
                g = torch.Generator().manual_seed(i)
                ids = torch.randint(0, 128, (16,), generator=g)
                return {"input_ids": ids, "labels": ids.clone()}

        args = TrainingArguments(
            output_dir=config["output_dir"],
            max_steps=4,
            per_device_train_batch_size=4,
            logging_steps=1,
            save_steps=4,
            save_total_limit=1,
            report_to=[],
            use_cpu=True,
            disable_tqdm=True,
        )
        return Trainer(model=model, args=args, train_dataset=ToyLM())

    trainer = train.TransformersTrainer(
        trainer_init,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="hf_test"),
        train_loop_config={"output_dir": out_dir},
    )
    result = trainer.fit()
    assert result.error is None, result.error
    # last report is HF's end-of-train summary (train_loss); per-step
    # reports carried 'loss'
    assert result.metrics and (
        "loss" in result.metrics or "train_loss" in result.metrics
    ), result.metrics
    assert result.metrics["step"] >= 4
    # the HF checkpoint rode through as a Train checkpoint
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        import os

        assert any("model" in f or "safetensors" in f or "bin" in f
                   for f in os.listdir(d)), os.listdir(d)
