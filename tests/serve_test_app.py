"""Importable Serve application for declarative-deploy tests (the
`import_path` target, like the reference's test config modules)."""

from ray_tpu import serve


@serve.deployment
class Echo:
    def __call__(self, request):
        return {"echo": getattr(request, "query", {}).get("m", "none")}


app = Echo.bind()


def app_builder():
    return Echo.options(name="BuiltEcho").bind()


# --- typed gRPC fixtures (stand-ins for protoc-generated code) -----------
# Real deployments pass protoc output; these hand-rolled messages expose
# the same surface the generated code uses (FromString / SerializeToString
# + an add_XServicer_to_server registrar), so the typed-servicer plumbing
# is exercised without a .proto compile step in the image.


class TextRequest:
    def __init__(self, text: str = ""):
        self.text = text

    def SerializeToString(self) -> bytes:
        return self.text.encode()

    @classmethod
    def FromString(cls, data: bytes) -> "TextRequest":
        return cls(data.decode())


class TextReply:
    def __init__(self, text: str = "", length: int = 0):
        self.text = text
        self.length = length

    def SerializeToString(self) -> bytes:
        import json as _j

        return _j.dumps({"text": self.text, "length": self.length}).encode()

    @classmethod
    def FromString(cls, data: bytes) -> "TextReply":
        import json as _j

        d = _j.loads(data.decode())
        return cls(d["text"], d["length"])


def add_TextServicer_to_server(servicer, server):
    """Shape of protoc's generated add_XServicer_to_server."""
    import grpc

    handlers = {
        "Upper": grpc.unary_unary_rpc_method_handler(
            servicer.Upper,
            request_deserializer=TextRequest.FromString,
            response_serializer=lambda r: r.SerializeToString(),
        ),
        "Spell": grpc.unary_stream_rpc_method_handler(
            servicer.Spell,
            request_deserializer=TextRequest.FromString,
            response_serializer=lambda r: r.SerializeToString(),
        ),
    }
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler("test.TextService", handlers),
    ))


@serve.deployment
class TextService:
    """Typed gRPC deployment: methods named after the service's RPCs,
    receiving/returning the proto messages."""

    def Upper(self, request: TextRequest) -> TextReply:
        return TextReply(request.text.upper(), len(request.text))

    def Spell(self, request: TextRequest):
        for ch in request.text:
            yield TextReply(ch, 1)


text_app = TextService.bind()
