"""Importable Serve application for declarative-deploy tests (the
`import_path` target, like the reference's test config modules)."""

from ray_tpu import serve


@serve.deployment
class Echo:
    def __call__(self, request):
        return {"echo": getattr(request, "query", {}).get("m", "none")}


app = Echo.bind()


def app_builder():
    return Echo.options(name="BuiltEcho").bind()
