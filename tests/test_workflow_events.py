"""Workflow event listeners + management actor (ray parity:
python/ray/workflow/event_listener.py + workflow_access.py)."""

import time

import pytest

import ray_tpu
from ray_tpu import workflow


def test_event_checkpoint_and_ack(ray_start_regular, tmp_path):
    """An observed event is checkpointed: resume never re-polls, and
    event_checkpointed fires exactly once, after durability."""
    polls = tmp_path / "polls"
    acks = tmp_path / "acks"

    class FileListener(workflow.EventListener):
        def __init__(self, payload):
            self.payload = payload

        def poll_for_event(self):
            polls.write_text(str(int(polls.read_text() or 0) + 1)
                             if polls.exists() else "1")
            return self.payload

        def event_checkpointed(self, event):
            acks.write_text(str(int(acks.read_text() or 0) + 1)
                            if acks.exists() else "1")

    @ray_tpu.remote
    def consume(ev):
        return f"got:{ev}"

    storage = str(tmp_path / "wf")
    dag = consume.bind(workflow.wait_for_event(FileListener, "E1"))
    out = workflow.run(dag, workflow_id="evwf", storage=storage)
    assert out == "got:E1"
    assert polls.read_text() == "1" and acks.read_text() == "1"

    # resume: the event step replays from its checkpoint — no new poll
    dag2 = consume.bind(workflow.wait_for_event(FileListener, "E1"))
    out2 = workflow.resume("evwf", dag2, storage=storage)
    assert out2 == "got:E1"
    assert polls.read_text() == "1", "resume must not re-wait for events"


def test_timer_listener(ray_start_regular, tmp_path):
    @ray_tpu.remote
    def after(ts):
        return time.time() >= ts

    fire_at = time.time() + 1.0
    dag = after.bind(workflow.wait_for_event(workflow.TimerListener,
                                             fire_at))
    assert workflow.run(dag, storage=str(tmp_path / "wf")) is True


def test_cancel_via_management_actor(ray_start_regular, tmp_path):
    """A long workflow canceled from 'outside' (the management actor)
    stops before its next step; status and registry reflect CANCELED."""
    storage = str(tmp_path / "wf")

    @ray_tpu.remote
    def slow_step(i):
        time.sleep(1.5)
        return i

    @ray_tpu.remote
    def combine(*xs):
        return sum(xs)

    # a chain of slow steps gives cancel a window between steps
    n1 = slow_step.bind(1)
    n2 = combine.bind(n1)
    n3 = slow_step.bind(n2)
    n4 = combine.bind(n3)
    fut = workflow.run_async(n4, workflow_id="cancelme", storage=storage)
    time.sleep(0.5)  # let it register + start step 1
    workflow.cancel("cancelme", storage=storage)
    with pytest.raises(workflow.WorkflowCancellationError):
        fut.result(timeout=60)
    assert workflow.get_status("cancelme", storage=storage) == "CANCELED"

    runs = ray_tpu.get(
        workflow.get_management_actor().list_runs.remote(), timeout=30
    )
    assert runs["cancelme"]["status"] == "CANCELED"
    assert runs["cancelme"]["host"]
