"""ExperimentAnalysis: offline queries against a finished experiment dir
(ray parity: tune/analysis/experiment_analysis.py)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.analysis import ExperimentAnalysis


@pytest.fixture(scope="module")
def finished_experiment():
    ray_tpu.init(num_cpus=4)

    def objective(config):
        for i in range(5):
            tune.report({"score": config["rate"] * (i + 1)})

    grid = tune.Tuner(
        objective,
        param_space={"rate": tune.grid_search([1.0, 3.0, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    ).fit()
    yield grid.experiment_path
    ray_tpu.shutdown()


def test_offline_best_queries(finished_experiment):
    ea = ExperimentAnalysis(finished_experiment)
    # defaults recovered from the experiment snapshot
    assert ea.default_metric == "score" and ea.default_mode == "max"
    assert len(ea.trials) == 3
    best = ea.best_result()
    assert best["score"] == pytest.approx(15.0)  # rate 3.0 * 5 steps
    assert ea.best_config()["rate"] == 3.0
    # explicit min flips the choice
    worst_cfg = ea.best_config(metric="score", mode="min")
    assert worst_cfg["rate"] == 1.0


def test_dataframes(finished_experiment):
    ea = ExperimentAnalysis(finished_experiment)
    df = ea.dataframe()
    assert len(df) == 3
    assert set(df["config/rate"]) == {1.0, 2.0, 3.0}
    assert df["score"].max() == pytest.approx(15.0)
    per_trial = ea.trial_dataframes()
    # 5 reports + the terminal duplicate-result line
    assert all(len(v) in (5, 6) for v in per_trial.values())


def test_missing_dir_and_empty_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        ExperimentAnalysis(str(tmp_path / "nope"))
    with pytest.raises(ValueError, match="result.json"):
        ExperimentAnalysis(str(tmp_path))
