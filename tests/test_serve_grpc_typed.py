"""Typed gRPC ingress: user servicer registration via generated-style
``add_XServicer_to_server`` functions (ray parity:
serve.config.gRPCOptions.grpc_servicer_functions + the DummyServicer in
serve/_private/grpc_util.py). Clients call typed stubs with proto
(de)serializers; deployments receive/return message objects."""

import grpc
import pytest

import ray_tpu
from ray_tpu import serve
from tests.serve_test_app import TextReply, TextRequest, text_app


@pytest.fixture
def typed_serve(ray_start_regular):
    serve.start(grpc_options={
        "grpc_servicer_functions": [
            "tests.serve_test_app:add_TextServicer_to_server",
        ],
    })
    serve.run(text_app, name="textapp", route_prefix="/")
    controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
    proxies = ray_tpu.get(controller.get_proxies.remote(), timeout=30)
    port = next(iter(proxies.values()))["grpc_port"]
    assert port, "gRPC proxy did not start"
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield channel
    channel.close()
    serve.shutdown()


def test_typed_unary_call(typed_serve):
    stub = typed_serve.unary_unary(
        "/test.TextService/Upper",
        request_serializer=lambda r: r.SerializeToString(),
        response_deserializer=TextReply.FromString,
    )
    reply = stub(TextRequest("hello"), timeout=60,
                 metadata=(("application", "textapp"),))
    assert reply.text == "HELLO"
    assert reply.length == 5


def test_typed_server_streaming(typed_serve):
    stub = typed_serve.unary_stream(
        "/test.TextService/Spell",
        request_serializer=lambda r: r.SerializeToString(),
        response_deserializer=TextReply.FromString,
    )
    out = [r.text for r in stub(TextRequest("abc"), timeout=60,
                                metadata=(("application", "textapp"),))]
    assert out == ["a", "b", "c"]


def test_typed_unknown_app_not_found(typed_serve):
    stub = typed_serve.unary_unary(
        "/test.TextService/Upper",
        request_serializer=lambda r: r.SerializeToString(),
        response_deserializer=TextReply.FromString,
    )
    with pytest.raises(grpc.RpcError) as err:
        stub(TextRequest("x"), timeout=30,
             metadata=(("application", "nope"),))
    assert err.value.code() == grpc.StatusCode.NOT_FOUND
