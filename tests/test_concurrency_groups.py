"""Concurrency-group enforcement tests.

Analog of ray: python/ray/tests/test_concurrency_group.py — per-group
admission limits, @method(concurrency_group=...) annotations, .options()
overrides, and loud rejection of undeclared groups (the option used to be
accepted and silently ignored).
"""

import time

import pytest

import ray_tpu


def test_group_limits_enforced(ray_start_regular):
    """Two groups saturate independently: "io" (cap 2) runs 2-wide while
    "compute" (cap 1) serializes, and neither blocks the other."""

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class A:
        def __init__(self):
            self.peak = {"io": 0, "compute": 0}
            self.cur = {"io": 0, "compute": 0}
            import threading

            self.lock = threading.Lock()

        def _run(self, group, t):
            with self.lock:
                self.cur[group] += 1
                self.peak[group] = max(self.peak[group], self.cur[group])
            time.sleep(t)
            with self.lock:
                self.cur[group] -= 1
            return group

        @ray_tpu.method(concurrency_group="io")
        def io_task(self, t=0.3):
            return self._run("io", t)

        @ray_tpu.method(concurrency_group="compute")
        def compute_task(self, t=0.3):
            return self._run("compute", t)

        def peaks(self):
            return dict(self.peak)

    a = A.remote()
    ray_tpu.get(a.peaks.remote(), timeout=60)  # wait for the actor to be up
    t0 = time.time()
    refs = [a.io_task.remote() for _ in range(4)]
    refs += [a.compute_task.remote() for _ in range(2)]
    out = ray_tpu.get(refs, timeout=60)
    elapsed = time.time() - t0
    assert out == ["io"] * 4 + ["compute"] * 2
    peaks = ray_tpu.get(a.peaks.remote(), timeout=30)
    assert peaks["io"] == 2  # saturated its cap, not beyond
    assert peaks["compute"] == 1  # serialized
    # 4 io tasks 2-wide = ~0.6s; 2 compute serial = ~0.6s, overlapping.
    assert elapsed < 2.5


def test_options_override_and_default_group(ray_start_regular):
    @ray_tpu.remote(concurrency_groups={"g": 1}, max_concurrency=4)
    class B:
        def tagged(self):
            import threading

            return threading.current_thread().name

        def plain(self, t=0.2):
            time.sleep(t)
            return "ok"

    b = B.remote()
    # Route an un-annotated method into group "g" via .options().
    assert ray_tpu.get(
        b.tagged.options(concurrency_group="g").remote(), timeout=60
    )
    # Default-group methods run concurrently under max_concurrency.
    ray_tpu.get(b.plain.remote(0.0), timeout=60)
    t0 = time.time()
    assert ray_tpu.get([b.plain.remote() for _ in range(4)], timeout=60) == [
        "ok"
    ] * 4
    assert time.time() - t0 < 0.75


def test_undeclared_group_rejected(ray_start_regular):
    @ray_tpu.remote(concurrency_groups={"io": 2})
    class C:
        def f(self):
            return 1

    c = C.remote()
    with pytest.raises(ValueError, match="not declared"):
        c.f.options(concurrency_group="nope").remote()

    with pytest.raises(ValueError, match="declares concurrency_group"):

        @ray_tpu.remote(concurrency_groups={"io": 2})
        class D:
            @ray_tpu.method(concurrency_group="typo")
            def f(self):
                return 1

        D.remote()

    with pytest.raises(ValueError, match="positive int"):

        @ray_tpu.remote(concurrency_groups={"io": 0})
        class E:
            def f(self):
                return 1

        E.remote()
