"""Runtime env materialization: working_dir + py_modules + env_vars.

ray parity: python/ray/tests/test_runtime_env_working_dir.py — a task's
runtime_env ships local code/data to the worker that runs it.
"""

import os

import pytest

import ray_tpu

# cluster-state-mutating module: always gets (and leaves behind) a
# fresh cluster instead of joining the shared fast-lane one
RAY_REUSE_CLUSTER = False


def test_working_dir_ships_files(ray_start_regular, tmp_path):
    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "data.txt").write_text("shipped-payload")
    (wd / "helper.py").write_text("VALUE = 41\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def read_back():
        # cwd is the materialized working_dir...
        with open("data.txt") as f:
            content = f.read()
        # ...and it is importable
        import helper

        return content, helper.VALUE + 1

    content, value = ray_tpu.get(read_back.remote(), timeout=120)
    assert content == "shipped-payload"
    assert value == 42


def test_py_modules_importable(ray_start_regular, tmp_path):
    mod = tmp_path / "shiny_mod"
    mod.mkdir()
    (mod / "__init__.py").write_text("def answer():\n    return 7\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
    def use_module():
        import shiny_mod

        return shiny_mod.answer()

    assert ray_tpu.get(use_module.remote(), timeout=120) == 7


def test_env_vars_and_pool_isolation(ray_start_regular, tmp_path):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "on"}})
    def with_flag():
        return os.environ.get("MY_FLAG")

    @ray_tpu.remote
    def without_flag():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(with_flag.remote(), timeout=120) == "on"
    # a different env hash means a different worker pool: no leakage
    assert ray_tpu.get(without_flag.remote(), timeout=120) is None


def test_unsupported_plugins_fail_fast(ray_start_regular, monkeypatch):
    # pip is supported WITH a wheelhouse; without one it must still fail
    # at submission time with the documented guidance
    monkeypatch.delenv("RAY_TPU_WHEELHOUSE", raising=False)

    @ray_tpu.remote(runtime_env={"pip": ["requests"]})
    def nope():
        return 1

    with pytest.raises(ValueError, match="wheelhouse"):
        nope.remote()

    @ray_tpu.remote(runtime_env={"conda": ["whatever"]})
    def nope2():
        return 1

    with pytest.raises(ValueError, match="env name or an env spec"):
        nope2.remote()


def test_actor_runtime_env(ray_start_regular, tmp_path):
    wd = tmp_path / "awd"
    wd.mkdir()
    (wd / "marker.txt").write_text("actor-env")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    class Reader:
        def read(self):
            with open("marker.txt") as f:
                return f.read()

    r = Reader.remote()
    assert ray_tpu.get(r.read.remote(), timeout=120) == "actor-env"
