"""PB2: PBT with GP-bandit exploration (ray parity:
tune/schedulers/pb2.py)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers import PB2


@pytest.fixture(scope="module")
def ray_start_regular():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_pb2_requires_bounds():
    with pytest.raises(ValueError):
        PB2(metric="score", mode="max")


def test_pb2_explored_configs_respect_bounds(ray_start_regular):
    def objective(config):
        ck = tune.get_checkpoint()
        base = ck.to_dict()["score"] if ck else 0.0
        for _ in range(12):
            base += config["rate"]
            tune.report(
                {"score": base},
                checkpoint=ray_tpu.air.Checkpoint.from_dict({"score": base}),
            )

    pb2 = PB2(
        metric="score",
        mode="max",
        perturbation_interval=3,
        hyperparam_bounds={"rate": [0.1, 2.0]},
        seed=0,
    )
    grid = tune.Tuner(
        objective,
        param_space={"rate": tune.grid_search([0.1, 0.2, 1.0, 1.8])},
        tune_config=tune.TuneConfig(
            scheduler=pb2, max_concurrent_trials=4, metric="score",
            mode="max",
        ),
        run_config=ray_tpu.air.RunConfig(stop={"training_iteration": 12}),
    ).fit()
    # NOTE: no num_perturbations assertion here — on a starved 1-core box
    # the controller can serialize trials so bottom/top quantiles never
    # coexist; test_pb2_exploit_path_deterministic covers the mechanism.
    for res in grid:
        rate = res.config.get("rate")
        assert rate is None or 0.1 <= rate <= 2.0, rate
    assert grid.get_best_result().metrics["score"] > 1.0


def test_pb2_exploit_path_deterministic():
    """Drive the scheduler interface directly: two trials with a clear
    score gap at an interval boundary must trigger a GP-explored exploit
    within bounds."""

    class _Trial:
        def __init__(self, tid, rate):
            self.trial_id = tid
            self.config = {"rate": rate}

    class _Controller:
        def __init__(self, trials):
            self._trials = {t.trial_id: t for t in trials}
            self.exploits = []

        def get_trial(self, tid):
            return self._trials[tid]

        def exploit_trial(self, trial, donor, new_config):
            self.exploits.append((trial.trial_id, donor.trial_id,
                                  new_config))

    # long interval: deltas accumulate for several reports before the
    # first exploit, so the asserted perturbation exercises the GP path
    # (the fit requires >= 4 observations), not the random fallback
    pb2 = PB2(metric="score", mode="max", perturbation_interval=6,
              hyperparam_bounds={"rate": [0.1, 2.0]}, seed=0)
    lo, hi = _Trial("lo", 0.1), _Trial("hi", 1.9)
    ctl = _Controller([lo, hi])
    pb2.on_trial_add(ctl, lo)
    pb2.on_trial_add(ctl, hi)
    for t in range(1, 8):
        pb2.on_trial_result(ctl, hi, {"score": 2.0 * t,
                                      "training_iteration": t})
        pb2.on_trial_result(ctl, lo, {"score": 0.1 * t,
                                      "training_iteration": t})
    assert len(pb2._y) >= 4  # GP path active at the asserted exploit
    assert pb2.num_perturbations > 0
    assert ctl.exploits, "bottom-quantile trial never exploited"
    tid, donor, new_config = ctl.exploits[0]
    assert (tid, donor) == ("lo", "hi")
    assert 0.1 <= new_config["rate"] <= 2.0


def test_pb2_gp_picks_high_ucb_region():
    """With clear observations (high rate -> high improvement), the GP
    explore step must select from the high region, not uniformly."""
    pb2 = PB2(metric="score", mode="max",
              hyperparam_bounds={"rate": [0.0, 1.0]}, seed=1)
    # synthetic history: improvement equals the rate that produced it
    for t in range(20):
        r = (t % 10) / 10.0
        pb2._X.append([float(t), r])
        pb2._y.append(r)
        pb2._now_t = float(t)
    picks = [pb2._make_explored_config({"rate": 0.5})["rate"]
             for _ in range(5)]
    assert sum(p > 0.6 for p in picks) >= 4, picks
