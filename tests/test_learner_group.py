"""LearnerGroup (multi-learner DDP) tests.

Analog of ray: rllib/core/learner/tests/test_learner_group.py — N learner
actors shard the batch, gradients mean-allreduce in lockstep, replicas
stay bit-identical, and multi-learner training matches single-learner
learning on CartPole.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import IMPALAConfig, PPOConfig


@pytest.mark.slow
def test_replicas_stay_in_sync(ray_start_regular):
    """After updates, every learner replica holds identical params (they
    all applied the same averaged gradients from the same init)."""
    algo = (
        PPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=128)
        .learners(num_learners=2)
        .training(lr=5e-3, num_epochs=2, minibatch_size=64)
        .debugging(seed=0)
        .build()
    )
    algo.train()
    algo.train()
    from ray_tpu.rllib.learner_group import LearnerGroup

    assert isinstance(algo.learner, LearnerGroup)
    w0, w1 = ray_tpu.get(
        [w.get_weights.remote() for w in algo.learner.workers], timeout=60
    )
    import jax

    leaves0 = jax.tree.leaves(w0)
    leaves1 = jax.tree.leaves(w1)
    assert len(leaves0) == len(leaves1) and leaves0
    for a, b in zip(leaves0, leaves1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    algo.stop()


@pytest.mark.slow
def test_ppo_two_learners_matches_single(ray_start_regular):
    """CartPole learning with 2 DDP learners reaches the single-learner
    bar (the VERDICT's acceptance: multi-learner matches 1-learner)."""
    algo = (
        PPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .learners(num_learners=2)
        .training(lr=5e-3, num_epochs=6, minibatch_size=128)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(25):
        result = algo.train()
        best = max(best, result.get("episode_return_mean", 0.0))
        if best >= 120:
            break
    algo.stop()
    assert best >= 100, f"2-learner PPO failed to learn CartPole (best={best})"


@pytest.mark.slow
def test_impala_two_learners_improves(ray_start_regular):
    algo = (
        IMPALAConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .learners(num_learners=2)
        .debugging(seed=0)
        .build()
    )
    first, best = None, 0.0
    for _ in range(30):
        result = algo.train()
        r = result.get("episode_return_mean")
        if r is not None:
            first = first if first is not None else r
            best = max(best, r)
    algo.stop()
    assert best > first + 10, (first, best)


@pytest.mark.slow
def test_checkpoint_roundtrip_with_group(ray_start_regular):
    """save/load must round-trip through the group (weights + opt state
    fan out to every replica)."""
    algo = (
        PPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=1, rollout_fragment_length=128)
        .learners(num_learners=2)
        .training(num_epochs=2, minibatch_size=64)
        .debugging(seed=0)
        .build()
    )
    algo.train()
    ckpt = algo.save_checkpoint()
    w_before = algo.learner.get_weights()
    algo.train()  # drift past the checkpoint
    algo.load_checkpoint(ckpt)
    w_after = algo.learner.get_weights()
    import jax

    for a, b in zip(jax.tree.leaves(w_before), jax.tree.leaves(w_after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    algo.stop()
