"""Custom runtime-env plugin used by tests/test_runtime_env_plugins.py."""

import os

from ray_tpu._private.runtime_env import RuntimeEnvPlugin


class MarkerPlugin(RuntimeEnvPlugin):
    """Materializes runtime_env["marker"] as an env var in the worker."""

    name = "marker"
    priority = 40

    def validate(self, env):
        m = env.get("marker")
        if m is not None and not isinstance(m, str):
            raise ValueError("marker must be a string")

    def materialize(self, core_worker, env):
        if env.get("marker"):
            os.environ["RTPU_TEST_MARKER"] = env["marker"]
