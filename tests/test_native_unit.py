"""Build + run the native C++ unit tests (src/native_test.cpp) from
pytest so CI exercises the C ABI directly (analog of the reference's
per-component gtest suites)."""

import os
import subprocess

SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "src")


def test_native_cpp_unit_suite():
    build = subprocess.run(
        ["make", "-C", SRC, "native_test"], capture_output=True, text=True,
        timeout=300,
    )
    assert build.returncode == 0, build.stdout + build.stderr
    run = subprocess.run(
        [os.path.join(SRC, "native_test")], capture_output=True, text=True,
        timeout=120,
    )
    assert run.returncode == 0, run.stdout + run.stderr
    assert "checks passed" in run.stdout
