"""Data execution stats + memory-budget backpressure tests.

Analog of ray: python/ray/data/tests/test_stats.py (Dataset.stats()
per-operator summary) and the streaming_executor_state backpressure tests
(per-operator byte budgets limit in-flight tasks, not just a task-count
window).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data import DataContext


@pytest.fixture(scope="module")
def data_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_stats_summary(data_cluster):
    ds = rd.range(200, parallelism=8).map(lambda r: r * 2)
    ds = ds.materialize()
    s = ds.stats()
    assert "Execution stats:" in s
    assert "Read" in s and "Map" in s
    assert "8 tasks" in s
    assert "wall" in s and "Total:" in s


def test_context_budget_limits_inflight(data_cluster):
    """With a budget smaller than two estimated blocks, admission stays at
    one task in flight even though the window allows 8."""
    ctx = DataContext.get_current()
    old_budget, old_seed = ctx.op_memory_budget, ctx.target_max_block_size
    try:
        ctx.op_memory_budget = 1  # byte — nothing fits beyond 1 task
        ds = rd.range(64, parallelism=8).map(lambda r: r).materialize()
        assert ds.count() == 64  # still completes (admit-at-least-one)
        stats = ds._exec_stats
        for op in stats.ops:
            assert op.peak_inflight_tasks == 1, (
                f"{op.name} exceeded the byte budget: "
                f"peak={op.peak_inflight_tasks}"
            )
        assert any(op.backpressure_s >= 0 for op in stats.ops)
    finally:
        ctx.op_memory_budget = old_budget
        ctx.target_max_block_size = old_seed


def test_default_budget_allows_parallelism(data_cluster):
    ds = rd.range(64, parallelism=8).materialize()
    stats = ds._exec_stats
    assert max(op.peak_inflight_tasks for op in stats.ops) > 1


def test_stats_disabled(data_cluster):
    ctx = DataContext.get_current()
    ctx.enable_stats = False
    try:
        ds = rd.range(10).materialize()
        assert "Execution stats:" not in ds.stats()
    finally:
        ctx.enable_stats = True
