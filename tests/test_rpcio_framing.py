"""v2 wire-frame codec: out-of-band buffer table round-trips, size
enforcement (both directions), truncation rejection, v1<->v2 preamble
negotiation, and the zero-copy send guarantee (payload buffers reach the
transport by reference, never through the pickle stream).

Pure rpcio/serialization unit tests — no cluster.
"""

import asyncio
import pickle

import numpy as np
import pytest

from ray_tpu._private import rpcio, serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.rpcio import (
    KIND_NOTIFY,
    KIND_REQ,
    Connection,
    Finalized,
    RpcError,
    RpcServer,
    _decode_v2,
    connect,
)


class FakeWriter:
    """Captures every part handed to the transport, by reference."""

    def __init__(self):
        self.writes = []
        self.closed = False

    def write(self, data):
        self.writes.append(data)

    async def drain(self):
        pass

    def close(self):
        self.closed = True


def _conn(version=2):
    return Connection(None, FakeWriter(), name="test", version=version)


def _roundtrip(payload, version=2):
    """Encode one frame, then decode it the way the recv loop would."""
    conn = _conn(version)
    parts = conn._encode_frame(7, KIND_REQ, "m", payload)
    wire = b"".join(bytes(p) for p in parts)
    total = int.from_bytes(wire[:4], "little")
    body = wire[4: 4 + total]
    assert len(body) == total, "frame length header must cover the body"
    if version >= 3:
        return rpcio._decode_v3(body)
    if version == 2:
        return _decode_v2(body)
    return pickle.loads(body)


# ---------------------------------------------------------------- codec --


def test_roundtrip_no_buffers():
    msg_id, kind, method, payload = _roundtrip({"a": 1, "b": "x"})
    assert (msg_id, kind, method) == (7, KIND_REQ, "m")
    assert payload == {"a": 1, "b": "x"}


@pytest.mark.parametrize("nbufs", [1, 2, 7, 32])
def test_roundtrip_buffer_counts(nbufs):
    arrs = [np.arange(i + 1, dtype=np.int64).repeat(200) for i in range(nbufs)]
    _, _, _, payload = _roundtrip({"arrs": arrs})
    assert len(payload["arrs"]) == nbufs
    for got, want in zip(payload["arrs"], arrs):
        assert np.array_equal(got, want)


@pytest.mark.parametrize("size", [0, 1, 511, 512, 513, 1 << 20])
def test_roundtrip_buffer_sizes(size):
    arr = np.full(size, 7, dtype=np.uint8)
    _, _, _, payload = _roundtrip({"arr": arr, "tag": "t"})
    assert payload["tag"] == "t"
    assert np.array_equal(payload["arr"], arr)


def test_roundtrip_fuzz_mixed():
    rng = np.random.RandomState(0)
    for trial in range(25):
        n = int(rng.randint(0, 6))
        sizes = [int(rng.randint(0, 5000)) for _ in range(n)]
        value = {
            "bufs": [np.arange(s, dtype=np.uint8) for s in sizes],
            "blob": bytes(rng.bytes(int(rng.randint(0, 2000)))),
            "n": trial,
        }
        _, _, _, got = _roundtrip(value)
        assert got["n"] == trial
        assert got["blob"] == value["blob"]
        assert len(got["bufs"]) == n
        for g, w in zip(got["bufs"], value["bufs"]):
            assert np.array_equal(g, w)


@pytest.mark.parametrize("nbufs", [0, 1, 3, 32])
def test_v3_crc_roundtrip(nbufs):
    arrs = [np.arange(1000 * (i + 1), dtype=np.int32) for i in range(nbufs)]
    msg_id, kind, method, payload = _roundtrip(
        {"arrs": arrs, "tag": "t"}, version=3)
    assert (msg_id, kind, method) == (7, KIND_REQ, "m")
    assert payload["tag"] == "t"
    for got, want in zip(payload["arrs"], arrs):
        assert np.array_equal(got, want)


def test_v3_crc_detects_head_corruption():
    """Any flipped byte in the CRC-covered head (count byte, table,
    envelope) must raise the typed corruption error."""
    parts = _conn(3)._encode_frame(1, KIND_NOTIFY, "m",
                                   {"arr": np.zeros(4096, dtype=np.uint8)})
    wire = b"".join(bytes(p) for p in parts)
    body = bytearray(wire[4:])
    head_len = len(bytes(parts[0])) - 4  # head part minus the 4B length
    for off in (0, 5, head_len - 5, head_len - 1):
        mutated = bytearray(body)
        mutated[off] ^= 0x01
        with pytest.raises(rpcio.FrameCorruptError):
            rpcio._decode_v3(bytes(mutated))
    # untouched body still decodes
    _, _, _, payload = rpcio._decode_v3(bytes(body))
    assert payload["arr"].nbytes == 4096


def test_frame_exactly_at_max_message_passes():
    GLOBAL_CONFIG.update({"rpc_max_message_bytes": 1 << 20})
    try:
        conn = _conn()
        # binary-search a buffer size whose frame lands exactly on the cap
        lo, hi = 0, 1 << 20
        while lo < hi:
            mid = (lo + hi + 1) // 2
            try:
                conn._encode_frame(1, KIND_REQ, "m",
                                   {"a": np.zeros(mid, dtype=np.uint8)})
                lo = mid
            except RpcError:
                hi = mid - 1
        parts = conn._encode_frame(1, KIND_REQ, "m",
                                   {"a": np.zeros(lo, dtype=np.uint8)})
        wire = b"".join(bytes(p) for p in parts)
        assert int.from_bytes(wire[:4], "little") == (1 << 20)
        _, _, _, payload = _decode_v2(wire[4:])
        assert payload["a"].nbytes == lo
    finally:
        GLOBAL_CONFIG.reset()


# ----------------------------------------------------- size enforcement --


@pytest.mark.parametrize("version", [1, 2])
def test_send_side_oversize_raises_with_method_and_size(version):
    GLOBAL_CONFIG.update({"rpc_max_message_bytes": 10_000})
    try:
        conn = _conn(version)
        with pytest.raises(RpcError) as ei:
            conn._encode_frame(1, KIND_REQ, "push_chunks",
                               {"data": np.zeros(50_000, dtype=np.uint8)})
        msg = str(ei.value)
        assert "push_chunks" in msg and "10000" in msg
        assert not conn.writer.writes, "nothing may reach the wire"
    finally:
        GLOBAL_CONFIG.reset()


def test_request_nowait_oversize_leaves_no_pending_entry():
    async def main():
        GLOBAL_CONFIG.update({"rpc_max_message_bytes": 10_000})
        try:
            conn = _conn()
            with pytest.raises(RpcError):
                conn.request_nowait(
                    "m", {"data": np.zeros(50_000, dtype=np.uint8)})
            assert not conn._pending
            assert not conn.writer.writes
        finally:
            GLOBAL_CONFIG.reset()

    asyncio.run(main())


# ---------------------------------------------------------- truncation --


def _v2_body(payload):
    parts = _conn()._encode_frame(1, KIND_NOTIFY, "m", payload)
    return b"".join(bytes(p) for p in parts)[4:]


def test_truncated_buffer_table_rejected():
    body = _v2_body({"arr": np.zeros(4096, dtype=np.uint8)})
    # claim 200 table entries in a 5-byte body
    with pytest.raises(RpcError):
        _decode_v2(bytes([200]) + body[1:5])


def test_buffers_exceeding_frame_rejected():
    body = bytearray(_v2_body({"arr": np.zeros(4096, dtype=np.uint8)}))
    assert body[0] == 1
    # inflate the recorded buffer length past the frame end
    body[1:5] = (1 << 30).to_bytes(4, "little")
    with pytest.raises(RpcError):
        _decode_v2(bytes(body))


def test_empty_body_rejected():
    with pytest.raises(RpcError):
        _decode_v2(b"")


# ---------------------------------------------------------- negotiation --


class EchoHandler:
    def rpc_echo(self, conn, p):
        return p

    def rpc_finalized(self, conn, p):
        self.released = False

        def _rel():
            self.released = True

        return Finalized({"ok": True}, _rel)


def test_v3_negotiation_and_echo():
    async def main():
        handler = EchoHandler()
        srv = RpcServer(handler)
        port = await srv.start()
        conn = await connect("127.0.0.1", port, name="c", retries=3)
        try:
            assert conn.version == 3  # default: v2 framing + CRC trailer
            (sconn,) = srv.connections
            assert sconn.version == 3
            arr = np.arange(65536, dtype=np.uint8)
            reply = await conn.request("echo", {"arr": arr})
            assert np.array_equal(reply["arr"], arr)
            reply = await conn.request("finalized", {})
            assert reply == {"ok": True}
            # release ran after the response frame was handed off
            for _ in range(10):
                if getattr(handler, "released", False):
                    break
                await asyncio.sleep(0.01)
            assert handler.released
        finally:
            await conn.close()
            await srv.stop()

    asyncio.run(main())


def test_v1_client_against_v2_server():
    async def main():
        srv = RpcServer(EchoHandler())
        port = await srv.start()
        conn = await connect("127.0.0.1", port, name="c", retries=3,
                             version=1)
        try:
            assert conn.version == 1
            for _ in range(100):  # no ack on v1: wait for server accept
                if srv.connections:
                    break
                await asyncio.sleep(0.01)
            (sconn,) = srv.connections
            assert sconn.version == 1
            arr = np.arange(4096, dtype=np.uint8)
            reply = await conn.request("echo", {"arr": arr})
            assert np.array_equal(reply["arr"], arr)
        finally:
            await conn.close()
            await srv.stop()

    asyncio.run(main())


def test_frame_version_flag_pins_v2():
    async def main():
        GLOBAL_CONFIG.update({"rpc_frame_version": 2})
        try:
            srv = RpcServer(EchoHandler())
            port = await srv.start()
            conn = await connect("127.0.0.1", port, name="c", retries=3)
            assert conn.version == 2
            arr = np.arange(65536, dtype=np.uint8)
            reply = await conn.request("echo", {"arr": arr})
            assert np.array_equal(reply["arr"], arr)
            await conn.close()
            await srv.stop()
        finally:
            GLOBAL_CONFIG.reset()

    asyncio.run(main())


def test_frame_version_flag_pins_v1():
    async def main():
        GLOBAL_CONFIG.update({"rpc_frame_version": 1})
        try:
            srv = RpcServer(EchoHandler())
            port = await srv.start()
            conn = await connect("127.0.0.1", port, name="c", retries=3)
            assert conn.version == 1
            reply = await conn.request("echo", {"x": 1})
            assert reply == {"x": 1}
            await conn.close()
            await srv.stop()
        finally:
            GLOBAL_CONFIG.reset()

    asyncio.run(main())


def test_fallback_to_v1_against_legacy_server():
    """A pre-v2 server closes an RTPU2 preamble at the digest compare; the
    client must redial with the v1 preamble and interoperate."""

    async def main():
        handler = EchoHandler()
        legacy_expected = rpcio._auth_preamble(rpcio.cluster_token(), 1)

        async def legacy_accept(reader, writer):
            preamble = await reader.readexactly(rpcio._AUTH_LEN)
            if preamble != legacy_expected:  # unknown magic: close, no ack
                writer.close()
                return
            Connection(reader, writer, handler, name="legacy",
                       version=1).start()

        server = await asyncio.start_server(legacy_accept, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        conn = await connect("127.0.0.1", port, name="c", retries=5,
                             retry_delay=0.05)
        try:
            assert conn.version == 1
            reply = await conn.request("echo", {"x": 42})
            assert reply == {"x": 42}
        finally:
            await conn.close()
            server.close()
            await server.wait_closed()

    asyncio.run(main())


# ------------------------------------------------------------ zero-copy --


def test_1mb_numpy_send_is_zero_copy():
    """The acceptance check: a 1MB array payload crosses _send with a tiny
    pickle envelope and the array's memory handed to the transport BY
    REFERENCE (a memoryview aliasing the array), never copied."""

    async def main():
        conn = _conn()
        arr = np.arange(1 << 20, dtype=np.uint8)
        await conn._send(1, KIND_NOTIFY, "m", {"arr": arr})
        writes = conn.writer.writes
        assert writes, "flush must have run"
        head = bytes(writes[0])
        total = int.from_bytes(head[:4], "little")
        nbufs = head[4]
        assert nbufs == 1
        buf_len = int.from_bytes(head[5:9], "little")
        assert buf_len == arr.nbytes
        # envelope = head minus 4B total, 1B nbufs, 4B table entry
        envelope_len = len(head) - 9
        assert envelope_len < 1024, (
            f"envelope carries payload bytes: {envelope_len}"
        )
        assert total == 1 + 4 + envelope_len + arr.nbytes
        views = [w for w in writes[1:] if isinstance(w, memoryview)]
        assert views, "buffer must be written as its own part"
        assert any(
            v.nbytes == arr.nbytes
            and np.shares_memory(np.frombuffer(v, dtype=np.uint8), arr)
            for v in views
        ), "buffer must alias the array's memory (zero-copy)"

    asyncio.run(main())


def test_serialized_value_slot_is_zero_copy_on_send():
    """The worker inline-arg shape: ('v', metadata, sv.to_wire()) must ship
    the value's array buffer by reference through a v2 connection."""

    async def main():
        arr = np.arange(1 << 20, dtype=np.uint8)
        sv = serialization.serialize({"weights": arr})
        slot = ("v", sv.metadata, sv.to_wire())
        conn = _conn()
        await conn._send(2, KIND_NOTIFY, "execute", {"args": [slot]})
        writes = conn.writer.writes
        views = [w for w in writes if isinstance(w, memoryview)]
        assert any(
            v.nbytes == arr.nbytes
            and np.shares_memory(np.frombuffer(v, dtype=np.uint8), arr)
            for v in views
        ), "inline arg buffer must alias the caller's array"
        head = bytes(writes[0])
        envelope_len = len(head) - 5 - 4 * head[4]
        assert envelope_len < 4096

    asyncio.run(main())


def test_bufferlist_roundtrip_v2_and_v1():
    arr = np.arange(100_000, dtype=np.float32)
    sv = serialization.serialize({"x": arr, "y": "small"})
    for version in (2, 1):
        _, _, _, payload = _roundtrip(
            {"slot": ("v", sv.metadata, sv.to_wire())}, version=version)
        kind, meta, data = payload["slot"]
        assert kind == "v"
        assert isinstance(data, serialization.BufferList)
        value = serialization.deserialize(meta, data)
        assert value["y"] == "small"
        assert np.array_equal(value["x"], arr)


def test_bufferlist_concat_matches_to_bytes():
    arr = np.arange(5000, dtype=np.uint8)
    sv = serialization.serialize([arr, b"tail"])
    assert sv.to_wire().concat() == sv.to_bytes()
    # raw-bytes fast path: to_bytes returns the buffer itself, no copy
    raw = b"z" * 4096
    sv2 = serialization.serialize(raw)
    assert sv2.to_bytes() is raw
