"""Elastic fault-tolerant training tests.

Covers the gang-supervision + recovery stack end to end:

- ProgressWatchdog verdicts (arming on first progress, health-snapshot
  refresh, disarm, disabled mode) — pure units.
- Epoch-keyed collective rendezvous isolation and the abort marker
  (``CollectiveWorldChangedError``) — units on a monkeypatched KV.
- Drain semantics at the session layer (SIGTERM → checkpoint at the next
  step boundary → clean exit) and budget accounting in the executor's
  recovery loop (drain is free, real failures spend ``max_failures``,
  exhaustion is terminal) — units.
- Live-gang integration: a SIGKILLed rank recovers from the latest
  checkpoint within budget; a drain requeues with ``max_failures=0``;
  an out-of-budget failure surfaces ``FailureBudgetExhaustedError``.
- Chaos e2e (slow): kill -9 a rank mid-collective on a 2-node cluster;
  the gang re-forms at the next generation, resumes from the last
  checkpoint, and the loss sequence stays continuous.
"""

import os
import time
import types

import pytest

import ray_tpu

pytestmark = pytest.mark.train_ft

# Workers get SIGKILLed / drained here; never bequeath this cluster.
RAY_REUSE_CLUSTER = False

_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
}


# ---------------------------------------------------------------------------
# ProgressWatchdog units
# ---------------------------------------------------------------------------


def test_watchdog_arms_only_after_first_progress():
    from ray_tpu.train.backend_executor import ProgressWatchdog

    wd = ProgressWatchdog(2, timeout_s=5.0)
    # no progress ever observed: never wedged (long jit/compile is legal)
    assert wd.wedged(now=1e9) == []
    wd.touch(0, now=100.0)
    assert wd.wedged(now=104.0) == []      # inside the window
    assert wd.wedged(now=106.0) == [0]     # stale past timeout
    wd.touch(0, now=107.0)                  # progress clears the verdict
    assert wd.wedged(now=110.0) == []
    # rank 1 never armed, stays invisible throughout
    wd.touch(1, now=107.0)
    assert wd.wedged(now=113.0) == [0, 1]


def test_watchdog_observe_requires_step_advance():
    from ray_tpu.train.backend_executor import ProgressWatchdog

    wd = ProgressWatchdog(1, timeout_s=5.0)
    wd.observe(0, 0, now=10.0)              # step 0 == initial: not progress
    assert wd.wedged(now=1e9) == []
    wd.observe(0, 1, now=10.0)              # first completed step: arms
    assert wd.wedged(now=16.0) == [0]
    wd.observe(0, 1, now=20.0)              # same step again: NOT a refresh
    assert wd.wedged(now=16.5) == [0]
    wd.observe(0, 2, now=20.0)              # advance: refreshed
    assert wd.wedged(now=24.0) == []
    wd.disarm(0)                            # rank finished cleanly
    assert wd.wedged(now=1e9) == []


def test_watchdog_disabled_with_zero_timeout():
    from ray_tpu.train.backend_executor import ProgressWatchdog

    wd = ProgressWatchdog(1, timeout_s=0.0)
    wd.touch(0, now=0.0)
    assert wd.wedged(now=1e9) == []


# ---------------------------------------------------------------------------
# Epoch-keyed rendezvous + abort marker (monkeypatched KV)
# ---------------------------------------------------------------------------


@pytest.fixture
def kv_store(monkeypatch):
    from ray_tpu.util.collective import collective as colmod

    store = {}
    monkeypatch.setattr(colmod, "_kv_put",
                        lambda k, v: store.__setitem__(k, v))
    monkeypatch.setattr(colmod, "_kv_get", lambda k: store.get(k))

    def _del_prefix(prefix):
        for k in [k for k in store if k.startswith(prefix)]:
            del store[k]

    monkeypatch.setattr(colmod, "_kv_del_prefix", _del_prefix)
    return store


def test_epoch_keys_isolate_generations(kv_store):
    from ray_tpu.util.collective import collective as colmod

    # a dead generation's rank-1 contribution sits in the KV
    stale = f"{colmod._keybase('gg', 0)}:1:ar:1".encode()
    colmod._kv_put(stale, b"stale-grad")
    # the re-formed generation's rendezvous for the SAME (seq, op, rank)
    # must not see it — its keys live under gg@1
    fresh = f"{colmod._keybase('gg', 1)}:1:ar:1".encode()
    with pytest.raises(TimeoutError):
        colmod._kv_wait(fresh, timeout=0.2)
    # while the dead generation's key is still addressable at its epoch
    assert colmod._kv_wait(stale, timeout=0.2) == b"stale-grad"


def test_abort_marker_unwedges_kv_wait(kv_store):
    from ray_tpu.util.collective import collective as colmod
    from ray_tpu.util.collective import CollectiveWorldChangedError

    abort_key = colmod._keybase("gg", 0).encode() + colmod._ABORT_SUFFIX
    colmod.abort_group("gg", epoch=0)
    assert kv_store.get(abort_key) is not None
    t0 = time.monotonic()
    with pytest.raises(CollectiveWorldChangedError):
        colmod._kv_wait(f"{colmod._keybase('gg', 0)}:9:ar:1".encode(),
                        timeout=30.0, abort_key=abort_key)
    # fails over within ~a poll interval, nowhere near the 30s timeout
    assert time.monotonic() - t0 < 5.0
    # without an abort_key the same wait ignores the marker entirely
    with pytest.raises(TimeoutError):
        colmod._kv_wait(f"{colmod._keybase('gg', 0)}:9:ar:1".encode(),
                        timeout=0.2)


def test_group_keybase_and_trace_name(kv_store):
    from ray_tpu.util.collective import collective as colmod

    colmod.init_collective_group(2, 0, backend="store", group_name="gg",
                                 epoch=0)
    g0 = colmod._groups["gg"]
    assert g0.keybase == "gg@0"
    assert g0.trace_name == "gg"            # epoch 0 keeps the bare name
    assert f"{g0.keybase}:member:0".encode() in kv_store
    colmod.init_collective_group(2, 0, backend="store", group_name="gg",
                                 epoch=3)
    g3 = colmod._groups["gg"]
    assert g3.keybase == "gg@3"
    assert g3.trace_name == "gg@3"          # re-formed gang is visible
    # destroy wipes every epoch's keys under the name
    colmod.destroy_collective_group("gg")
    assert not [k for k in kv_store if k.startswith(b"gg@")]


# ---------------------------------------------------------------------------
# Session drain semantics (unit)
# ---------------------------------------------------------------------------


def test_session_drain_checkpoints_then_exits():
    from ray_tpu.air import Checkpoint
    from ray_tpu.train import session as sess

    s = sess.init_session(sess.TrainContext(rank=0, world_size=1), None)
    try:
        assert sess.health()["active"] is True
        assert sess.request_drain() is True
        assert sess.health()["draining"] is True
        with pytest.raises(SystemExit):
            sess.report({"loss": 1.0},
                        checkpoint=Checkpoint.from_dict({"step": 0}))
        payload = s.queue.get_nowait()
        # the drain report carries the checkpoint the executor restores from
        assert payload["type"] == "report" and payload["drain"] is True
        assert payload["checkpoint_data"] == {"step": 0}
    finally:
        sess.shutdown_session()
    assert sess.request_drain() is False     # no session: SIGTERM falls back
    assert sess.health() == {"active": False}


# ---------------------------------------------------------------------------
# Recovery-loop budget accounting (unit: fake attempts, real run())
# ---------------------------------------------------------------------------


def _fake_executor(tmp_path, max_failures, outcomes):
    from ray_tpu.air.config import FailureConfig, RunConfig
    from ray_tpu.train.backend_executor import BackendExecutor

    ex = object.__new__(BackendExecutor)
    ex.run_config = RunConfig(
        failure_config=FailureConfig(max_failures=max_failures))
    ex.trial_dir = str(tmp_path)
    ex._last_metrics = None
    ex._ckpts = types.SimpleNamespace(latest=lambda: None)
    ex.worker_group = types.SimpleNamespace(generation=0)
    it = iter(outcomes)
    ex._run_attempt = lambda *a, **k: next(it)
    ex.recovered = []
    ex._recover = ex.recovered.append
    return ex


def _failed(cause):
    return {"status": "failed", "cause": cause, "error": RuntimeError(cause),
            "detected": time.time()}


def test_drain_recovery_is_budget_free(tmp_path):
    ex = _fake_executor(tmp_path, max_failures=0, outcomes=[
        {"status": "failed", "cause": "drain", "error": None,
         "detected": time.time()},
        {"status": "done"},
    ])
    result = ex.run(lambda: None)
    assert result.error is None
    assert ex.recovered == [0]               # requeued despite zero budget


def test_failure_with_no_budget_is_terminal(tmp_path):
    from ray_tpu.train.backend_executor import FailureBudgetExhaustedError

    ex = _fake_executor(tmp_path, max_failures=0,
                        outcomes=[_failed("actor_died")])
    result = ex.run(lambda: None)
    assert isinstance(result.error, FailureBudgetExhaustedError)
    assert ex.recovered == []                # no re-place attempt


def test_budget_decrements_then_exhausts(tmp_path):
    from ray_tpu.train.backend_executor import FailureBudgetExhaustedError

    ex = _fake_executor(tmp_path, max_failures=1,
                        outcomes=[_failed("wedged"), _failed("actor_died")])
    result = ex.run(lambda: None)
    assert isinstance(result.error, FailureBudgetExhaustedError)
    assert ex.recovered == [0]               # one funded recovery, then stop


def test_negative_budget_means_unlimited(tmp_path):
    ex = _fake_executor(tmp_path, max_failures=-1, outcomes=[
        _failed("actor_died"), _failed("wedged"), _failed("unresponsive"),
        {"status": "done"},
    ])
    result = ex.run(lambda: None)
    assert result.error is None
    assert len(ex.recovered) == 3


# ---------------------------------------------------------------------------
# faultsim "kill" kind (parse + plan only — never through rpcio here)
# ---------------------------------------------------------------------------


def test_faultsim_kill_rule_parses_and_fires():
    from ray_tpu._private import faultsim

    rules = faultsim.parse_spec("execute_task:kill:1.0:7")
    assert len(rules) == 1 and rules[0].kind == "kill"
    plan = faultsim.FaultPlan(rules)
    kind, rule = plan.on_send("execute_task", None)
    assert kind == "kill" and rule.seed == 7
    # keepalives stay exempt: the failure detector must outlive the chaos
    assert plan.on_send("__ping", None) is None
    assert plan.on_send("kv_put", None) is None


# ---------------------------------------------------------------------------
# Restart spans in the train timeline
# ---------------------------------------------------------------------------


def test_restart_records_render_in_timeline():
    from ray_tpu._private import steptrace

    rec = {"kind": "restart", "idx": 0, "cause": "actor_died",
           "generation": 1, "start": 10.0, "end": 12.5}
    merged = steptrace.merge_records([rec])
    assert merged["restarts"] == [rec]
    trace = steptrace.chrome_trace(merged)
    spans = [e for e in trace if e.get("cat") == "restart"]
    assert len(spans) == 1
    assert "restart[actor_died]" in spans[0]["name"]
    assert spans[0]["pid"] == -1             # the driver (recovery) row
    assert spans[0]["args"]["recovery_s"] == pytest.approx(2.5)
    assert any(e.get("ph") == "M" and e.get("pid") == -1 for e in trace)


# ---------------------------------------------------------------------------
# Live-gang integration
# ---------------------------------------------------------------------------


def _ft_counters():
    from ray_tpu.train.backend_executor import _ft_metrics

    failures, restarts, hist = _ft_metrics()
    return failures, restarts, hist


def _gang_failures(failures):
    return sum(failures.labels(cause=c).value()
               for c in ("actor_died", "unresponsive", "wedged"))


def _kill_recovery_loop(config):
    import os
    import signal

    from ray_tpu import train
    from ray_tpu.air import Checkpoint

    ctx = train.get_context()
    start = 0
    ck = train.get_checkpoint()
    if ck is not None:
        start = ck.to_dict()["step"] + 1
    for step in range(start, 6):
        if (ctx.get_world_rank() == 1 and step == 2
                and not os.path.exists(config["marker"])):
            open(config["marker"], "w").close()   # exactly one kill per run
            os.kill(os.getpid(), signal.SIGKILL)
        train.report({"step": step, "loss": 1.0 / (step + 1)},
                     checkpoint=Checkpoint.from_dict({"step": step}))


def test_gang_recovers_from_rank_sigkill(ray_start_regular, tmp_path):
    from ray_tpu import train

    failures, restarts, hist = _ft_counters()
    f0, r0 = _gang_failures(failures), restarts.default.value()
    trainer = train.DataParallelTrainer(
        _kill_recovery_loop,
        train_loop_config={"marker": str(tmp_path / "killed")},
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(
            name="t_ft_kill", storage_path=str(tmp_path),
            failure_config=train.FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 5       # resumed and finished
    assert result.checkpoint is not None
    assert restarts.default.value() == r0 + 1
    assert _gang_failures(failures) == f0 + 1


def _drain_loop(config):
    from ray_tpu import train
    from ray_tpu.air import Checkpoint
    from ray_tpu.train import session as sess_mod

    start = 0
    ck = train.get_checkpoint()
    if ck is not None:
        start = ck.to_dict()["step"] + 1
    for step in range(start, 4):
        if step == 1 and ck is None:
            # what the worker's SIGTERM handler does on spot preemption
            sess_mod.request_drain()
        train.report({"step": step},
                     checkpoint=Checkpoint.from_dict({"step": step}))


def test_drain_requeues_without_spending_budget(ray_start_regular, tmp_path):
    from ray_tpu import train

    failures, restarts, hist = _ft_counters()
    d0, r0 = failures.labels(cause="drain").value(), restarts.default.value()
    trainer = train.DataParallelTrainer(
        _drain_loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name="t_ft_drain", storage_path=str(tmp_path),
            # zero budget: completion proves the drain didn't consume any
            failure_config=train.FailureConfig(max_failures=0)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3       # resumed past the drained step
    assert failures.labels(cause="drain").value() == d0 + 1
    assert restarts.default.value() == r0 + 1


def _always_dies_loop(config):
    import os
    import signal

    from ray_tpu import train

    train.report({"step": 0})
    os.kill(os.getpid(), signal.SIGKILL)


def test_exhausted_budget_is_terminal(ray_start_regular, tmp_path):
    from ray_tpu import train
    from ray_tpu.train.backend_executor import FailureBudgetExhaustedError

    trainer = train.DataParallelTrainer(
        _always_dies_loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name="t_ft_exhaust", storage_path=str(tmp_path),
            failure_config=train.FailureConfig(max_failures=0)),
    )
    result = trainer.fit()
    assert isinstance(result.error, FailureBudgetExhaustedError)


# ---------------------------------------------------------------------------
# Chaos e2e: kill -9 a rank mid-collective on a 2-node cluster
# ---------------------------------------------------------------------------


def _chaos_loop(config):
    import os
    import signal

    import numpy as np

    from ray_tpu import train
    from ray_tpu.air import Checkpoint
    from ray_tpu.util import collective as col

    ctx = train.get_context()
    rank = ctx.get_world_rank()
    start, losses = 0, []
    ck = train.get_checkpoint()
    if ck is not None:
        d = ck.to_dict()
        start, losses = d["step"] + 1, list(d["losses"])
    for step in range(start, 6):
        if (rank == 1 and step == 3
                and not os.path.exists(config["marker"])):
            # mid-step rank death: the survivor is (or is about to be)
            # blocked in this step's allreduce rendezvous
            open(config["marker"], "w").close()
            os.kill(os.getpid(), signal.SIGKILL)
        vals = col.allreduce(np.array([float(rank + step)], np.float64),
                             group_name="train_dp")
        loss = float(vals[0]) / ctx.get_world_size()
        losses.append(loss)
        train.report(
            {"step": step, "loss": loss},
            checkpoint=Checkpoint.from_dict({"step": step, "losses": losses}),
        )


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_kill_rank_mid_step_two_nodes(ray_start_cluster, tmp_path):
    from ray_tpu import train
    from ray_tpu._private import steptrace

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)

    failures, restarts, hist = _ft_counters()
    r0 = restarts.default.value()
    h0 = hist.default._series()["count"]
    marker = tmp_path / "killed"
    t0 = time.time()
    trainer = train.JaxTrainer(
        _chaos_loop,
        train_loop_config={"marker": str(marker)},
        jax_config=train.JaxConfig(distributed="off", env_vars=_CPU_ENV),
        scaling_config=train.ScalingConfig(
            num_workers=2, placement_strategy="SPREAD"),
        run_config=train.RunConfig(
            name="t_ft_chaos", storage_path=str(tmp_path),
            failure_config=train.FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 5
    # loss continuity across the restart: the deterministic allreduce
    # sequence has no gap and no duplicate (mean over ranks = step + 0.5)
    losses = result.checkpoint.to_dict()["losses"]
    assert losses == pytest.approx([s + 0.5 for s in range(6)])
    # exactly one funded recovery, with a latency sample
    assert restarts.default.value() == r0 + 1
    assert hist.default._series()["count"] == h0 + 1
    # the driver recorded the restart span; detection (span start) landed
    # within 5s of the SIGKILL instant (the marker's mtime)
    recs = [r for r in steptrace.snapshot()
            if r.get("kind") == "restart" and r["start"] >= t0]
    assert recs, "driver steptrace ring has no restart record for this run"
    kill_t = marker.stat().st_mtime
    assert recs[-1]["start"] - kill_t < 5.0
    assert recs[-1]["generation"] == 1
