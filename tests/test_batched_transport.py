"""Failure/order semantics of the round-5 batched direct transport
(execute_task_batch + streamed task_result notifies): early results must
stream out of a batch, and a mid-burst worker death must fail ONLY the
calls whose results never landed — resubmitting an already-resulted call
would break at-most-once (ray parity: direct_task_transport.cc +
actor_task ordering guarantees)."""

import time

import pytest

import ray_tpu


def test_wait_sees_fast_task_inside_a_burst(ray_start_regular):
    """A burst drains into one batch frame; a slow task in the batch must
    not gate the delivery of faster ones behind it."""
    @ray_tpu.remote
    def quick(i):
        return i

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return "slow"

    refs = [quick.remote(i) for i in range(20)] + [slow.remote()]
    ready, not_ready = ray_tpu.wait(refs, num_returns=20, timeout=4)
    assert len(ready) == 20 and not_ready == [refs[-1]]
    assert ray_tpu.get(refs[-1], timeout=30) == "slow"


def test_actor_burst_streams_in_order(ray_start_regular):
    """Sequential-actor bursts ride batch frames; results stream back and
    the calls run strictly in submission order."""
    @ray_tpu.remote
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return i

        def history(self):
            return list(self.log)

    a = Seq.remote()
    refs = [a.add.remote(i) for i in range(200)]
    assert ray_tpu.get(refs, timeout=60) == list(range(200))
    assert ray_tpu.get(a.history.remote(), timeout=30) == list(range(200))
    ray_tpu.kill(a)


def test_mid_burst_actor_death_fails_only_pending_calls(ray_start_regular):
    """Kill the actor while a burst is in flight: calls whose results
    already streamed back keep them; the rest surface ActorDiedError —
    and nothing re-executes (at-most-once)."""
    @ray_tpu.remote(max_restarts=0)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, delay):
            self.n += 1
            time.sleep(delay)
            return self.n

    a = Counter.remote()
    # first call settles the connection; then a burst where call #3
    # sleeps long enough for the kill to land mid-batch
    assert ray_tpu.get(a.bump.remote(0.0), timeout=30) == 1
    refs = [a.bump.remote(0.0), a.bump.remote(0.0),
            a.bump.remote(3.0)] + [a.bump.remote(0.0) for _ in range(5)]
    # let the early calls complete and stream back
    early = ray_tpu.get(refs[:2], timeout=30)
    assert early == [2, 3]
    ray_tpu.kill(a)
    from ray_tpu._private.serialization import TaskError

    outcomes = []
    for r in refs[2:]:
        # short timeout: a silent hang must FAIL here as GetTimeoutError,
        # not masquerade as a pass after minutes of waiting
        try:
            outcomes.append(("ok", ray_tpu.get(r, timeout=15)))
        except Exception as e:  # noqa: BLE001
            cause = e.cause if isinstance(e, TaskError) else e
            outcomes.append(("err", type(cause).__name__))
    # every unfinished call fails WITH A DEATH ERROR (typed, prompt —
    # never a timeout) and nothing re-executes
    assert all(
        kind == "err" and name in ("ActorDiedError", "WorkerDiedError")
        for kind, name in outcomes
    ), outcomes
    assert ray_tpu.get(refs[0], timeout=5) == 2  # result survives the death
