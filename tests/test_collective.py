"""Collective + mesh tests (analog of ray: python/ray/util/collective/tests/)."""

import numpy as np
import pytest

import ray_tpu

pytestmark = pytest.mark.collective


@ray_tpu.remote
class CollectiveWorker:
    def _rt_init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend, group_name)
        return rank

    def do_allreduce(self, value, group_name):
        from ray_tpu.util import collective as col

        arr = np.full((4,), float(value))
        out = col.allreduce(arr, group_name)
        return out

    def do_allgather(self, value, group_name):
        from ray_tpu.util import collective as col

        return col.allgather(np.full((2,), float(value)), group_name)

    def do_broadcast(self, value, group_name):
        from ray_tpu.util import collective as col

        arr = np.full((3,), float(value))
        return col.broadcast(arr, src_rank=0, group_name=group_name)

    def do_reducescatter(self, value, group_name):
        from ray_tpu.util import collective as col

        arr = np.full((4, 2), float(value))
        return col.reducescatter(arr, group_name)

    def do_barrier(self, group_name):
        from ray_tpu.util import collective as col

        col.barrier(group_name)
        return True


def test_collective_store_backend(ray_start_regular):
    from ray_tpu.util import collective as col

    workers = [CollectiveWorker.remote() for _ in range(2)]
    col.create_collective_group(workers, 2, [0, 1], backend="store",
                                group_name="g1")
    outs = ray_tpu.get(
        [w.do_allreduce.remote(i + 1, "g1") for i, w in enumerate(workers)],
        timeout=60,
    )
    for out in outs:
        np.testing.assert_allclose(out, np.full((4,), 3.0))
    gathered = ray_tpu.get(
        [w.do_allgather.remote(i + 1, "g1") for i, w in enumerate(workers)],
        timeout=60,
    )
    for g in gathered:
        assert len(g) == 2
        np.testing.assert_allclose(g[0], np.full((2,), 1.0))
        np.testing.assert_allclose(g[1], np.full((2,), 2.0))
    bc = ray_tpu.get(
        [w.do_broadcast.remote(i + 10, "g1") for i, w in enumerate(workers)],
        timeout=60,
    )
    np.testing.assert_allclose(bc[0], np.full((3,), 10.0))
    np.testing.assert_allclose(bc[1], np.full((3,), 10.0))
    rs = ray_tpu.get(
        [w.do_reducescatter.remote(i + 1, "g1") for i, w in enumerate(workers)],
        timeout=60,
    )
    np.testing.assert_allclose(rs[0], np.full((2, 2), 3.0))
    np.testing.assert_allclose(rs[1], np.full((2, 2), 3.0))
    assert all(
        ray_tpu.get([w.do_barrier.remote("g1") for w in workers], timeout=60)
    )


@ray_tpu.remote
class XlaCollectiveWorker:
    """A rank in a jax.distributed gang — the real backend="xla" path."""

    def setup(self, coordinator, world_size, rank):
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.distributed.initialize(
            coordinator, num_processes=world_size, process_id=rank
        )
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend="xla",
                                  group_name="xg")
        return rank

    def do_ops(self, rank):
        import numpy as np

        from ray_tpu.util import collective as col

        out = {}
        out["ar"] = col.allreduce(np.full((4,), float(rank + 1)), "xg")
        out["ag"] = col.allgather(np.full((2,), float(rank + 1)), "xg")
        out["bc"] = col.broadcast(np.full((3,), float(rank + 10)), src_rank=0,
                                  group_name="xg")
        out["rs"] = col.reducescatter(
            np.arange(8, dtype=np.float32).reshape(4, 2) * (rank + 1), "xg"
        )
        col.barrier("xg")
        return out


def test_collective_xla_backend(ray_start_regular):
    """backend="xla": ops run as compiled shard_map programs over a global
    mesh spanning the jax.distributed gang (reference analog: the NCCL group
    in ray: util/collective/collective_group/nccl_collective_group.py)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coordinator = f"127.0.0.1:{port}"

    workers = [XlaCollectiveWorker.remote() for _ in range(2)]
    ray_tpu.get(
        [w.setup.remote(coordinator, 2, i) for i, w in enumerate(workers)],
        timeout=300,
    )
    outs = ray_tpu.get(
        [w.do_ops.remote(i) for i, w in enumerate(workers)], timeout=300
    )
    for out in outs:
        np.testing.assert_allclose(out["ar"], np.full((4,), 3.0))
        np.testing.assert_allclose(out["ag"][0], np.full((2,), 1.0))
        np.testing.assert_allclose(out["ag"][1], np.full((2,), 2.0))
        np.testing.assert_allclose(out["bc"], np.full((3,), 10.0))
    reduced = np.arange(8, dtype=np.float32).reshape(4, 2) * 3
    np.testing.assert_allclose(outs[0]["rs"], reduced[:2])
    np.testing.assert_allclose(outs[1]["rs"], reduced[2:])


def test_mesh_and_ingraph_collectives():
    import jax
    import jax.numpy as jnp

    from ray_tpu import parallel

    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = parallel.create_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}

    mesh2 = parallel.auto_mesh(model=2)
    assert mesh2.shape["model"] == 2 and mesh2.shape["data"] == 4

    # compiled allreduce: psum over data axis
    ar = parallel.compiled_allreduce(mesh, "data")
    x = jnp.arange(8.0)
    out = ar(x)
    # each data shard of size 2 is summed across 4 data ranks; model axis
    # replicates. Sum over the data axis of the per-shard values:
    x_resh = x.reshape(4, 2)
    expected = jnp.tile(x_resh.sum(axis=0), 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected))


def test_fsdp_param_sharding():
    import jax.numpy as jnp

    from ray_tpu import parallel

    mesh = parallel.create_mesh({"data": 2, "fsdp": 4})
    params = {
        "big": jnp.zeros((1024, 256)),
        "small": jnp.zeros((4,)),
    }
    shardings = parallel.shard_params_fsdp(params, mesh)
    assert "fsdp" in str(shardings["big"].spec)
    assert shardings["small"].spec == ()
