"""State API + task events + timeline + metrics.

Reference analogs: ray python/ray/tests/test_state_api.py (list_actors/
list_tasks/...), `ray timeline` chrome trace, util/metrics.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util import state


@ray_tpu.remote
def work(x):
    return x * 2


@ray_tpu.remote
class Greeter:
    def hi(self):
        return "hi"


def _wait_for(fn, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.3)
    raise TimeoutError("condition not met")


def test_list_tasks_and_summary(ray_start_regular):
    refs = [work.remote(i) for i in range(5)]
    assert ray_tpu.get(refs, timeout=60) == [0, 2, 4, 6, 8]

    # Task events are flushed in batches; wait for the FINISHED records.
    def finished():
        rows = state.list_tasks(filters=[("state", "=", "FINISHED"),
                                         ("name", "=", "work")])
        return rows if len(rows) >= 5 else None

    rows = _wait_for(finished)
    assert all(r["node_id"] for r in rows)
    assert all(r.get("duration") is not None for r in rows)

    summary = state.summarize_tasks()
    assert summary["work"]["FINISHED"] >= 5


def test_list_actors_and_nodes(ray_start_regular):
    g = Greeter.remote()
    assert ray_tpu.get(g.hi.remote(), timeout=60) == "hi"
    actors = state.list_actors(filters=[("state", "=", "ALIVE")])
    assert any(a["class_name"] == "Greeter" for a in actors)

    nodes = state.list_nodes()
    assert len(nodes) >= 1
    assert all("resources_total" in n for n in nodes)

    stats = state.get_node_stats(nodes[0]["node_id"])
    assert stats is not None and "store_used_bytes" in stats


def test_list_objects(ray_start_regular):
    import numpy as np

    ref = ray_tpu.put(np.zeros(200_000, dtype=np.float32))  # plasma-sized
    objs = _wait_for(
        lambda: [o for o in state.list_objects()
                 if o["object_id"] == ref.binary().hex()] or None
    )
    assert objs[0]["locations"]
    del ref


def test_timeline_chrome_trace(ray_start_regular, tmp_path):
    ray_tpu.get([work.remote(i) for i in range(3)], timeout=60)
    path = str(tmp_path / "trace.json")
    trace = _wait_for(
        lambda: [e for e in ray_tpu.timeline(path)
                 if e["name"] == "work"] or None
    )
    ev = trace[0]
    assert ev["ph"] == "X" and ev["dur"] >= 1.0
    import json

    with open(path) as f:
        assert json.load(f)


def test_metrics_counter_gauge_histogram(ray_start_regular):
    from ray_tpu.util import metrics

    c = metrics.Counter("test_requests", tag_keys=("route",))
    c.inc(1.0, tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = metrics.Gauge("test_depth")
    g.set(7.0)
    h = metrics.Histogram("test_latency", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(5.0)
    h.observe(50.0)
    with pytest.raises(ValueError):
        c.inc(-1.0)
    with pytest.raises(ValueError):
        c.inc(1.0, tags={"bogus": "x"})

    metrics.flush()
    out = metrics.list_metrics()
    counter = out["test_requests"][0]
    assert counter["series"][0]["value"] == 3.0
    assert out["test_depth"][0]["series"][0]["value"] == 7.0
    hist = out["test_latency"][0]["series"][0]
    assert hist["buckets"] == [1, 1, 1] and hist["count"] == 3
