"""Tensor-parallel GPT-2 (SURVEY §2.9: TP as first-class mesh axis).

The TP path is pure GSPMD: column/row sharding annotations on the block
matmuls (gpt2.shard_params_tp); XLA inserts the per-block model-axis
allreduce. The train step must produce the same loss as the DP run —
same math, different layout.
"""

import jax
import numpy as np

from ray_tpu import parallel
from ray_tpu.models import gpt2


def _one_step(mesh, tp: bool):
    config = gpt2.GPT2Config.small_test()
    model, params, tx, opt_state = gpt2.make_train_state(
        config, jax.random.PRNGKey(0)
    )
    if tp:
        params, opt_state = gpt2.shard_train_state_tp(params, opt_state, mesh)
    else:
        params, opt_state = gpt2.shard_train_state(params, opt_state, mesh)
    step = gpt2.build_train_step(model, tx, donate=False)
    batch = gpt2.shard_batch(
        gpt2.synthetic_batch(jax.random.PRNGKey(1), 8, 32, config.vocab_size),
        mesh,
    )
    params2, _, loss = step(params, opt_state, batch)
    jax.block_until_ready(loss)
    return float(loss), params2


def test_tp_matches_dp_loss():
    assert len(jax.devices()) == 8
    dp_mesh = parallel.create_mesh({"data": 8}, devices=jax.devices())
    tp_mesh = parallel.create_mesh(
        {"data": 2, "model": 4}, devices=jax.devices()
    )
    dp_loss, _ = _one_step(dp_mesh, tp=False)
    tp_loss, tp_params = _one_step(tp_mesh, tp=True)
    assert abs(dp_loss - tp_loss) < 1e-2

    # the TP layout actually shards: qkv kernel lives split over "model"
    qkv = tp_params["h_0"]["attn"]["c_attn"]["kernel"]
    assert "model" in str(qkv.sharding.spec)


def test_tp_sharding_specs():
    mesh = parallel.create_mesh({"data": 4, "model": 2}, devices=jax.devices())
    config = gpt2.GPT2Config.small_test()
    model, params, _, _ = gpt2.make_train_state(config, jax.random.PRNGKey(0))
    shardings = gpt2.shard_params_tp(params, mesh)
    block = shardings["h_0"]
    assert str(block["attn"]["c_attn"]["kernel"].spec) == \
        str(jax.sharding.PartitionSpec(None, "model"))
    assert str(block["attn"]["c_proj"]["kernel"].spec) == \
        str(jax.sharding.PartitionSpec("model", None))
    assert str(block["mlp"]["c_fc"]["kernel"].spec) == \
        str(jax.sharding.PartitionSpec(None, "model"))
    # replicated leaves: embeddings, layernorms, down-proj bias
    assert str(shardings["wte"]["embedding"].spec) == "PartitionSpec()"
    assert str(block["ln_1"]["scale"].spec) == "PartitionSpec()"
    assert str(block["mlp"]["c_proj"]["bias"].spec) == "PartitionSpec()"
