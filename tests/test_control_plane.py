"""Control-plane fast path (PR 16): pinned invariants.

The submit->lease->dispatch fast path is a perf change; these tests pin
the SEMANTICS the optimization must not bend:

  - block-minted binary task/object ids stay unique and layout-compatible
    with the id classes;
  - the receiver-side idempotency cache stays bounded without ever
    evicting an in-flight (pending) entry;
  - the submit_batch idem key covers the WHOLE frame (first, last, len) —
    the first-spec-only key deduped a regrouped retry frame wrong;
  - a retry storm (same frame delivered repeatedly, same idem token) and
    wire-level dup/delay chaos on the batched-ack lane stay exactly-once;
  - per-callsite templates are cached, invalidated by .options(), and
    never ride a pickle;
  - the lease grace window reuses grants instead of re-leasing per call;
  - failures still surface through the fire-and-forget ack="batch" lane;
  - >=64KB array args stay zero-copy (inline wire form shares memory);
  - scripts/lint_hotpath.py guards the marked hot sections.
"""

import asyncio
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import faultsim
from ray_tpu._private import metrics_core as mc
from ray_tpu._private import rpcio
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import (
    ACTOR_ID_UNIQUE_BYTES,
    TASK_ID_SIZE,
    ActorID,
    JobID,
    ObjectID,
    TaskID,
    TaskIDMinter,
    object_id_binary,
)

# chaos + monkeypatched submit plumbing mutate driver-global state: build
# a private cluster and tear it down after this module
RAY_REUSE_CLUSTER = False

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faultsim():
    yield
    faultsim.clear()
    faultsim.set_self_id(f"pid:{os.getpid()}")


# ------------------------------------------------------------ id minting --


def test_task_id_minter_unique_and_layout():
    job = JobID.from_int(7)
    minter = TaskIDMinter.for_job(job)
    minted = {minter.next_binary() for _ in range(10_000)}
    assert len(minted) == 10_000  # block refills never repeat an id
    for b in list(minted)[:64]:
        assert len(b) == TASK_ID_SIZE
        t = TaskID(b)
        # same layout the one-off constructor produces: driver tasks carry
        # the nil-actor sentinel + job id in the suffix
        assert t.job_id() == job
        assert t.actor_id().binary()[:ACTOR_ID_UNIQUE_BYTES] == (
            b"\xff" * ACTOR_ID_UNIQUE_BYTES
        )

    actor = ActorID.of(job)
    t = TaskID(TaskIDMinter.for_actor(actor).next_binary())
    assert t.actor_id() == actor
    assert t.job_id() == job


def test_task_id_minter_thread_safe():
    minter = TaskIDMinter.for_job(JobID.from_int(1))
    per_thread = [set() for _ in range(4)]

    def mint(bucket):
        for _ in range(5_000):
            bucket.add(minter.next_binary())

    threads = [threading.Thread(target=mint, args=(b,)) for b in per_thread]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    total = sum(len(b) for b in per_thread)
    union = set().union(*per_thread)
    # an id handed to two threads (torn block refill) would collapse the
    # union below the per-thread total
    assert total == 20_000
    assert len(union) == total


def test_object_id_binary_matches_object_id():
    t = TaskID.for_task(JobID.from_int(3))
    for index in (0, 1, 2, 255, 256, 70_000):
        assert object_id_binary(t.binary(), index) == (
            ObjectID.from_index(t, index).binary()
        )


# ------------------------------------------- receiver-side idem cache --


def test_idem_cache_bounded_and_pending_survives_eviction():
    async def run():
        pending_tok = ("t-pending", os.getpid())
        pending_fut, owner = rpcio._idem_claim(pending_tok)
        assert owner
        # churn far past the cap with completed entries
        toks = [("t-churn", os.getpid(), i)
                for i in range(rpcio._IDEM_MAX + 512)]
        for tok in toks:
            fut, owner = rpcio._idem_claim(tok)
            assert owner
            fut.set_result(tok)
        # bounded: the ring evicted completed entries instead of growing
        assert len(rpcio._idem_results) <= rpcio._IDEM_MAX + 16
        # the pending entry survived the churn (evicting it would let a
        # retry double-execute), and a duplicate claim is NOT an owner
        dup_fut, dup_owner = rpcio._idem_claim(pending_tok)
        assert dup_fut is pending_fut
        assert not dup_owner
        pending_fut.set_result(None)
        rpcio._idem_forget(pending_tok)
        for tok in toks:
            rpcio._idem_forget(tok)

    asyncio.run(run())


# ------------------------------------------------ batched submit lane --


def _append_line(path):
    # O_APPEND single short write: atomic across worker processes
    with open(path, "a") as f:
        f.write(f"{os.getpid()}\n")


def test_submit_batch_idem_key_covers_whole_frame(ray_start_regular,
                                                  monkeypatch):
    """Regression: the idem key must identify the full frame (first, last,
    len), not just batch[0] — a grown retry frame sharing its head with an
    earlier frame must not alias its cached ack."""
    import ray_tpu._private.worker as worker_mod

    real = worker_mod.call_with_retries
    seen = []

    async def spy(get_conn, method, payload=None, **kw):
        if method == "submit_batch":
            seen.append((list(payload["specs"]), kw.get("idem")))
        return await real(get_conn, method, payload, **kw)

    monkeypatch.setattr(worker_mod, "call_with_retries", spy)

    @ray_tpu.remote
    def echo(x):
        return x

    refs = [echo.options(scheduling_strategy="SPREAD").remote(i)
            for i in range(6)]
    assert ray_tpu.get(refs, timeout=60) == list(range(6))

    assert seen, "SPREAD tasks must route through the submit_batch lane"
    keys = set()
    for specs, idem in seen:
        assert idem == ("submit_batch", specs[0].task_id,
                        specs[-1].task_id, len(specs), specs[0].attempt)
        keys.add(idem)
    assert len(keys) == len(seen)  # distinct frames -> distinct keys


def test_retry_storm_on_batched_ack_lane_executes_once(ray_start_regular,
                                                       monkeypatch,
                                                       tmp_path):
    """Deliver every submit_batch frame three times with the SAME idem
    token — the wire pattern of a driver whose acks were lost mid-retry.
    The raylet's idem cache must execute the frame once."""
    import ray_tpu._private.worker as worker_mod

    real = worker_mod.call_with_retries
    storms = []

    async def storm(get_conn, method, payload=None, **kw):
        if method != "submit_batch":
            return await real(get_conn, method, payload, **kw)
        r1 = await real(get_conn, method, payload, **kw)
        r2 = await real(get_conn, method, payload, **kw)
        r3 = await real(get_conn, method, payload, **kw)
        storms.append(kw.get("idem"))
        assert r1 == r2 == r3  # duplicates re-send the first ack
        return r3

    monkeypatch.setattr(worker_mod, "call_with_retries", storm)

    marker = tmp_path / "ran.txt"

    @ray_tpu.remote
    def mark(path):
        _append_line(path)
        return 1

    n = 8
    refs = [mark.options(scheduling_strategy="SPREAD").remote(str(marker))
            for _ in range(n)]
    assert ray_tpu.get(refs, timeout=60) == [1] * n
    assert storms, "storm wrapper never saw a submit_batch frame"
    time.sleep(0.5)  # let any (wrongly) re-scheduled duplicates land
    assert len(marker.read_text().splitlines()) == n


@pytest.mark.parametrize("spec", [
    "submit_batch:dup:1.0:5",        # every frame duplicated on the wire
    "submit_batch:delay:1.0:2:40",   # every frame delayed 40ms
])
def test_chaos_on_batched_ack_lane_exactly_once(ray_start_regular, tmp_path,
                                                spec):
    """Wire-level chaos (the RAY_TPU_RPC_FAULTS machinery) on the
    fire-and-forget submit lane: duplicated frames are suppressed by msg-id
    dedup, delayed frames just arrive late — either way each task runs
    exactly once."""
    faultsim.install(spec)
    marker = tmp_path / "ran.txt"

    @ray_tpu.remote
    def mark(path):
        _append_line(path)
        return 1

    n = 6
    refs = [mark.options(scheduling_strategy="SPREAD").remote(str(marker))
            for _ in range(n)]
    assert ray_tpu.get(refs, timeout=60) == [1] * n
    faultsim.clear()
    time.sleep(0.5)
    assert len(marker.read_text().splitlines()) == n


def test_batched_ack_failures_still_surface(ray_start_regular):
    """ack="batch" acks frame acceptance, not completion — app errors must
    still reach the caller via the task-result stream."""

    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom-cp16")

    with pytest.raises(Exception, match="kaboom-cp16"):
        ray_tpu.get(boom.options(scheduling_strategy="SPREAD").remote(),
                    timeout=60)


# --------------------------------------------------- spec templates --


def test_remote_function_template_cached_and_options_fresh(
        ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return x * 2

    assert ray_tpu.get(double.remote(2), timeout=60) == 4
    tmpl = double._template
    assert tmpl is not None
    assert ray_tpu.get(double.remote(3), timeout=60) == 6
    assert double._template is tmpl  # reused, not rebuilt per call

    spread = double.options(scheduling_strategy="SPREAD")
    assert spread._template is None  # new options -> fresh template
    assert ray_tpu.get(spread.remote(4), timeout=60) == 8
    assert spread._template is not tmpl

    # the template pins the live CoreWorker: it must not ride a pickle
    assert double.__getstate__()["_template"] is None


def test_actor_method_template_cached(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, k):
            self.n += k
            return self.n

    a = Counter.remote()
    assert ray_tpu.get(a.bump.remote(1), timeout=60) == 1
    method = a.bump
    assert a.bump is method  # memoized on the handle
    tmpl = method._template
    assert tmpl is not None
    assert ray_tpu.get(a.bump.remote(2), timeout=60) == 3
    assert a.bump._template is tmpl
    assert method.__getstate__()["_template"] is None
    ray_tpu.kill(a)


# --------------------------------------------------- lease grace window --


def _lease_calls() -> float:
    dump = mc.registry().snapshot().get("rpc_request_latency_seconds")
    if not dump:
        return 0.0
    return sum(s.get("count", 0) for s in dump.get("series", ())
               if s.get("tags", {}).get("method") == "lease_workers")


def test_lease_grace_reuses_grant_across_sync_calls(ray_start_regular):
    """Back-to-back sync calls must ride one lease grant (grace window),
    not re-lease per call (the old return-on-drain behavior)."""

    @ray_tpu.remote
    def nop():
        return 1

    ray_tpu.get(nop.remote(), timeout=60)  # warm the pump + first lease
    before = _lease_calls()
    for _ in range(20):
        assert ray_tpu.get(nop.remote(), timeout=60) == 1
    grew = _lease_calls() - before
    # without grace this is ~20 (one lease round trip per drain); with it,
    # ~0. Allow slack for a scheduler hiccup outliving the grace window.
    assert grew <= 5, f"lease_workers grew by {grew} over 20 sync calls"


# ----------------------------------------------------- stage timing --


def test_stage_timing_flag_records_driver_stages(ray_start_regular):
    prev = cfg.control_plane_stage_timing
    cfg.update({"control_plane_stage_timing": True})
    try:
        @ray_tpu.remote
        def nop():
            return 1

        assert ray_tpu.get(nop.remote(), timeout=60) == 1
        dump = mc.registry().snapshot().get("control_plane_stage_seconds")
        assert dump, "stage histogram family missing"
        stages = {s["tags"].get("stage") for s in dump.get("series", ())
                  if s.get("count", 0) > 0}
        assert {"id_mint", "envelope_build", "result_return"} <= stages
    finally:
        cfg.update({"control_plane_stage_timing": prev})


# --------------------------------------------------------- zero copy --


def test_large_array_arg_stays_zero_copy_inline(ray_start_regular):
    """A 64KB ndarray arg rides the inline ('v', meta, BufferList) wire
    form with the payload buffer SHARING memory with the caller's array —
    the fast path must not reintroduce a defensive copy."""
    from ray_tpu._private.worker import global_worker

    cw = global_worker.core_worker
    arr = np.arange(64 * 1024, dtype=np.uint8)
    pins = []
    enc_args, enc_kwargs, pending = cw._encode_slots((arr,), None, pins)
    assert not pending and not enc_kwargs
    kind, _meta, wire = enc_args[0]
    assert kind == "v"  # inline: below max_direct_call_object_size
    assert any(
        memoryview(buf).nbytes == arr.nbytes
        and np.shares_memory(np.frombuffer(buf, dtype=np.uint8), arr)
        for buf in wire.buffers
    ), "no wire buffer shares memory with the source array"

    # and end-to-end through an actor call the bytes arrive intact
    @ray_tpu.remote
    class Summer:
        def total(self, a):
            return int(a.sum())

    s = Summer.remote()
    assert ray_tpu.get(s.total.remote(arr), timeout=60) == int(arr.sum())
    ray_tpu.kill(s)


# ------------------------------------------------------ hotpath lint --


def test_lint_hotpath_gate(tmp_path):
    script = os.path.join(REPO_ROOT, "scripts", "lint_hotpath.py")
    r = subprocess.run([sys.executable, script], capture_output=True,
                       text=True, cwd=REPO_ROOT)
    assert r.returncode == 0, (
        f"hot sections regressed:\n{r.stdout}\n{r.stderr}"
    )

    bad = tmp_path / "hot.py"
    bad.write_text(
        "x = 1\n"
        "f'{x} outside any region is fine'\n"
        "# hotpath: begin demo\n"
        "opts = dict(base)\n"                       # line 4: violation
        "tid = f'task-{x}'\n"                       # line 5: violation
        "raise ValueError(f'err {x}')  # lint: allow-hotpath (error path)\n"
        "# f'in a comment' is skipped\n"
        "# hotpath: end demo\n"
    )
    r = subprocess.run([sys.executable, script, str(bad)],
                       capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 1
    assert "hot.py:4" in r.stdout and "dict(" in r.stdout
    assert "hot.py:5" in r.stdout and "f-string" in r.stdout
    assert "hot.py:2" not in r.stdout  # outside a region
    assert "hot.py:6" not in r.stdout  # allow-marked error path

    # a hot file with NO marked regions fails: markers are the contract
    unmarked = tmp_path / "unmarked.py"
    unmarked.write_text("x = dict(y)\n")
    r = subprocess.run([sys.executable, script, str(unmarked)],
                       capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 1
    assert "no '# hotpath: begin' regions" in r.stdout


def test_fast_path_flags_exist():
    # pins the A/B lever names the bench + docs reference
    assert cfg.direct_lease_grace_s >= 0
    assert cfg.actor_sender_linger_s >= 0
    assert cfg.submit_ack_mode in ("batch", "spec")
    assert cfg.task_events_flush_interval_s >= 0
    assert cfg.free_flush_interval_s >= 0
