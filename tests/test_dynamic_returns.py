"""num_returns="dynamic": generator tasks yield a variable number of
objects; the visible ref resolves to the per-item ObjectRefs
(ray parity: task_manager.h:96 ObjectRefStream / dynamic generators)."""

import numpy as np
import pytest

import ray_tpu


@ray_tpu.remote(num_returns="dynamic")
def splitter(n):
    for i in range(n):
        # big enough that items always go to plasma
        yield np.full(64 * 1024, i, dtype=np.uint8)


def test_dynamic_returns_roundtrip(ray_start_regular):
    ref = splitter.remote(5)
    item_refs = ray_tpu.get(ref, timeout=60)
    assert isinstance(item_refs, list) and len(item_refs) == 5
    for i, r in enumerate(item_refs):
        arr = ray_tpu.get(r, timeout=60)
        assert arr.shape == (64 * 1024,) and int(arr[0]) == i


def test_dynamic_returns_empty_and_list(ray_start_regular):
    assert ray_tpu.get(splitter.remote(0), timeout=60) == []

    @ray_tpu.remote(num_returns="dynamic")
    def from_list():
        return [b"a" * 200_000, b"b" * 200_000]  # plain iterable works too

    refs = ray_tpu.get(from_list.remote(), timeout=60)
    assert [ray_tpu.get(r, timeout=60)[:1] for r in refs] == [b"a", b"b"]


@pytest.mark.slow  # ~60s of reconstruction timeouts: slow lane (tier-1 budget)
def test_dynamic_item_lineage_reconstruction(ray_start_regular):
    """Deleting a dynamic item's plasma file behind the runtime triggers
    re-execution of the producing task (lineage adopted by the caller)."""
    import os

    from ray_tpu._private.worker import global_worker
    from ray_tpu._private import object_store

    ref = splitter.remote(3)
    item_refs = ray_tpu.get(ref, timeout=60)
    target = item_refs[1]
    # drop the backing copy (slab entry or .obj file) behind the runtime
    store_dir = global_worker.core_worker.store_dir
    assert object_store.object_exists(store_dir, target.id())
    assert object_store.discard_local(store_dir, target.id())
    arr = ray_tpu.get(target, timeout=120)
    assert int(arr[0]) == 1 and arr.shape == (64 * 1024,)


def test_dynamic_generator_error_surfaces_and_cleans_up(ray_start_regular):
    import glob
    import os

    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote(num_returns="dynamic", max_retries=0)
    def bad(n):
        for i in range(n):
            yield np.full(64 * 1024, i, dtype=np.uint8)
        raise RuntimeError("boom after yields")

    import pytest

    from ray_tpu._private.serialization import TaskError

    ref = bad.remote(3)
    with pytest.raises(TaskError, match="boom"):
        ray_tpu.get(ref, timeout=60)
    # partial items were unlinked, not orphaned, on the executing node
    store_dir = global_worker.core_worker.store_dir
    tid_hex = ref.id().task_id().binary().hex()
    leftovers = [p for p in glob.glob(os.path.join(store_dir, "*"))
                 if tid_hex in os.path.basename(p)]
    assert leftovers == [], leftovers


def test_dynamic_nested_ref_in_item_survives(ray_start_regular):
    inner = ray_tpu.put(b"payload" * 50_000)

    @ray_tpu.remote(num_returns="dynamic")
    def wrap(rl):
        # rl is a container, so rl[0] stays an ObjectRef (top-level args
        # are materialized; nested refs travel as refs)
        yield {"inner": rl[0]}

    refs = ray_tpu.get(wrap.remote([inner]), timeout=60)
    item = ray_tpu.get(refs[0], timeout=60)
    del inner  # only the nested ref inside the item keeps it alive now
    import gc

    gc.collect()
    assert ray_tpu.get(item["inner"], timeout=60)[:7] == b"payload"


def test_dynamic_rejected_for_actor_methods(ray_start_regular):
    import pytest

    @ray_tpu.remote
    class A:
        def gen(self):
            yield 1

    a = A.remote()
    with pytest.raises(ValueError, match="not supported for actor"):
        a.gen.options(num_returns="dynamic")
    ray_tpu.kill(a)
