"""Differential tests: native (C++) scheduling policies vs the Python oracle.

The native engine (src/scheduler.cpp via ray_tpu/_private/native_sched.py)
must pick the same node as the pure-Python policies in
ray_tpu/_private/common.py for every strategy on randomized clusters —
mirroring how the reference unit-tests its policy classes
(ray: src/ray/raylet/scheduling/policy/scheduling_policy_test.cc).
"""

import random

import pytest

from ray_tpu._private import native_sched
from ray_tpu._private.common import (
    NodeInfo,
    SchedulingStrategy,
    pick_node_py,
    place_bundles_py,
)

pytestmark = pytest.mark.skipif(
    not native_sched.available(), reason="native scheduler not built"
)


def _rand_cluster(rng, n_nodes):
    nodes = []
    for i in range(n_nodes):
        total = {"CPU": rng.choice([1, 2, 4, 8, 16])}
        if rng.random() < 0.5:
            total["TPU"] = rng.choice([1, 4, 8])
        if rng.random() < 0.3:
            total["memory"] = rng.choice([2.5, 8.0, 16.0])
        avail = {
            k: round(v * rng.choice([0.0, 0.25, 0.5, 0.75, 1.0]), 4)
            for k, v in total.items()
        }
        labels = {}
        if rng.random() < 0.6:
            labels["zone"] = rng.choice(["a", "b", "c"])
        if rng.random() < 0.4:
            labels["tpu-slice"] = rng.choice(["s0", "s1"])
        nodes.append(
            NodeInfo(
                node_id=f"node{i:03d}", host="127.0.0.1", port=0,
                store_dir="", resources_total=total,
                resources_available=avail, labels=labels,
                alive=rng.random() > 0.1,
            )
        )
    return nodes


def _rand_demand(rng):
    d = {"CPU": rng.choice([0.5, 1, 2, 4])}
    if rng.random() < 0.3:
        d["TPU"] = rng.choice([1, 4])
    return d


def _strategies(rng, nodes):
    yield SchedulingStrategy()
    yield SchedulingStrategy(kind="SPREAD")
    nid = rng.choice(nodes).node_id if nodes else "nodeX"
    yield SchedulingStrategy(kind="NODE_AFFINITY", node_id=nid, soft=False)
    yield SchedulingStrategy(kind="NODE_AFFINITY", node_id=nid, soft=True)
    yield SchedulingStrategy(kind="NODE_AFFINITY", node_id="missing", soft=True)
    yield SchedulingStrategy(kind="NODE_LABEL", labels_hard={"zone": "a"})
    yield SchedulingStrategy(
        kind="NODE_LABEL", labels_hard={"zone": ["a", "b"]},
        labels_soft={"tpu-slice": "s0"},
    )
    yield SchedulingStrategy(kind="NODE_LABEL", labels_hard={"zone": "!c"})
    yield SchedulingStrategy(kind="NODE_LABEL", labels_hard={"tpu-slice": None})


def test_pick_node_matches_python_oracle():
    rng = random.Random(7)
    checked = picked = 0
    for trial in range(200):
        nodes = _rand_cluster(rng, rng.randint(1, 12))
        demand = _rand_demand(rng)
        local = rng.choice(nodes).node_id if rng.random() < 0.7 else None
        for strat in _strategies(rng, nodes):
            rr_py, rr_nat = [trial % 5], [trial % 5]
            want = pick_node_py(nodes, demand, strat, local, rr_py)
            got = native_sched.pick_node(nodes, demand, strat, local, rr_nat, 0.5)
            assert got == want, (
                f"trial {trial} strat={strat}: native={got} py={want}\n"
                f"demand={demand} local={local}\n"
                + "\n".join(
                    f"  {n.node_id} alive={n.alive} t={n.resources_total} "
                    f"a={n.resources_available} l={n.labels}" for n in nodes
                )
            )
            assert rr_nat == rr_py
            checked += 1
            picked += got is not None
    assert checked > 1000 and picked > 100  # the sweep actually exercised both


def test_place_bundles_matches_python_oracle():
    rng = random.Random(11)
    checked = placed = 0
    for trial in range(200):
        nodes = _rand_cluster(rng, rng.randint(1, 8))
        bundles = [
            {"CPU": rng.choice([0.5, 1, 2])} for _ in range(rng.randint(1, 5))
        ]
        for strategy in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
            want = place_bundles_py(nodes, bundles, strategy)
            got = native_sched.place_bundles(nodes, bundles, strategy)
            assert got == want, (
                f"trial {trial} {strategy}: native={got} py={want}"
            )
            checked += 1
            placed += got is not None
    assert checked == 800 and placed > 200


def test_wire_format_edge_cases_fall_back_consistently():
    """Values the wire format cannot carry (separator chars, empty-string
    conditions, non-string conditions) must not diverge from the oracle:
    common.pick_node guards with encodable() and falls back to Python."""
    from ray_tpu._private.common import pick_node, place_bundles

    def node(nid, labels):
        return NodeInfo(node_id=nid, host="h", port=0, store_dir="",
                        resources_total={"CPU": 4},
                        resources_available={"CPU": 4}, labels=labels)

    # label value with a separator char -> encodable() is False
    nodes = [node("n1", {"pool": "a,b"}), node("n2", {"pool": "c"})]
    strat = SchedulingStrategy(kind="NODE_LABEL", labels_hard={"pool": "a,b"})
    assert not native_sched.encodable(nodes, {"CPU": 1}, strat)
    assert pick_node(nodes, {"CPU": 1}, strat, None, [0]) == "n1"

    # int conditions match string labels identically on both paths
    nodes = [node("n1", {"slice": "1"}), node("n2", {"slice": "9"})]
    strat = SchedulingStrategy(kind="NODE_LABEL", labels_hard={"slice": [1, 2]})
    want = pick_node_py(nodes, {"CPU": 1}, strat, None, [0])
    assert want == "n1"
    assert native_sched.pick_node(nodes, {"CPU": 1}, strat, None, [0], 0.5) == want

    # empty-string equality cannot ride the wire -> oracle handles it
    nodes = [node("n1", {"zone": ""})]
    strat = SchedulingStrategy(kind="NODE_LABEL", labels_hard={"zone": ""})
    assert not native_sched.encodable(nodes, {"CPU": 1}, strat)
    assert pick_node(nodes, {"CPU": 1}, strat, None, [0]) == "n1"

    # empty bundle list: [] on both paths, not ['']
    assert native_sched.place_bundles(nodes, [], "PACK") == []
    assert place_bundles(nodes, [], "PACK") == place_bundles_py(nodes, [], "PACK")


def test_place_bundles_parity_property_on_grid_resources():
    """Property-style parity: on randomized grid-resource clusters (all
    values on the engine's 1e-4 fixed-point grid), native and Python
    place_bundles agree node-for-node across every strategy AND the
    agreed placement is actually feasible and honors the strategy
    (distinct nodes for STRICT_SPREAD, one node for STRICT_PACK, fits
    under sequential reservation) — equality alone would also pass on
    two identically-wrong engines."""
    from ray_tpu._private.common import res_fits, res_sub

    rng = random.Random(23)
    checked = placed = 0
    for trial in range(150):
        nodes = _rand_cluster(rng, rng.randint(1, 10))
        bundles = [
            {"CPU": rng.choice([0.5, 1, 2]),
             **({"TPU": rng.choice([1.0, 4.0])}
                if rng.random() < 0.3 else {})}
            for _ in range(rng.randint(1, 5))
        ]
        for strategy in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
            want = place_bundles_py(nodes, bundles, strategy)
            got = native_sched.place_bundles(nodes, bundles, strategy)
            assert got == want, (
                f"trial {trial} {strategy}: native={got} py={want}"
            )
            checked += 1
            if got is None:
                continue
            placed += 1
            by_id = {n.node_id: n for n in nodes}
            avail = {n.node_id: dict(n.resources_available)
                     for n in nodes if n.alive}
            for nid, b in zip(got, bundles):
                assert by_id[nid].alive, (trial, strategy, got)
                assert res_fits(b, avail[nid]), (trial, strategy, got)
                res_sub(avail[nid], b)
            if strategy == "STRICT_SPREAD":
                assert len(set(got)) == len(got)
            if strategy == "STRICT_PACK":
                assert len(set(got)) == 1
    assert checked == 600 and placed > 150


def test_torus_coord_labels_stay_on_the_native_path():
    """Topology labels in the canonical "x"-separated form must remain
    wire-encodable — a cluster advertising coords must NOT silently fall
    off the native pick_node fast path."""
    from ray_tpu._private import topology
    from ray_tpu._private.common import SchedulingStrategy, pick_node_py

    nodes = [
        NodeInfo(node_id=f"n{i}", host="h", port=0, store_dir="",
                 resources_total={"CPU": 4}, resources_available={"CPU": 4},
                 labels={topology.COORD_LABEL: topology.format_coord((i, 0)),
                         topology.DIMS_LABEL: topology.format_coord((4, 1))})
        for i in range(4)
    ]
    assert native_sched.encodable(nodes, {"CPU": 1}, SchedulingStrategy())
    strat = SchedulingStrategy()
    want = pick_node_py(nodes, {"CPU": 1}, strat, None, [0])
    assert native_sched.pick_node(
        nodes, {"CPU": 1}, strat, None, [0], 0.5) == want


def test_build_scheduling_converts_node_label_strategy():
    from ray_tpu.api import _build_scheduling
    from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy

    s = _build_scheduling({
        "scheduling_strategy": NodeLabelSchedulingStrategy(
            hard={"zone": "a"}, soft={"tpu-slice": "s0"}
        )
    })
    assert s.kind == "NODE_LABEL"
    assert s.labels_hard == {"zone": "a"}
    assert s.labels_soft == {"tpu-slice": "s0"}


def test_node_label_strategy_end_to_end():
    """NODE_LABEL picks only matching nodes; infeasible without a match."""
    nodes = [
        NodeInfo(node_id="n1", host="h", port=0, store_dir="",
                 resources_total={"CPU": 4}, resources_available={"CPU": 4},
                 labels={"zone": "a"}),
        NodeInfo(node_id="n2", host="h", port=0, store_dir="",
                 resources_total={"CPU": 4}, resources_available={"CPU": 4},
                 labels={"zone": "b"}),
    ]
    strat = SchedulingStrategy(kind="NODE_LABEL", labels_hard={"zone": "b"})
    assert native_sched.pick_node(nodes, {"CPU": 1}, strat, None, [0], 0.5) == "n2"
    strat = SchedulingStrategy(kind="NODE_LABEL", labels_hard={"zone": "z"})
    assert native_sched.pick_node(nodes, {"CPU": 1}, strat, None, [0], 0.5) is None
