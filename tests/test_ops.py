"""Attention kernel numerics (vs naive reference) on the virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops import (
    attention_reference,
    flash_attention,
    ring_self_attention,
)


def _qkv(b=2, h=2, s=64, d=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_scan_matches_reference(causal):
    q, k, v = _qkv()
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, impl="scan", block_k=16)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_scan_uneven_blocks():
    q, k, v = _qkv(s=48)
    ref = attention_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, impl="scan", block_k=32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_interpret_matches_reference(causal):
    q, k, v = _qkv(b=1, h=2, s=32, d=8)
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(
        q, k, v, causal=causal, impl="pallas_interpret",
        block_q=16, block_k=16,
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_scan_grad_matches_reference():
    q, k, v = _qkv(s=32)

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, impl="scan",
                               block_k=8).sum()

    g_ref = jax.grad(loss_ref)(q, k, v)
    g_out = jax.grad(loss_flash)(q, k, v)
    np.testing.assert_allclose(g_out, g_ref, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    from ray_tpu import parallel

    n = min(8, len(jax.devices()))
    mesh = parallel.create_mesh({"sp": n})
    q, k, v = _qkv(b=1, h=2, s=8 * n, d=16)
    ref = attention_reference(q, k, v, causal=causal)
    out = ring_self_attention(q, k, v, mesh, seq_axis="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_differentiable():
    from ray_tpu import parallel

    n = min(4, len(jax.devices()))
    mesh = parallel.create_mesh({"sp": n})
    q, k, v = _qkv(b=1, h=1, s=4 * n, d=8)

    def f_ring(q, k, v):
        return ring_self_attention(q, k, v, mesh, causal=True).sum()

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_ring = jax.grad(f_ring)(q, k, v)
    g_ref = jax.grad(f_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


def test_gpt2_sequence_parallel_step():
    """End-to-end: GPT-2 with ring attention trains under a data x sp mesh
    and matches the single-device step numerically."""
    import jax.numpy as jnp

    from ray_tpu import parallel
    from ray_tpu.models import gpt2

    n = min(8, len(jax.devices()))
    if n < 4:
        pytest.skip("needs 4+ devices")
    mesh = parallel.create_mesh({"data": 2, "sp": n // 2})

    cfg_sp = gpt2.GPT2Config.small_test(attention="ring", dtype=jnp.float32)
    cfg_1d = gpt2.GPT2Config.small_test(dtype=jnp.float32)
    model_sp, params, tx, opt_state = gpt2.make_train_state(
        cfg_sp, jax.random.PRNGKey(0)
    )
    model_1d = gpt2.GPT2(cfg_1d)

    batch = gpt2.synthetic_batch(jax.random.PRNGKey(1), 4, 32,
                                 cfg_sp.vocab_size)
    step_sp = gpt2.build_train_step_sp(model_sp, tx, mesh, donate=False)
    p2, o2, loss_sp = step_sp(params, opt_state, batch)

    loss_1d = gpt2.loss_fn(params, model_1d, batch)
    assert jnp.isfinite(loss_sp)
    np.testing.assert_allclose(
        float(loss_sp), float(loss_1d), rtol=2e-4, atol=2e-4
    )
    # one more step runs on the updated (still sharded) state
    _, _, loss2 = step_sp(p2, o2, batch)
    assert float(loss2) < float(loss_sp)


def test_flash_pallas_grad_matches_reference():
    """The Pallas path is differentiable end-to-end: forward saves the
    logsumexp and the backward runs real Pallas dq / dkv kernels."""
    q, k, v = _qkv(b=1, h=1, s=32, d=8)

    def loss_pallas(q, k, v):
        return flash_attention(q, k, v, causal=True, impl="pallas_interpret",
                               block_q=16, block_k=16).sum()

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_p = jax.grad(loss_pallas)(q, k, v)
    g_r = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(g_p, g_r, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_grad_nonuniform_cotangent(causal):
    """Non-uniform cotangents exercise the delta = rowsum(dO*O) term of the
    flash backward — a uniform .sum() cotangent can mask a wrong delta."""
    q, k, v = _qkv(b=1, h=2, s=64, d=8, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape, q.dtype)

    def loss_pallas(q, k, v):
        return (flash_attention(q, k, v, causal=causal,
                                impl="pallas_interpret",
                                block_q=16, block_k=16) * w).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) * w).sum()

    g_p = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gp, gr in zip(g_p, g_r):
        np.testing.assert_allclose(gp, gr, atol=2e-4, rtol=2e-4)


def test_flash_pallas_cross_lengths():
    """q_len != k_len (decode-style causal offset) with streamed KV blocks:
    the kv axis is a grid dimension, so K/V VMEM residency is one
    (block_k, d) tile regardless of sequence length."""
    b, h, d = 1, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(ks[0], (b, h, 16, d))
    k = jax.random.normal(ks[1], (b, h, 64, d))
    v = jax.random.normal(ks[2], (b, h, 64, d))
    for causal in (False, True):
        ref = attention_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal,
                              impl="pallas_interpret",
                              block_q=16, block_k=16)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss_pallas(q, k, v):
        return flash_attention(q, k, v, causal=True, impl="pallas_interpret",
                               block_q=16, block_k=16).sum()

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_p = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gp, gr in zip(g_p, g_r):
        np.testing.assert_allclose(gp, gr, atol=1e-4, rtol=1e-4)
