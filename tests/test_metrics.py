"""Runtime metrics plane (_private/metrics_core.py + the rebased
ray_tpu.util.metrics): hot-path counters/gauges/log2 histograms, the
metrics_snapshot RPC fan-out (worker -> raylet -> GCS), and the
Prometheus scrape surfaces.

Analog of ray: python/ray/tests/test_metrics_agent.py (every subsystem's
series shows up on the scrape endpoint) plus the src/ray/stats/ unit
tests (bucket placement, merge) — rebuilt over the dependency-free core.

Fast deterministic tests (unmarked beyond ``metrics``, tier-1): core
types, log2/explicit bucket placement, quantile estimation, cross-process
snapshot merge, Prometheus exposition validity, the user-metrics rebase,
and the rpcio accounting invariants (per-ATTEMPT latency vs exactly-once
logical counters through the idempotent-retry dedup path). Cluster tests
(slow): single-node scrape end-to-end with live-process GC, the 2-node
/metrics <250ms smoke, and the <2% self-measured overhead gate.
"""

import asyncio
import json
import re
import time
import urllib.request
import uuid

import pytest

from ray_tpu._private import faultsim, metrics_core
from ray_tpu._private.rpcio import (
    ConnectionLost,
    RpcServer,
    RpcTimeoutError,
    call_with_retries,
    connect,
)
from tests.conftest import wait_for_condition

pytestmark = pytest.mark.metrics


# ---------------------------------------------------------------------------
# unit: core types (standalone Registry — never the process-global one)
# ---------------------------------------------------------------------------
def test_counter_gauge_basics():
    r = metrics_core.Registry()
    c = r.counter("reqs", "requests")
    c.inc()
    c.inc(2.5)
    c.labels(route="/a").inc()
    g = r.gauge("depth")
    g.set(7)
    g.inc()
    g.dec(3)
    r.gauge("cb_depth").set_fn(lambda: 42.0)
    snap = r.snapshot()
    assert snap["reqs"]["type"] == "counter"
    by_tags = {tuple(sorted(s["tags"].items())): s["value"]
               for s in snap["reqs"]["series"]}
    assert by_tags[()] == 3.5
    assert by_tags[(("route", "/a"),)] == 1.0
    assert snap["depth"]["series"][0]["value"] == 5.0
    # callback gauges evaluate at snapshot time (zero hot-path cost)
    assert snap["cb_depth"]["series"][0]["value"] == 42.0
    # same name, same family object; conflicting type raises
    assert r.counter("reqs") is c or r.counter("reqs").name == "reqs"
    with pytest.raises(ValueError):
        r.gauge("reqs")


def test_lazy_default_child_no_spurious_series():
    """A labeled-only family must not emit an empty unlabeled series."""
    r = metrics_core.Registry()
    r.counter("labeled_only").labels(kind="x").inc()
    tags = [s["tags"] for s in r.snapshot()["labeled_only"]["series"]]
    assert tags == [{"kind": "x"}]


def test_histogram_log2_bucket_placement():
    """LATENCY scale: floor 1us, 26 buckets; bucket i holds values
    < floor * 2**i (index = int(v/floor).bit_length()), overflow clamps."""
    h = metrics_core.Histogram({}, scale=metrics_core.LATENCY)
    assert len(h._bounds) == 26 and h._bounds[0] == 1e-6
    cases = [
        (0.5e-6, 0),    # below the floor
        (1.5e-6, 1),    # [1us, 2us)
        (3e-6, 2),      # [2us, 4us)
        (1.0, 20),      # 2**20 us ~ 1.05s bucket
        (1e9, 26),      # way past 32s -> overflow bucket
    ]
    for v, want in cases:
        before = h._counts[want]
        h.record(v)
        assert h._counts[want] == before + 1, (v, want, h._counts)
    assert h.count() == len(cases)
    series = h._series()
    assert series["count"] == len(cases)
    assert series["sum"] == pytest.approx(sum(v for v, _ in cases))


def test_histogram_size_scale_and_explicit_boundaries():
    s = metrics_core.Histogram({}, scale=metrics_core.SIZE)
    s.record(1024)
    assert s._counts[11] == 1  # [1KiB, 2KiB)
    # explicit boundaries take the bisect path; le is inclusive
    e = metrics_core.Histogram({}, boundaries=[1.0, 10.0, 100.0])
    for v, want in [(0.5, 0), (1.0, 0), (5, 1), (10.0, 1), (99, 2),
                    (1e6, 3)]:
        before = e._counts[want]
        e.record(v)
        assert e._counts[want] == before + 1, (v, want)


def test_hist_quantiles_bounded_error():
    """Log2 buckets keep the quantile estimate within a factor of 2 of
    the true value, and the estimates are monotone in q."""
    h = metrics_core.Histogram({}, scale=metrics_core.LATENCY)
    for _ in range(90):
        h.record(100e-6)
    for _ in range(10):
        h.record(10e-3)
    qs = metrics_core.hist_quantiles(h._series(), (0.5, 0.95, 0.99))
    assert qs[0.5] <= qs[0.95] <= qs[0.99]
    assert 50e-6 <= qs[0.5] <= 200e-6
    assert 5e-3 <= qs[0.99] <= 20e-3
    # empty histogram -> zeros, no division error
    empty = metrics_core.Histogram({}, scale=metrics_core.LATENCY)
    assert metrics_core.hist_quantiles(empty._series())[0.5] == 0.0


def test_enable_flag_gates_recording():
    r = metrics_core.Registry()
    c = r.counter("gated")
    h = r.histogram("gated_h")
    calls0 = metrics_core.record_calls()
    metrics_core.set_enabled(False)
    try:
        c.inc()
        h.record(1e-3)
        assert c.default.value() == 0.0
        assert h.default.count() == 0
        assert metrics_core.record_calls() == calls0
    finally:
        metrics_core.set_enabled(True)
    c.inc()
    h.record(1e-3)
    assert c.default.value() == 1.0
    assert metrics_core.record_calls() == calls0 + 2


# ---------------------------------------------------------------------------
# unit: cross-process merge (the raylet/GCS fan-out layers)
# ---------------------------------------------------------------------------
def _two_process_snapshots():
    r1, r2 = metrics_core.Registry(), metrics_core.Registry()
    for r, n in ((r1, 3), (r2, 4)):
        c = r.counter("ops_total")
        c.labels(verb="put").inc(n)
        h = r.histogram("lat", scale=metrics_core.LATENCY)
        for i in range(n):
            h.record(1e-6 * (1 << i))
    r1.counter("ops_total").labels(verb="get").inc(7)  # only in r1
    r2.gauge("depth").set(5)                           # only in r2
    return r1.snapshot(), r2.snapshot()


def test_merge_snapshots_sums_and_buckets():
    s1, s2 = _two_process_snapshots()
    merged = metrics_core.merge_snapshots([s1, s2])
    ops = {tuple(sorted(s["tags"].items())): s["value"]
           for s in merged["ops_total"]["series"]}
    assert ops[(("verb", "put"),)] == 7.0  # 3 + 4
    assert ops[(("verb", "get"),)] == 7.0  # r1 only, carried through
    assert merged["depth"]["series"][0]["value"] == 5.0
    lat = merged["lat"]["series"][0]
    assert lat["count"] == 7
    # buckets merged elementwise: each process recorded one value per
    # power-of-two, the smaller set is a prefix of the larger
    per1 = s1["lat"]["series"][0]["buckets"]
    per2 = s2["lat"]["series"][0]["buckets"]
    assert lat["buckets"] == [a + b for a, b in zip(per1, per2)]
    assert lat["sum"] == pytest.approx(
        s1["lat"]["series"][0]["sum"] + s2["lat"]["series"][0]["sum"])
    # merge is associative enough for the fan-out: (s1+s2)+s1 == 2*s1+s2
    again = metrics_core.merge_snapshots([merged, s1])
    assert again["lat"]["series"][0]["count"] == 10


def test_merge_drops_mismatched_boundaries():
    r1, r2 = metrics_core.Registry(), metrics_core.Registry()
    r1.histogram("h", boundaries=[1, 2, 4]).record(1.5)
    r2.histogram("h", boundaries=[1, 10]).record(1.5)
    merged = metrics_core.merge_snapshots([r1.snapshot(), r2.snapshot()])
    # first declaration wins; the conflicting dump is dropped whole
    s = merged["h"]["series"][0]
    assert s["boundaries"] == [1.0, 2.0, 4.0]
    assert s["count"] == 1


def test_summarize_shapes():
    s1, s2 = _two_process_snapshots()
    out = metrics_core.summarize(metrics_core.merge_snapshots([s1, s2]))
    assert out["ops_total"]["type"] == "counter"
    lat = out["lat"]["series"][0]
    assert set(lat) >= {"count", "sum", "mean", "p50", "p95", "p99"}
    assert lat["count"] == 7 and lat["p50"] <= lat["p99"]


# ---------------------------------------------------------------------------
# unit: Prometheus text exposition validity
# ---------------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"            # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""  # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
    r" [-+0-9.eE]+(e[-+]?[0-9]+)?$|^.* \+?[Ii]nf$|^.* [Nn]a[Nn]$")


def assert_valid_prometheus_text(text: str):
    """Structural validation of the exposition: every line is a comment
    or a well-formed sample; histogram bucket counts are cumulative and
    the +Inf bucket equals _count."""
    assert text.endswith("\n")
    cum = {}        # (name, non-le labels) -> last cumulative count
    counts = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
        name = line.split("{")[0].split(" ")[0]
        value = float(line.rsplit(" ", 1)[1])
        labels = ""
        if "{" in line:
            labels = line[line.index("{") + 1:line.rindex("}")]
        if name.endswith("_bucket"):
            parts = [kv for kv in labels.split(",") if kv]
            le = [kv for kv in parts if kv.startswith('le="')][0]
            rest = ",".join(kv for kv in parts if not kv.startswith('le="'))
            key = (name, rest)
            assert value >= cum.get(key, 0.0), f"non-cumulative: {line!r}"
            cum[key] = value
            if le == 'le="+Inf"':
                counts[(name[:-len("_bucket")], rest)] = value
        elif name.endswith("_count"):
            base = name[: -len("_count")]
            if (base, labels) in counts:
                assert value == counts[(base, labels)], \
                    f"+Inf bucket != _count for {base}"
    return True


def test_render_metrics_valid_exposition():
    s1, s2 = _two_process_snapshots()
    merged = metrics_core.merge_snapshots([s1, s2])
    from ray_tpu.dashboard.prometheus import render_metrics

    text = render_metrics(metrics_core.snapshot_records(merged))
    assert_valid_prometheus_text(text)
    assert 'ops_total{verb="put"} 7.0' in text
    assert "# TYPE lat histogram" in text
    assert "lat_count 7" in text


# ---------------------------------------------------------------------------
# unit: user-metrics API rebased onto the core
# ---------------------------------------------------------------------------
def test_user_metrics_register_in_core_registry():
    from ray_tpu.util import metrics as um

    name = f"user_reqs_{uuid.uuid4().hex[:8]}"
    hname = f"user_lat_{uuid.uuid4().hex[:8]}"
    try:
        c = um.Counter(name, "user counter", tag_keys=("route",))
        c.inc(2, tags={"route": "/a"})
        with pytest.raises(ValueError):
            c.inc(1, tags={"bogus": "k"})
        with pytest.raises(ValueError):
            c.inc(-1)
        # no boundaries -> the pre-rebase default buckets, NOT the
        # runtime latency scale (user values are arbitrary magnitudes)
        assert um.Histogram(
            f"dflt_{hname}").boundaries == [0.1, 1, 10, 100, 1000]
        metrics_core.registry().unregister(f"dflt_{hname}")
        h = um.Histogram(hname, boundaries=[0.1, 1, 10])
        h.observe(0.5)
        snap = metrics_core.registry().snapshot()
        assert snap[name]["series"][0]["value"] == 2.0
        assert snap[name]["series"][0]["tags"] == {"route": "/a"}
        assert snap[hname]["series"][0]["count"] == 1
        assert snap[hname]["series"][0]["boundaries"] == [0.1, 1.0, 10.0]
        # default tags merge under declared keys
        c.set_default_tags({"route": "/b"})
        c.inc()
        by = {s["tags"]["route"]: s["value"]
              for s in metrics_core.registry().snapshot()[name]["series"]}
        assert by == {"/a": 2.0, "/b": 1.0}
    finally:
        metrics_core.registry().unregister(name)
        metrics_core.registry().unregister(hname)


# ---------------------------------------------------------------------------
# rpcio accounting invariants (in-process RpcServer, process-global
# registry — all assertions are deltas)
# ---------------------------------------------------------------------------
class _Handler:
    def __init__(self):
        self.count = 0

    def rpc_bump(self, conn, p):
        self.count += 1
        return self.count

    def rpc_echo(self, conn, p):
        return p

    async def rpc_hang(self, conn, p):
        await asyncio.sleep(60)


def _counter_value(name, **tags):
    dump = metrics_core.registry().snapshot().get(name)
    for s in (dump or {}).get("series", ()):
        if s["tags"] == tags:
            return s["value"]
    return 0.0


def _hist_count(name, **tags):
    dump = metrics_core.registry().snapshot().get(name)
    for s in (dump or {}).get("series", ()):
        if s["tags"] == tags:
            return s["count"]
    return 0


def test_rpc_latency_per_attempt_but_handled_once():
    """THE dedup-accounting invariant: a retried idempotent request
    records one latency observation per ATTEMPT while the logical
    rpc_handled_total counter counts the execution exactly once (the
    replay path answers from the idempotency cache without re-counting).
    """

    async def main():
        handler = _Handler()
        srv = RpcServer(handler)
        port = await srv.start()
        lat0 = _hist_count("rpc_request_latency_seconds", method="bump")
        handled0 = _counter_value("rpc_handled_total", method="bump")
        c1 = await connect("127.0.0.1", port, retries=3)
        r1 = await c1.request("bump", {}, timeout=10, idem=("tok-m", 1))
        await c1.close()
        # retry on a FRESH connection, as a real post-connection-loss
        # retry would: replayed result, no second execution
        c2 = await connect("127.0.0.1", port, retries=3)
        try:
            r2 = await c2.request("bump", {}, timeout=10, idem=("tok-m", 1))
            assert (r1, r2) == (1, 1) and handler.count == 1
            lat1 = _hist_count("rpc_request_latency_seconds", method="bump")
            handled1 = _counter_value("rpc_handled_total", method="bump")
            assert lat1 - lat0 == 2, "each attempt records latency"
            assert handled1 - handled0 == 1, \
                "deduped retry must not double-count the logical request"
        finally:
            await c2.close()
            await srv.stop()

    asyncio.run(main())


def test_rpc_timeout_and_retry_and_fault_counters():
    """Deadline hits bump rpc_request_timeouts_total; call_with_retries
    re-attempts bump rpc_retries_total; injected faults are metered by
    kind in rpc_faults_injected_total."""

    async def main():
        srv = RpcServer(_Handler())
        port = await srv.start()
        to0 = _counter_value("rpc_request_timeouts_total", method="hang")
        rt0 = _counter_value("rpc_retries_total", method="echo")
        dr0 = _counter_value("rpc_faults_injected_total", kind="drop")
        conn = await connect("127.0.0.1", port, retries=3)
        state = {"conn": conn}

        async def get_conn():
            # drop faults sever the connection mid-frame; real retry
            # loops redial, so this one does too
            if state["conn"] is None or state["conn"].closed:
                state["conn"] = await connect("127.0.0.1", port, retries=3)
            return state["conn"]

        try:
            with pytest.raises(RpcTimeoutError):
                await conn.request("hang", {}, timeout=0.2)
            assert _counter_value(
                "rpc_request_timeouts_total", method="hang") - to0 == 1
            faultsim.install("echo:drop:1.0:7")
            try:
                with pytest.raises(ConnectionLost):
                    await call_with_retries(
                        get_conn, "echo", {"x": 1}, timeout=0.2,
                        attempts=3, base_delay=0.01)
            finally:
                faultsim.clear()
            assert _counter_value(
                "rpc_retries_total", method="echo") - rt0 == 2
            assert _counter_value(
                "rpc_faults_injected_total", kind="drop") - dr0 == 3
        finally:
            faultsim.clear()
            if state["conn"] is not None:
                await state["conn"].close()
            await srv.stop()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# cluster: scrape end-to-end (single node, shared fixture)
# ---------------------------------------------------------------------------
def test_cluster_scrape_end_to_end(ray_start_regular):
    """One GCS fan-out scrape returns runtime AND user metrics merged:
    rpcio latency histograms, raylet queue gauges, object-store size
    histograms, and a driver-side user Counter — all in one snapshot,
    and the Prometheus rendering of it is structurally valid."""
    import ray_tpu
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.util import metrics as um
    from ray_tpu.util import state

    name = f"e2e_user_total_{uuid.uuid4().hex[:8]}"
    c = um.Counter(name, "e2e", tag_keys=("route",))
    c.inc(3, tags={"route": "/x"})

    @ray_tpu.remote
    def nop():
        return 1

    assert sum(ray_tpu.get([nop.remote() for _ in range(20)])) == 20
    # plain tasks ride direct worker leases past the raylet scheduler;
    # push one burst through the raylet-routed path so the placement
    # histogram sees queue->dispatch transitions
    GLOBAL_CONFIG.update({"direct_task_leases": False})
    try:
        assert sum(ray_tpu.get([nop.remote() for _ in range(10)])) == 10
    finally:
        GLOBAL_CONFIG.update({"direct_task_leases": True})
    # past max_direct_call_object_size so the put hits the shm store
    ray_tpu.get(ray_tpu.put(b"z" * (256 * 1024)))
    try:
        snap = um.cluster_snapshot()
        merged = snap["merged"]
        roles = {p.get("role") for p in snap["processes"]
                 if not p.get("error")}
        assert {"gcs", "raylet", "driver"} <= roles  # workers via raylet
        assert snap.get("record_calls", 0) > 0
        # runtime series from three different subsystems
        lat = merged["rpc_request_latency_seconds"]
        assert any(s["count"] > 0 for s in lat["series"])
        assert any(s["tags"].get("node")
                   for s in merged["raylet_ready_queue_depth"]["series"])
        # BOTH dispatch paths stamp placement latency now: the raylet's
        # ready->dispatch series AND the driver-side direct-lease
        # enqueue->push series, split by the path label
        plat = merged["raylet_task_placement_latency_seconds"]
        paths = {s["tags"].get("path")
                 for s in plat["series"] if s["count"] > 0}
        assert {"raylet", "direct"} <= paths, paths
        assert any(s["count"] > 0
                   for s in merged["object_store_put_bytes"]["series"])
        assert merged["worker_task_run_seconds"]["series"]
        # the user counter rides the SAME scrape
        assert merged[name]["series"][0]["value"] == 3.0
        # summary + exposition over the same snapshot
        summary = state.metrics_summary()
        s = summary["rpc_request_latency_seconds"]["series"][0]
        assert s["count"] > 0 and 0 < s["p50"] <= s["p99"]
        text = um.prometheus_text(merged)
        assert_valid_prometheus_text(text)
        assert "rpc_request_latency_seconds_bucket" in text
        assert name in text
        # monotonic *_total series expose TYPE counter (rate() contract)
        assert "# TYPE raylet_tasks_dispatched_total counter" in text
        # list_metrics reflects LIVE processes and does not accumulate
        a = um.list_metrics()
        b = um.list_metrics()
        assert len(a[name]) == len(b[name]) == 1
        assert a[name][0]["role"] == "driver"
    finally:
        metrics_core.registry().unregister(name)


def test_dead_process_metrics_drop_from_scrape(ray_start_regular):
    """The KV-GC satellite, by construction: a killed actor's process
    stops answering the scrape, so its user metric disappears from
    list_metrics() instead of accumulating forever."""
    import ray_tpu
    from ray_tpu.util import metrics as um

    name = f"gc_actor_total_{uuid.uuid4().hex[:8]}"

    @ray_tpu.remote
    class M:
        def __init__(self, name):
            from ray_tpu.util.metrics import Counter

            self.c = Counter(name, "dies with the actor")
            self.name = name

        def bump(self):
            self.c.inc()
            return 1

    a = M.remote(name)
    assert ray_tpu.get(a.bump.remote()) == 1
    wait_for_condition(lambda: name in um.list_metrics(), timeout=15)
    ray_tpu.kill(a)
    wait_for_condition(lambda: name not in um.list_metrics(), timeout=30)


def test_dashboard_metrics_endpoints(ray_start_regular):
    """/metrics (Prometheus text), /api/metrics?format=json (summary),
    and the /api/v0/metrics_history ring the SPA sparklines read."""
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    GLOBAL_CONFIG.update({"metrics_history_interval_s": 0.5})
    port = start_dashboard()
    base = f"http://127.0.0.1:{port}"
    try:
        text = urllib.request.urlopen(base + "/metrics", timeout=30).read(
        ).decode()
        assert_valid_prometheus_text(text)
        assert "rpc_request_latency_seconds_bucket" in text
        assert "ray_tpu_node_count" in text  # synthesized built-ins merge in
        summary = json.loads(urllib.request.urlopen(
            base + "/api/metrics?format=json", timeout=30).read())
        assert "rpc_request_latency_seconds" in summary
        text2 = urllib.request.urlopen(
            base + "/api/metrics", timeout=30).read().decode()
        assert_valid_prometheus_text(text2)

        def ring_filled():
            hist = json.loads(urllib.request.urlopen(
                base + "/api/v0/metrics_history", timeout=30).read())
            return (len(hist) >= 2
                    and "rpc_request_latency_seconds" in hist[-1]["metrics"]
                    and hist[-1]["ts"] > hist[0]["ts"])

        wait_for_condition(ring_filled, timeout=30)
    finally:
        stop_dashboard()
        GLOBAL_CONFIG.update({"metrics_history_interval_s": 5.0})


# ---------------------------------------------------------------------------
# cluster: 2-node smoke + overhead gate (slow lane)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_two_node_scrape_smoke(ray_start_cluster):
    """The acceptance scrape: a 2-node cluster's merged /metrics is valid
    Prometheus text carrying per-node raylet series from BOTH nodes, and
    the node agent's /metrics answers in <250ms."""
    import ray_tpu
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def nop():
        return 1

    # touch both nodes so every raylet has dispatch activity
    assert sum(ray_tpu.get([nop.remote() for _ in range(40)])) == 40
    from ray_tpu.util import metrics as um

    merged = um.cluster_snapshot()["merged"]
    nodes = {s["tags"].get("node")
             for s in merged["raylet_worker_pool_size"]["series"]}
    assert len(nodes) == 2, f"expected both raylets in the merge: {nodes}"

    port = start_dashboard()
    try:
        url = f"http://127.0.0.1:{port}/metrics"
        urllib.request.urlopen(url, timeout=60).read()  # warm the path
        t0 = time.perf_counter()
        text = urllib.request.urlopen(url, timeout=60).read().decode()
        elapsed = time.perf_counter() - t0
        assert_valid_prometheus_text(text)
        assert "raylet_task_placement_latency_seconds_bucket" in text
        assert elapsed < 0.25, f"/metrics took {elapsed * 1e3:.0f}ms"
    finally:
        stop_dashboard()


@pytest.mark.slow
def test_metrics_overhead_under_2_percent(ray_start_regular_fn):
    """The bench.py acceptance gate, as a test: self-measured
    instrumentation share of the sync-task hot path < 2% (paired with
    the profiler gate's posture — the end-to-end throughput delta is
    reported only, this box's A/A noise swamps it)."""
    from ray_tpu.util.metrics import metrics_overhead_bench

    out = metrics_overhead_bench(batch=150, repeat=3, rounds=2)
    assert out["events_in_window"] > 0, "instrumentation must be live"
    assert out["self_fraction"] < 0.02, out
