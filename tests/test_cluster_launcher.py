"""Cluster launcher e2e (ray parity: `ray up/down cluster.yaml`,
autoscaler/_private/commands.py): a YAML with a head + one fake v5e
slice comes up (head + monitor processes, worker raylet launched by the
FakeTpuPodProvider to satisfy min_workers), status shows both nodes,
down tears everything down."""

import os
import time

import pytest
import yaml

from ray_tpu.autoscaler import commands
from ray_tpu.autoscaler.commands import (
    ClusterConfigError,
    validate_config,
)


def _base_cfg(name):
    return {
        "cluster_name": name,
        "provider": {"type": "fake_tpu_pod"},
        "head_node": {"resources": {"CPU": 2}},
        "available_node_types": {
            "v5e_4": {
                "resources": {"TPU": 4, "CPU": 2},
                "min_workers": 1,
                "max_workers": 2,
            },
        },
    }


def test_validate_config_rejects_bad_shapes():
    with pytest.raises(ClusterConfigError, match="cluster_name"):
        validate_config({"provider": {"type": "mock"}})
    with pytest.raises(ClusterConfigError, match="provider.type"):
        validate_config({"cluster_name": "x", "provider": {}})
    with pytest.raises(ClusterConfigError, match="unknown provider.type"):
        validate_config({"cluster_name": "x",
                         "provider": {"type": "aws"}})
    with pytest.raises(ClusterConfigError, match="resources"):
        validate_config({"cluster_name": "x",
                         "provider": {"type": "mock"},
                         "available_node_types": {"t": {}}})
    with pytest.raises(ClusterConfigError, match="min_workers"):
        validate_config({"cluster_name": "x",
                         "provider": {"type": "mock"},
                         "available_node_types": {
                             "t": {"resources": {"CPU": 1},
                                   "min_workers": 3, "max_workers": 1}}})
    with pytest.raises(ClusterConfigError, match="project"):
        validate_config({"cluster_name": "x",
                         "provider": {"type": "tpu_pod"}})
    validate_config(_base_cfg("ok"))


@pytest.fixture
def launcher_env(tmp_path, monkeypatch):
    monkeypatch.setattr(commands, "_STATE_DIR", str(tmp_path / "clusters"))
    cfg = _base_cfg("launchertest")
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(cfg))
    yield str(path)
    # belt-and-braces teardown if the test failed mid-way
    try:
        commands.teardown_cluster(str(path))
    except Exception:
        pass


def test_up_status_down_end_to_end(launcher_env):
    path = launcher_env
    state = commands.create_or_update_cluster(path)
    assert commands._pid_alive(state["head_pid"])
    assert commands._pid_alive(state["monitor_pid"])

    # idempotent re-up: same head adopted, no second monitor
    state2 = commands.create_or_update_cluster(path)
    assert state2["head_pid"] == state["head_pid"]
    assert state2["monitor_pid"] == state["monitor_pid"]

    # the monitor's first passes must launch the min_workers=1 fake slice;
    # status then shows head + worker with the slice's TPU resources
    deadline = time.time() + 90
    seen = []
    while time.time() < deadline:
        out = commands.cluster_status(path)
        seen = [n for n in out["nodes"] if n.get("alive", True)]
        if len(seen) >= 2:
            break
        time.sleep(2)
    assert len(seen) >= 2, f"worker slice never joined: {seen}"
    tpu_nodes = [
        n for n in seen
        if (n.get("resources_total") or {}).get("TPU", 0) >= 4
    ]
    assert tpu_nodes, f"no TPU slice node in {seen}"
    assert any(
        (n.get("labels") or {}).get("tpu-slice") == "v5e_4"
        for n in tpu_nodes
    )

    head_pids = list(state["head_pids"])
    mpid = state["monitor_pid"]
    commands.teardown_cluster(path)
    assert not commands._pid_alive(mpid)
    for pid in head_pids:
        assert not commands._pid_alive(pid)
    # state file dropped: status reports not-started
    out = commands.cluster_status(path)
    assert out["up"] is False
