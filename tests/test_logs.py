"""Cluster log plane tests.

Analog of ray: python/ray/tests/test_logging.py (driver log streaming,
dedup) + the `ray logs` state-API tests — plus the TPU-native
differentiator: per-task byte-range attribution (executors stamp exact
(log_file, start, end) spans into the task-event pipeline, so a task's
output is an offset read, never a grep).
"""

import json
import os
import subprocess
import sys
import time
import uuid

import pytest
import requests

import ray_tpu
from ray_tpu._private import logplane
from ray_tpu.util import state

pytestmark = pytest.mark.logs

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# pure units: dedup window
# ---------------------------------------------------------------------------

def test_dedup_first_immediate_then_repeated_suffix():
    d = logplane.LogDeduplicator(window_s=1.0, color=False)
    out = d.feed("(a pid=1) ", "same line", now=0.0)
    assert out == ["(a pid=1) same line"]
    # 7 more identical lines from other workers inside the window: silent
    for i in range(7):
        assert d.feed(f"(a pid={i}) ", "same line", now=0.1 * i) == []
    # window expires: ONE summary line with the [repeated Nx] suffix
    out = d.flush(now=5.0)
    assert out == ["(a pid=6) same line [repeated 7x]"]
    assert d.flush(now=9.0) == []  # nothing pending


def test_dedup_distinct_lines_pass_through_and_forced_flush():
    d = logplane.LogDeduplicator(window_s=10.0, color=False)
    assert d.feed("(p) ", "alpha", now=0.0) == ["(p) alpha"]
    assert d.feed("(p) ", "beta", now=0.1) == ["(p) beta"]
    assert d.feed("(p) ", "alpha", now=0.2) == []  # duplicate suppressed
    # forced flush (driver shutdown) drains summaries even mid-window
    assert d.flush(now=0.3, force=True) == ["(p) alpha [repeated 1x]"]


def test_dedup_expired_summaries_drain_before_new_lines():
    d = logplane.LogDeduplicator(window_s=1.0, color=False)
    d.feed("(p) ", "x", now=0.0)
    d.feed("(q) ", "x", now=0.5)
    out = d.feed("(r) ", "fresh", now=3.0)  # arrival past x's window
    assert out == ["(q) x [repeated 1x]", "(r) fresh"]


# ---------------------------------------------------------------------------
# pure units: length caps + span table
# ---------------------------------------------------------------------------

def test_truncate_line_caps_and_marks():
    raw, cut = logplane.truncate_line(b"x" * 100, 10)
    assert cut and raw.startswith(b"xxxxxxxxxx") and b"[truncated]" in raw
    raw, cut = logplane.truncate_line(b"short", 10)
    assert not cut and raw == b"short"


def test_span_table_closed_beats_open_and_prunes():
    t = logplane.SpanTable(history=8)
    # previous task's exact closed range [0, 100); next task's provisional
    # open starts early at 40 (raylet saw the file before buffers flushed)
    t.open_span("t2", "next_task", 40)
    t.close_span("t1", "prev_task", 0, 100)
    assert t.resolve(50) == "prev_task"   # closed (exact) wins
    assert t.resolve(120) == "next_task"  # past the closed range: open
    assert t.resolve(100) == "next_task"  # end is exclusive
    t.close_span("t2", "next_task", 100, 200)
    assert t.resolve(150) == "next_task"
    t.prune(200)  # tailer consumed everything
    assert t.resolve(50) is None
    t.discard("missing")  # no-op


def test_span_table_bounded_history():
    t = logplane.SpanTable(history=4)
    for i in range(20):
        t.close_span(f"t{i}", f"task{i}", i * 10, i * 10 + 10)
    assert len(t._closed) == 4
    assert t.resolve(195) == "task19"


# ---------------------------------------------------------------------------
# pure units: agent tail window scaling + range reads + name validation
# ---------------------------------------------------------------------------

def test_tail_window_scales_to_request(tmp_path):
    from ray_tpu.dashboard.agent import tail_file

    path = tmp_path / "big.out"
    lines = [f"line-{i:06d}" + "x" * 120 for i in range(5000)]
    path.write_bytes(b"\n".join(l.encode() for l in lines) + b"\n")
    # 2000 lines x ~130B ~= 260KB — past the old fixed 256KiB window
    out, start, end = tail_file(str(path), 2000)
    assert len(out) == 2000
    assert out[0] == lines[3000]   # exact, complete first line (not torn)
    assert out[-1] == lines[-1]
    assert end == path.stat().st_size


def test_tail_drops_torn_leading_line(tmp_path):
    from ray_tpu.dashboard.agent import tail_file

    path = tmp_path / "torn.out"
    lines = [f"L{i}:" + "y" * 997 for i in range(200)]  # ~1KB lines
    path.write_bytes(b"\n".join(l.encode() for l in lines) + b"\n")
    out, start, _ = tail_file(str(path), 3)
    assert out == lines[-3:]
    # the returned start offset points at a line boundary
    with open(path, "rb") as f:
        f.seek(max(0, start - 1))
        assert start == 0 or f.read(1) == b"\n"


def test_range_read_exact_bytes(tmp_path):
    from ray_tpu.dashboard.agent import read_range

    path = tmp_path / "r.out"
    path.write_bytes(b"aaaa\nbbbb\ncccc\n")
    assert read_range(str(path), 5, 10) == b"bbbb\n"
    assert read_range(str(path), 10, 10_000) == b"cccc\n"  # clamped to EOF


def test_bad_log_filenames_rejected():
    from ray_tpu.dashboard.agent import safe_log_name

    assert safe_log_name("worker-abc-1.out")
    for bad in ("../secret", "a/b.out", ".hidden", "", "..\\win", "/etc/pw"):
        assert not safe_log_name(bad), bad


# ---------------------------------------------------------------------------
# pure unit: raylet tailer (attribution segs, per-tick byte budget)
# ---------------------------------------------------------------------------

class _FakeProc:
    pid = 4242


class _FakeWorker:
    def __init__(self, path):
        self.proc = _FakeProc()
        self.job_id = b"\x01\x02"
        self.log_path = str(path)
        self.log_offset = 0
        self.log_partial = b""
        self.log_spans = logplane.SpanTable()
        self.log_name = None


def test_tail_worker_log_attributes_by_offset(tmp_path):
    from ray_tpu._private.raylet import _tail_worker_log

    path = tmp_path / "w.out"
    data = b"pre\nfrom-task-a\nfrom-task-a2\nafter\n"
    path.write_bytes(data)
    w = _FakeWorker(path)
    a_start = data.index(b"from-task-a")
    a_end = data.index(b"after")
    w.log_spans.close_span("ta", "task_a", a_start, a_end)
    # first look holds: the batch starts with an unresolved fresh line
    # ("pre") and worker-side task events are debounced, so unattributed
    # fresh lines wait one tail tick for their span to land
    entry, stats = _tail_worker_log(w)
    assert entry is None and stats["lines"] == 0
    entry, stats = _tail_worker_log(w)
    assert stats["lines"] == 4 and stats["truncated"] == 0
    assert entry["pid"] == 4242
    assert entry["segs"] == [
        [None, ["pre"]],
        ["task_a", ["from-task-a", "from-task-a2"]],
        [None, ["after"]],
    ]
    # nothing new -> no entry
    entry, stats = _tail_worker_log(w)
    assert entry is None and stats["lines"] == 0


def test_tail_worker_log_budget_and_truncation(tmp_path):
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg
    from ray_tpu._private.raylet import _tail_worker_log

    path = tmp_path / "chatty.out"
    path.write_bytes(b"\n".join(b"z" * 200 for _ in range(2000)) + b"\n")
    w = _FakeWorker(path)
    old_budget = cfg.log_publish_max_bytes
    old_cap = cfg.log_max_line_bytes
    try:
        cfg.update({"log_publish_max_bytes": 64 * 1024,
                    "log_max_line_bytes": 50})
        # first look holds the fresh unresolved batch (span-less lines
        # wait one tick); the second look publishes it
        entry, stats = _tail_worker_log(w)
        assert entry is None and stats["lines"] == 0
        entry, stats = _tail_worker_log(w)
        # bounded per tick: well under the whole file, lines length-capped
        assert 0 < stats["lines"] < 2000
        assert stats["truncated"] == stats["lines"]
        assert all(len(l) < 80 for _, ls in entry["segs"] for l in ls)
        first_batch = stats["lines"]
        # the next tick continues where the budget stopped
        entry2, stats2 = _tail_worker_log(w)
        assert stats2["lines"] > 0
        assert w.log_offset <= path.stat().st_size
        assert first_batch + stats2["lines"] <= 2000
    finally:
        cfg.update({"log_publish_max_bytes": old_budget,
                    "log_max_line_bytes": old_cap})


def test_lint_print_forbids_bare_prints(tmp_path):
    """CI satellite: scripts/lint_print.py passes on ray_tpu/_private and
    fails on a violating tree."""
    script = os.path.join(REPO_ROOT, "scripts", "lint_print.py")
    r = subprocess.run([sys.executable, script], capture_output=True,
                       text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    bad = tmp_path / "mod.py"
    bad.write_text('print("oops")\n'
                   'print("fine", file=__import__("sys").stderr)\n'
                   'print("annotated")  # lint: allow-print\n')
    r = subprocess.run([sys.executable, script, str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "mod.py:1" in r.stdout and "mod.py:2" not in r.stdout


# ---------------------------------------------------------------------------
# cluster: attribution offsets, state/CLI/dashboard surfaces, streaming
# ---------------------------------------------------------------------------

def _wait_for(fn, timeout=45.0, interval=0.3):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
        except Exception as e:  # surfaces flaky probes on timeout
            last = e
        time.sleep(interval)
    raise TimeoutError(f"condition not met (last: {last!r})")


def _wait_agents():
    """Every alive node's agent answers its log listing."""
    def probe():
        listing = state.list_logs()
        return listing and all(
            isinstance(files, list) for files in listing.values()
        ) and listing or None
    return _wait_for(probe, timeout=60)


def test_task_output_attributed_by_exact_offsets(ray_start_regular):
    """get_log(task_id) returns exactly that task's lines — resolved via
    the executor-stamped byte range, so a sibling task's output printed
    into the SAME worker log never bleeds in."""
    mark_a, mark_b = uuid.uuid4().hex[:12], uuid.uuid4().hex[:12]

    @ray_tpu.remote
    def shout(mark, n):
        for i in range(n):
            print(f"shout-{mark}-{i}")
        return mark

    ref_a = shout.remote(mark_a, 3)
    assert ray_tpu.get(ref_a, timeout=60) == mark_a
    ref_b = shout.remote(mark_b, 2)
    assert ray_tpu.get(ref_b, timeout=60) == mark_b
    _wait_agents()
    tid_a = ref_a.id().task_id().hex()
    tid_b = ref_b.id().task_id().hex()

    lines_a = _wait_for(lambda: state.get_log(task_id=tid_a))
    assert [l for l in lines_a if "shout-" in l] == \
        [f"shout-{mark_a}-{i}" for i in range(3)]
    assert not any(mark_b in l for l in lines_a)
    lines_b = _wait_for(lambda: state.get_log(task_id=tid_b))
    assert [l for l in lines_b if "shout-" in l] == \
        [f"shout-{mark_b}-{i}" for i in range(2)]
    assert not any(mark_a in l for l in lines_b)


def test_list_logs_and_get_log_filename(ray_start_regular):
    listing = _wait_agents()
    files = [f["file"] for files in listing.values() for f in files]
    worker_logs = [f for f in files if f.startswith("worker-")]
    assert worker_logs, files
    lines = state.get_log(filename=worker_logs[0], tail=5)
    assert isinstance(lines, list)
    with pytest.raises(ValueError):
        state.get_log(filename="no-such-file.out")
    with pytest.raises(ValueError):
        state.get_log()  # exactly one selector required


def test_actor_log_via_attribution(ray_start_regular):
    mark = uuid.uuid4().hex[:12]

    @ray_tpu.remote
    class Chatter:
        def speak(self, i):
            print(f"actor-{mark}-{i}")
            return i

    c = Chatter.remote()
    assert ray_tpu.get(c.speak.remote(1), timeout=60) == 1
    _wait_agents()
    from ray_tpu._private.ids import ActorID

    aid = ActorID(c._actor_id).hex()
    lines = _wait_for(lambda: [
        l for l in state.get_log(actor_id=aid, tail=200)
        if f"actor-{mark}-1" in l
    ])
    assert lines


def test_driver_stream_prefix_carries_task_name(ray_start_regular, capfd):
    mark = uuid.uuid4().hex[:12]

    @ray_tpu.remote
    def named_shouter():
        print(f"stream-{mark}")
        return 1

    assert ray_tpu.get(named_shouter.remote(), timeout=60) == 1
    seen = ""
    deadline = time.time() + 20
    while time.time() < deadline:
        out = capfd.readouterr()
        seen += out.out + out.err
        if f"stream-{mark}" in seen:
            break
        time.sleep(0.2)
    line = next(l for l in seen.splitlines() if f"stream-{mark}" in l)
    # (<TaskName> pid=<pid> node=<id8>) prefix, attributed by offset span
    assert "named_shouter" in line and "pid=" in line and "node=" in line


def test_finished_event_carries_log_span(ray_start_regular):
    mark = uuid.uuid4().hex[:12]

    @ray_tpu.remote
    def spanner():
        print(f"span-{mark}")
        return 1

    ref = spanner.remote()
    assert ray_tpu.get(ref, timeout=60) == 1
    tid = ref.id().task_id().hex()

    def finished_ev():
        for ev in state.list_task_events(limit=100_000):
            if ev.get("task_id") == tid and ev.get("state") == "FINISHED":
                return ev
        return None

    ev = _wait_for(finished_ev)
    assert ev.get("log_file", "").startswith("worker-")
    assert isinstance(ev.get("log_start"), int)
    assert ev.get("log_end", 0) > ev["log_start"]
    # the recorded range really contains the printed bytes
    assert ev["log_end"] - ev["log_start"] >= len(f"span-{mark}\n")


def test_dashboard_logs_endpoints(ray_start_regular):
    from ray_tpu.dashboard.head import start_dashboard, stop_dashboard

    mark = uuid.uuid4().hex[:12]

    @ray_tpu.remote
    def api_shout():
        print(f"api-{mark}")
        return 1

    ref = api_shout.remote()
    assert ray_tpu.get(ref, timeout=60) == 1
    _wait_agents()
    tid = ref.id().task_id().hex()
    port = start_dashboard()
    base = f"http://127.0.0.1:{port}/api/v0"
    try:
        listing = requests.get(f"{base}/logs", timeout=30).json()
        assert listing and all(isinstance(v, list) for v in listing.values())
        node_id, files = next(iter(listing.items()))
        fname = next(f["file"] for f in files
                     if f["file"].startswith("worker-"))
        tail = requests.get(f"{base}/logs/tail", params={
            "file": fname, "lines": 10, "node_id": node_id,
        }, timeout=30).json()
        assert "lines" in tail
        # bad names bounce before touching the filesystem
        r = requests.get(f"{base}/logs/tail",
                         params={"file": "../secret"}, timeout=30)
        assert r.json().get("error")
        task = _wait_for(lambda: (lambda p: p if p.get("lines") else None)(
            requests.get(f"{base}/logs/task", params={"task_id": tid},
                         timeout=30).json()))
        assert any(f"api-{mark}" in l for l in task["lines"])
    finally:
        stop_dashboard()
