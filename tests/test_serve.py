"""Serve tests (analog of ray: python/ray/serve/tests/)."""

import time

import pytest
import requests

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    ray_tpu.init(num_cpus=4)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _url(path):
    return f"http://127.0.0.1:{serve.http_port()}{path}"


def test_basic_deploy_http_and_handle(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, request: serve.Request):
            return {"path": request.path, "q": request.query.get("v")}

        def direct(self, x):
            return x + 1

    handle = serve.run(Echo.bind(), name="echo", route_prefix="/echo")
    r = requests.get(_url("/echo"), params={"v": "5"}, timeout=30)
    assert r.status_code == 200
    assert r.json() == {"path": "/echo", "q": "5"}
    assert handle.direct.remote(41).result(timeout_s=30) == 42
    serve.delete("echo")


def test_function_deployment(serve_cluster):
    @serve.deployment
    def square(request: serve.Request):
        return {"out": int(request.query["x"]) ** 2}

    serve.run(square.bind(), name="fn", route_prefix="/sq")
    r = requests.get(_url("/sq"), params={"x": "9"}, timeout=30)
    assert r.json() == {"out": 81}
    serve.delete("fn")


def test_composition_and_options(serve_cluster):
    @serve.deployment
    class Child:
        def __call__(self, x):
            return x * 10

    @serve.deployment
    class Parent:
        def __init__(self, child):
            self.child = child

        def __call__(self, request: serve.Request):
            return self.child.remote(int(request.query["x"])).result(
                timeout_s=30
            )

    big_child = Child.options(num_replicas=2)
    serve.run(Parent.bind(big_child.bind()), name="comp",
              route_prefix="/comp")
    r = requests.get(_url("/comp"), params={"x": "4"}, timeout=30)
    assert r.json() == 40
    st = serve.status()["comp"]["deployments"]
    assert st["Child"]["running_replicas"] == 2
    serve.delete("comp")


def test_post_json_body(serve_cluster):
    @serve.deployment
    class Adder:
        def __call__(self, request: serve.Request):
            payload = request.json()
            return {"sum": payload["a"] + payload["b"]}

    serve.run(Adder.bind(), name="adder", route_prefix="/add")
    r = requests.post(_url("/add"), json={"a": 2, "b": 3}, timeout=30)
    assert r.json() == {"sum": 5}
    serve.delete("adder")


def test_batching(serve_cluster):
    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        async def __call__(self, items):
            # whole batch processed at once
            return [{"v": i, "batch": len(items)} for i in items]

    handle = serve.run(Batched.bind(), name="batched", route_prefix="/b")
    futs = [handle.remote(i) for i in range(4)]
    outs = [f.result(timeout_s=30) for f in futs]
    assert sorted(o["v"] for o in outs) == [0, 1, 2, 3]
    assert any(o["batch"] > 1 for o in outs)
    serve.delete("batched")


def test_multiplexed_models(serve_cluster):
    @serve.deployment
    class MultiModel:
        @serve.multiplexed(max_num_models_per_replica=2)
        async def load(self, model_id: str):
            return {"id": model_id, "loaded_at": time.time()}

        async def __call__(self, model_id: str):
            m = await self.load(model_id)
            return m["id"]

    handle = serve.run(MultiModel.bind(), name="mm", route_prefix="/mm")
    assert handle.remote("a").result(timeout_s=30) == "a"
    assert handle.remote("b").result(timeout_s=30) == "b"
    assert handle.remote("a").result(timeout_s=30) == "a"
    serve.delete("mm")


def test_autoscaling_scales_up(serve_cluster):
    @serve.deployment(
        autoscaling_config={
            "min_replicas": 1, "max_replicas": 3,
            "target_ongoing_requests": 1, "upscale_delay_s": 0.1,
        },
    )
    class Slow:
        def __call__(self, _request=None):
            time.sleep(1.5)
            return "done"

    handle = serve.run(Slow.bind(), name="auto", route_prefix="/auto")
    assert serve.status()["auto"]["deployments"]["Slow"][
        "running_replicas"] == 1
    futs = [handle.remote() for _ in range(6)]
    deadline = time.time() + 30
    scaled = False
    while time.time() < deadline:
        n = serve.status()["auto"]["deployments"]["Slow"]["running_replicas"]
        if n > 1:
            scaled = True
            break
        time.sleep(0.25)
    assert scaled, "autoscaler never scaled up under load"
    for f in futs:
        assert f.result(timeout_s=60) == "done"
    serve.delete("auto")


def test_redeploy_updates_code(serve_cluster):
    def make(version):
        @serve.deployment(name="V")
        class V:
            def __call__(self, _request=None):
                return version

        return V

    serve.run(make("v1").bind(), name="ver", route_prefix="/ver")
    # str results render as plain text (dicts/lists as JSON)
    assert requests.get(_url("/ver"), timeout=30).text == "v1"
    serve.run(make("v2").bind(), name="ver", route_prefix="/ver")
    deadline = time.time() + 30
    while time.time() < deadline:
        r = requests.get(_url("/ver"), timeout=30)
        assert r.status_code == 200, r.text  # rolling update: no downtime
        if r.text == "v2":
            break
        time.sleep(0.2)
    assert requests.get(_url("/ver"), timeout=30).text == "v2"
    serve.delete("ver")


def test_unknown_route_404(serve_cluster):
    r = requests.get(_url("/definitely-not-a-route-xyz"), timeout=30)
    assert r.status_code == 404


def test_broken_replica_constructor_gives_up(serve_cluster):
    """A deployment whose __init__ always raises must not wedge the
    control loop (regression: infinite replica start retries)."""

    @serve.deployment
    class Broken:
        def __init__(self):
            raise RuntimeError("cannot construct")

        def __call__(self, _r=None):
            return "unreachable"

    with pytest.raises(RuntimeError, match="failed to become ready"):
        serve.run(Broken.bind(), name="broken", route_prefix="/broken")
    # other apps still deploy fine afterwards — the loop is not starved
    @serve.deployment
    def ok(_request):
        return "ok"

    serve.run(ok.bind(), name="okapp", route_prefix="/okapp")
    assert requests.get(_url("/okapp"), timeout=30).text == "ok"
    serve.delete("okapp")
    serve.delete("broken")
