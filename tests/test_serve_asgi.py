"""ASGI ingress: mounting an existing ASGI application (the FastAPI
shape — routers, path params, lifespan startup, custom status/headers) on
a serve deployment (ray parity: serve.api.ingress +
_private/http_proxy.py:395). fastapi isn't in this image, so the app
under test is a hand-rolled ASGI callable exercising the same protocol
surface FastAPI uses."""

import json

import pytest

import ray_tpu
from ray_tpu import serve


def _make_app():
    """Mini ASGI app: /items/{id} with query echo, /state showing lifespan
    startup ran, custom headers, JSON 404 fallback."""
    state = {"started": False}

    async def app(scope, receive, send):
        if scope["type"] == "lifespan":
            while True:
                msg = await receive()
                if msg["type"] == "lifespan.startup":
                    state["started"] = True
                    await send({"type": "lifespan.startup.complete"})
                elif msg["type"] == "lifespan.shutdown":
                    await send({"type": "lifespan.shutdown.complete"})
                    return
            return
        assert scope["type"] == "http"
        msg = await receive()
        body = msg.get("body", b"")
        path = scope["path"]
        qs = scope["query_string"].decode()

        async def reply(status, obj, headers=()):
            await send({
                "type": "http.response.start", "status": status,
                "headers": [(b"content-type", b"application/json"),
                            *headers],
            })
            await send({"type": "http.response.body",
                        "body": json.dumps(obj).encode()})

        if path.startswith("/items/") and scope["method"] == "GET":
            item_id = path.split("/")[2]
            await reply(200, {"item_id": item_id, "qs": qs,
                              "root_path": scope.get("root_path", "")},
                        headers=[(b"x-app", b"mini"),
                                 (b"set-cookie", b"session=abc"),
                                 (b"set-cookie", b"csrf=xyz")])
        elif path == "/state":
            await reply(200, {"started": state["started"]})
        elif path == "/echo" and scope["method"] == "POST":
            await reply(201, {"len": len(body)})
        else:
            await reply(404, {"detail": "Not Found"})

    return app


@pytest.fixture
def serve_cluster(ray_start_regular):
    serve.start()
    yield
    serve.shutdown()


def test_asgi_ingress_end_to_end(serve_cluster):
    import urllib.request

    app = _make_app()

    @serve.deployment
    @serve.ingress(app)
    class Api:
        pass

    serve.run(Api.bind(), name="api", route_prefix="/api")
    base = f"http://127.0.0.1:{serve.http_port()}"

    # path params + query string + root_path stripping
    with urllib.request.urlopen(base + "/api/items/42?q=hello") as r:
        assert r.status == 200
        assert r.headers["x-app"] == "mini"
        # duplicate headers must BOTH arrive (the multiple-Set-Cookie case)
        cookies = r.headers.get_all("set-cookie")
        assert cookies == ["session=abc", "csrf=xyz"], cookies
        out = json.loads(r.read())
    assert out["item_id"] == "42"
    assert out["qs"] == "q=hello"
    assert out["root_path"] == "/api"

    # lifespan startup hook ran before the first request
    with urllib.request.urlopen(base + "/api/state") as r:
        assert json.loads(r.read()) == {"started": True}

    # request body + non-200 status pass through
    req = urllib.request.Request(base + "/api/echo", data=b"x" * 10,
                                 method="POST")
    with urllib.request.urlopen(req) as r:
        assert r.status == 201
        assert json.loads(r.read()) == {"len": 10}

    # app-level 404 (with the app's body) — not the proxy's 404
    try:
        urllib.request.urlopen(base + "/api/missing")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert json.loads(e.read()) == {"detail": "Not Found"}


def test_query_string_fidelity_through_proxy(serve_cluster):
    """ADVICE item: the scope's query_string must be the WIRE form —
    duplicate parameters (?tag=a&tag=b) and percent-encoding previously
    collapsed through the parsed Dict[str, str] + urlencode round trip."""
    import urllib.request
    from urllib.parse import parse_qs

    app = _make_app()

    @serve.deployment
    @serve.ingress(app)
    class Api:
        pass

    serve.run(Api.bind(), name="qsapi", route_prefix="/qs")
    base = f"http://127.0.0.1:{serve.http_port()}"

    url = base + "/qs/items/7?tag=a&tag=b&q=a%2Fb%20c&empty="
    with urllib.request.urlopen(url) as r:
        out = json.loads(r.read())
    parsed = parse_qs(out["qs"], keep_blank_values=True)
    # duplicates survive (the dict round trip kept only the last value)
    assert parsed["tag"] == ["a", "b"], out["qs"]
    # percent-encoded reserved chars decode to the original value
    assert parsed["q"] == ["a/b c"], out["qs"]
    assert parsed["empty"] == [""], out["qs"]
    # and the raw string still carries both tag occurrences verbatim
    assert out["qs"].count("tag=") == 2, out["qs"]


def test_asgi_query_string_fallback_without_raw():
    """Hand-built Request envelopes (no proxy) still produce a usable
    query_string from the parsed dict."""
    import asyncio

    from ray_tpu.serve._common import Request
    from ray_tpu.serve.asgi import ASGIAppRunner

    seen = {}

    async def app(scope, receive, send):
        if scope["type"] == "lifespan":
            return
        seen["qs"] = scope["query_string"]
        await receive()
        await send({"type": "http.response.start", "status": 200,
                    "headers": []})
        await send({"type": "http.response.body", "body": b"ok"})

    runner = ASGIAppRunner(app)
    req = Request(method="GET", path="/x", query={"a": "1", "b": "2"})
    assert req.raw_query_string is None  # hand-built: no wire form
    resp = asyncio.run(runner(req))
    assert resp.status == 200
    assert seen["qs"] == b"a=1&b=2"
    # with the wire form present it wins, verbatim
    req2 = Request(method="GET", path="/x", query={"t": "b"},
                   raw_query_string="t=a&t=b")
    asyncio.run(runner(req2))
    assert seen["qs"] == b"t=a&t=b"


def test_asgi_ingress_composes_with_class_state(serve_cluster):
    """The decorated class's own __init__ still runs (the reference
    pattern: FastAPI routes defined on the class via app.get used with
    self-state; here we assert the instance exists alongside the app)."""
    import urllib.request

    inited = []

    async def app(scope, receive, send):
        if scope["type"] == "lifespan":
            return
        await receive()
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"text/plain")]})
        await send({"type": "http.response.body", "body": b"ok"})

    @serve.deployment
    @serve.ingress(app)
    class WithState:
        def __init__(self):
            inited.append(True)
            self.x = 7

    serve.run(WithState.bind(), name="ws", route_prefix="/ws")
    base = f"http://127.0.0.1:{serve.http_port()}"
    with urllib.request.urlopen(base + "/ws/") as r:
        assert r.read() == b"ok"
