"""util extras: ActorPool, Queue, multiprocessing.Pool, joblib, workflow.

Reference analogs: ray python/ray/tests/test_actor_pool.py, test_queue.py,
util/multiprocessing tests, workflow/tests.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


@ray_tpu.remote
class Doubler:
    def double(self, x):
        return 2 * x

    def slow_double(self, x):
        import time as _t

        _t.sleep(0.2 * x)
        return 2 * x


def test_actor_pool_map_ordered(ray_start_regular):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    assert list(pool.map(lambda a, v: a.double.remote(v), range(6))) == [
        0, 2, 4, 6, 8, 10,
    ]


def test_actor_pool_unordered_and_queueing(ray_start_regular):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    # More submissions than actors: excess queue and drain via returns.
    out = sorted(pool.map_unordered(lambda a, v: a.double.remote(v), range(5)))
    assert out == [0, 2, 4, 6, 8]
    assert pool.pop_idle() is not None


def test_queue_basics(ray_start_regular):
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    assert q.qsize() == 2 and q.full()
    with pytest.raises(Full):
        q.put(3, block=False)
    assert q.get() == 1
    assert q.get() == 2
    assert q.empty()
    with pytest.raises(Empty):
        q.get(block=False)
    with pytest.raises(Empty):
        q.get(timeout=0.2)


def test_queue_blocking_across_tasks(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    ref = producer.remote(q, 3)
    got = [q.get(timeout=30) for _ in range(3)]
    assert got == [0, 1, 2]
    assert ray_tpu.get(ref, timeout=30)


def _sq(x):
    return x * x


def test_multiprocessing_pool(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(_sq, range(8)) == [x * x for x in range(8)]
        assert pool.apply(_sq, (5,)) == 25
        r = pool.apply_async(_sq, (6,))
        assert r.get(timeout=60) == 36
        assert sorted(pool.imap_unordered(_sq, range(4))) == [0, 1, 4, 9]
        assert list(pool.imap(_sq, range(4))) == [0, 1, 4, 9]
        assert pool.starmap(pow, [(2, 3), (3, 2)]) == [8, 9]


def test_joblib_backend(ray_start_regular):
    import joblib

    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray_tpu", n_jobs=2):
        out = joblib.Parallel()(joblib.delayed(_sq)(i) for i in range(6))
    assert out == [0, 1, 4, 9, 16, 25]


def test_workflow_run_and_resume(ray_start_regular, tmp_path):
    from ray_tpu import workflow

    calls = tmp_path / "calls.txt"

    @ray_tpu.remote
    def add(a, b):
        with open(calls, "a") as f:
            f.write("add\n")
        return a + b

    @ray_tpu.remote
    def boom(x):
        raise RuntimeError("step failed")

    @ray_tpu.remote
    def double(x):
        with open(calls, "a") as f:
            f.write("double\n")
        return 2 * x

    storage = str(tmp_path / "wf")
    dag = double.bind(add.bind(1, 2))
    out = workflow.run(dag, workflow_id="wf1", storage=storage)
    assert out == 6
    assert workflow.get_status("wf1", storage=storage) == "SUCCESSFUL"
    assert workflow.get_output("wf1", storage=storage) == 6
    n_calls = len(calls.read_text().splitlines())
    assert n_calls == 2

    # Re-running the finished workflow replays from storage: no new calls.
    assert workflow.run(dag, workflow_id="wf1", storage=storage) == 6
    assert len(calls.read_text().splitlines()) == n_calls

    # A failing workflow checkpoints its completed prefix; after the fix
    # (new DAG tail) the prefix is reused.
    dag2 = boom.bind(add.bind(1, 2))
    with pytest.raises(Exception, match="step failed"):
        workflow.run(dag2, workflow_id="wf2", storage=storage)
    assert workflow.get_status("wf2", storage=storage) == "FAILED"
    fixed = double.bind(add.bind(1, 2))
    out = workflow.resume("wf2", fixed, storage=storage)
    assert out == 6
    # add ran once for wf2's failed attempt, double once on resume; the
    # checkpointed add step did NOT re-execute.
    assert len(calls.read_text().splitlines()) == n_calls + 2
    assert ("wf1", "SUCCESSFUL") in workflow.list_all(storage=storage)
    workflow.delete("wf1", storage=storage)
    assert ("wf1", "SUCCESSFUL") not in workflow.list_all(storage=storage)


def _first(t):
    return t[0]


def test_multiprocessing_pool_tuple_items(ray_start_regular):
    """map passes each item as ONE argument (stdlib contract): tuple items
    must not be star-unpacked."""
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(sum, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.map(_first, [(1, 2), (3, 4)]) == [1, 3]
        assert list(pool.imap(_first, [(9, 0)])) == [9]


def test_workflow_distinct_sibling_steps(ray_start_regular, tmp_path):
    """Two binds with identical signatures are distinct steps, each
    executed once (no checkpoint collapse)."""
    from ray_tpu import workflow

    marker = tmp_path / "runs.txt"

    @ray_tpu.remote
    def sample():
        with open(marker, "a") as f:
            f.write("x\n")
        return 1

    @ray_tpu.remote
    def combine(a, b):
        return a + b

    dag = combine.bind(sample.bind(), sample.bind())
    out = workflow.run(dag, workflow_id="wf_sib",
                       storage=str(tmp_path / "wf"))
    assert out == 2
    assert len(marker.read_text().splitlines()) == 2
