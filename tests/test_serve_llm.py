"""LLM serving engine (serve/llm/): continuous batching, arena-paged KV
cache, prefix-affinity routing.

Fast deterministic units (tier-1 under the ``llm`` marker): prefix chain
hash nesting + longest-match semantics, the KV pool's page lifecycle in
heap AND arena mode (the arena path driven against a real
LocalObjectStore — zero-copy ``np.shares_memory`` proof, dead-range
reclaim on free, KVPG deletion instead of adoption on client death),
prefix-cache insert/match/LRU, the sequence scheduler's step-boundary
admission / copy-on-extend / drain baseline / shed behavior, the
affinity router's pick math directly on ``_RouterState``, and the
ingraph-psum parity satellite. E2E (own serve cluster): HTTP token
streaming with prefix reuse, 503 load shedding, kill -9 mid-decode with
zero leaked pages, and the flags-off byte-identity pin for plain
deployments.
"""

import asyncio
import json
import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import memview, slab_arena
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import LocalObjectStore
from ray_tpu.serve._common import (SERVE_CONTROLLER_NAME, SERVE_NAMESPACE,
                                   OverloadedError)
from ray_tpu.serve.llm import prefix
from ray_tpu.serve.llm.engine import LLMServer, SequenceScheduler
from ray_tpu.serve.llm.kv_cache import (KV_PAGE_OID_PREFIX, KVPool,
                                        PrefixCache, mint_page_oid)
from ray_tpu.serve.llm.model import SyntheticLLM

pytestmark = pytest.mark.llm

KV_HEX = KV_PAGE_OID_PREFIX.hex()


# ---------------------------------------------------------------------------
# prefix identity
# ---------------------------------------------------------------------------

def test_chain_hashes_nest():
    """A chain value commits to its WHOLE prefix: two prompts sharing
    block 1's tokens but not block 0's must not share block 1's chain."""
    a = prefix.chain_hashes([1, 2, 3, 4, 5, 6, 7], 2)
    assert len(a) == 3  # partial tail block has no identity
    assert a == prefix.chain_hashes([1, 2, 3, 4, 5, 6], 2)
    b = prefix.chain_hashes([9, 9, 3, 4, 5, 6], 2)
    assert a[0] != b[0] and a[1] != b[1]  # same block-1 tokens, new chain
    assert prefix.chain_hashes([1], 2) == []
    assert prefix.chain_hashes([1, 2, 3], 0) == []


def test_longest_match_depth_stops_at_first_miss():
    c = ["h0", "h1", "h2"]
    assert prefix.longest_match_depth(c, set()) == 0
    assert prefix.longest_match_depth(c, {"h0", "h1", "h2"}) == 3
    # a stray deeper hit after a miss is a collision, not a prefix
    assert prefix.longest_match_depth(c, {"h0", "h2"}) == 1


def test_tokenize_stable_across_processes():
    """Builtin hash() is interpreter-salted; the blake2b tokenizer must
    pin exact values or router/replica chains would never agree."""
    toks = prefix.tokenize("the quick fox the")
    assert toks == prefix.tokenize("the quick fox the")
    assert toks[0] == toks[3]  # same word, same id
    assert all(0 <= t < 50_000 for t in toks)


def test_extract_tokens_shapes():
    assert prefix.extract_tokens((), {"tokens": [1, 2]}) == [1, 2]
    assert prefix.extract_tokens(({"tokens": [3]},), {}) == [3]
    p = prefix.extract_tokens((), {"prompt": "a b"})
    assert p == prefix.tokenize("a b")
    assert prefix.extract_tokens((), {}) == []
    assert prefix.extract_tokens((42,), {}) == []  # non-LLM call shape

    class Env:  # serve Request envelope
        body = json.dumps({"prompt": "a b"}).encode()

    assert prefix.extract_tokens((Env(),), {}) == p


# ---------------------------------------------------------------------------
# KV pool: heap mode lifecycle + budget
# ---------------------------------------------------------------------------

def test_kv_pool_heap_budget_and_free():
    pool = KVPool(page_tokens=4, kv_dim=8, max_pages=3, use_arena=False)
    assert not pool.arena_backed
    pages = [pool.alloc() for _ in range(3)]
    assert all(p is not None for p in pages)
    assert pool.alloc() is None  # budget, not an exception
    assert pool.counts() == {"active": 3, "cached": 0, "free": 0}
    pool.incref(pages[0])
    pool.decref(pages[0])  # still one ref
    assert pool.available() == 0
    for p in pages:
        pool.decref(p)
    assert pool.counts() == {"active": 0, "cached": 0, "free": 3}


def test_prefix_cache_match_and_lru_eviction():
    pool = KVPool(page_tokens=4, kv_dim=8, max_pages=8, use_arena=False)
    cache = PrefixCache(pool, max_pages=2)
    p0, p1, p2 = (pool.alloc() for _ in range(3))
    cache.insert("c0", p0)
    cache.insert("c1", p1)
    got = cache.match(["c0", "c1", "c-miss", "c1"])
    assert got == [p0, p1]  # stops at first miss
    for p in got:
        pool.decref(p)
    # the match touched c0 then c1, so c0 is now LRU-oldest: inserting
    # c2 over the 2-page cap evicts c0
    cache.insert("c2", p2)
    assert set(cache.chains()) == {"c1", "c2"}
    # owner drops its refs; cached pages stay alive via the cache's ref
    for p in (p0, p1, p2):
        pool.decref(p)
    assert pool.counts()["cached"] == 2
    cache.note_lookup(10, 4)
    assert cache.hit_rate() == pytest.approx(0.4)
    cache.clear()
    assert pool.counts() == {"active": 0, "cached": 0, "free": 8}


# ---------------------------------------------------------------------------
# KV pool: arena mode against a real LocalObjectStore
# ---------------------------------------------------------------------------

class _FakeCoreWorker:
    """The thin slice of core-worker surface KVPool uses, wired straight
    to a LocalObjectStore: lease_slab request, free_objects notify, and
    the batched slab report."""

    def __init__(self, store: LocalObjectStore, client_id: str = "kv"):
        self.store = store
        self.client_id = client_id
        self.io = self
        self.raylet = self
        self.reports = []

    # io facade: the pool hands us the raylet "coroutine" (here: the
    # already-computed reply) to run/schedule
    def run(self, x, timeout=None):
        return x

    def call_soon(self, x):
        return x

    def request(self, op, payload):
        assert op == "lease_slab"
        return self.store.lease_slab(self.client_id, payload["bytes"],
                                     payload.get("seals"))

    def notify(self, op, payload):
        assert op == "free_objects"
        for b in payload["object_ids"]:
            self.store.delete(ObjectID(b))

    def _queue_slab_report(self, ent):
        self.reports.append(ent)
        self.store.record_slab_objects([ent])


def _arena_pool(tmp_path, **kw):
    store = LocalObjectStore(str(tmp_path / "shm"), 1 << 22)
    pool = KVPool(use_arena=False, **kw)
    pool._worker = _FakeCoreWorker(store)
    pool._writer = slab_arena.SlabWriter(str(tmp_path / "shm"))
    return store, pool


def test_kv_page_arena_zero_copy_and_ledger(tmp_path):
    memview.set_enabled(True)
    memview.reset()
    store, pool = _arena_pool(tmp_path, page_tokens=4, kv_dim=8,
                              max_pages=16)
    page = pool.alloc()
    assert page.oid is not None and page.oid.startswith(KV_PAGE_OID_PREFIX)
    # writes land in the segment mapping itself: an independent view of
    # the same store region sees them with zero copies anywhere
    page.data[0] = np.arange(8, dtype=np.float32)
    rb = pool.readback(page)
    assert np.shares_memory(page.data, rb)
    assert np.array_equal(rb[0], np.arange(8, dtype=np.float32))
    # accounting rode the slab report: the store ledger has the row with
    # the allocating callsite, and the page pins as referenced
    assert store.contains(ObjectID(page.oid))
    rows = {r["object_id"]: r for r in store.memview_objects()}
    row = rows[page.oid.hex()]
    assert row["state"] == "arena"
    assert "test_serve_llm.py" in (
        pool._worker.reports[0].get("c") or "")
    assert page.oid.hex() in {o.hex() for o in memview.external_pins()}
    # free: one notify, the entry goes dead (dead ranges grow), unpinned
    dead0 = store.arena_introspect()["dead_bytes"]
    pool.decref(page)
    assert not store.contains(ObjectID(page.oid))
    assert store.arena_introspect()["dead_bytes"] > dead0
    assert page.oid.hex() not in {o.hex() for o in memview.external_pins()}
    assert pool.counts() == {"active": 0, "cached": 0, "free": 16}
    memview.reset()


def test_kv_pages_die_with_client_not_adopted(tmp_path):
    """kill -9 semantics at the store layer: reclaim_client_slabs must
    DELETE a dead client's KV pages (cache dies with its replica) while
    still adopting ordinary sealed entries in the same segment."""
    store, pool = _arena_pool(tmp_path, page_tokens=4, kv_dim=8,
                              max_pages=16)
    kv_pages = [pool.alloc() for _ in range(3)]
    assert all(p.oid for p in kv_pages)
    # an ordinary unreported put in the same client's OTHER segment —
    # the adoption path the KV carve-out must not break
    r = store.lease_slab("kv", 1 << 20)
    w = slab_arena.SlabWriter(store.store_dir)
    w.attach(r["seg_id"], r["size"])
    data_oid = ObjectID.from_random()
    payload = b"d" * 4096
    assert w.try_put(data_oid.binary(), b"", [payload], len(payload))
    # the client dies without reporting/freeing anything
    new = store.reclaim_client_slabs("kv")
    assert data_oid.binary() in new, "real data must be adopted"
    assert store.contains(data_oid)
    for p in kv_pages:
        assert p.oid not in new, "KV pages must not be adopted"
        assert not store.contains(ObjectID(p.oid))


def test_kv_pool_releases_lease_on_close(tmp_path):
    store, pool = _arena_pool(tmp_path, page_tokens=4, kv_dim=8,
                              max_pages=16)
    page = pool.alloc()
    pool.decref(page)
    pool.close()  # graceful: seals + retires the lease via lease_slab
    assert store.reclaim_client_slabs("kv") == []


# ---------------------------------------------------------------------------
# sequence scheduler
# ---------------------------------------------------------------------------

def _sched(**kw):
    kw.setdefault("max_running", 4)
    kw.setdefault("max_queued", 8)
    pool = KVPool(page_tokens=kw.pop("page_tokens", 4),
                  kv_dim=8, max_pages=kw.pop("max_pages", 32),
                  use_arena=False)
    return SequenceScheduler(SyntheticLLM(kv_dim=8), pool, **kw)


async def _run_one(s, tokens, n):
    seq = await s.submit(tokens, n)
    out = [t async for t in s.stream(seq)]
    return seq, out


def test_scheduler_deterministic_and_prefix_reuse():
    async def main():
        s = _sched(prefix_cache_pages=16)
        seq1, out1 = await _run_one(s, list(range(10)), 6)
        seq2, out2 = await _run_one(s, list(range(10)), 6)
        assert len(out1) == 6 and out1 == out2, \
            "same prompt through cached pages must decode identically"
        assert seq1.cached_tokens == 0
        assert seq2.cached_tokens == 8  # 2 full pages of 4 reused
        assert s.cache.hit_rate() > 0
        s.stop()
        assert s.pool.counts()["active"] == 0, "stop leaked pages"
        assert s.pool.counts()["cached"] == 0
    asyncio.run(main())


def test_scheduler_copy_on_extend_protects_cached_tail():
    """Appending through a shared page must copy first: the cached
    page's bytes are other sequences' prefix."""
    async def main():
        s = _sched(prefix_cache_pages=16)
        await _run_one(s, list(range(8)), 4)   # caches 2-3 full pages
        chains = s.cache.chains()
        assert chains
        snap = {c: s.cache._pages[c].data.copy() for c in chains}
        # a second sequence reuses them then generates right through
        await _run_one(s, list(range(8)), 8)
        for c in chains:
            assert np.array_equal(s.cache._pages[c].data, snap[c]), \
                "cached page mutated by a borrowing sequence"
        s.stop()
    asyncio.run(main())


def test_scheduler_continuous_admits_mid_batch_drain_does_not():
    """Step boundaries driven by hand (no background task): the
    admission semantics without timing races."""
    async def main():
        cont = _sched(batching="continuous")
        cont.ensure_running = lambda: None
        a = await cont.submit(list(range(4)), 8)
        cont._admit()
        cont._decode_step()
        assert a.generated == 1
        b = await cont.submit(list(range(4)), 8)
        cont._admit()  # next step boundary: b joins the RUNNING batch
        assert a in cont.running and b in cont.running
        cont._decode_step()
        assert (a.generated, b.generated) == (2, 1)
        cont.stop()

        drain = _sched(batching="drain")
        drain.ensure_running = lambda: None
        a = await drain.submit(list(range(4)), 8)
        drain._admit()
        drain._decode_step()
        b = await drain.submit(list(range(4)), 8)
        drain._admit()
        assert b not in drain.running, \
            "drain: b admitted into a non-empty batch"
        while a in drain.running:
            drain._decode_step()
        assert b.generated == 0
        drain._admit()  # batch drained: NOW b enters
        assert b in drain.running
        drain.stop()
    asyncio.run(main())


def test_scheduler_sheds_on_queue_and_impossible_kv():
    async def main():
        s = _sched(max_queued=1, max_pages=4, page_tokens=4)
        # worst case 5 pages > 4-page pool: doomed, shed immediately
        with pytest.raises(OverloadedError):
            await s.submit(list(range(4)), 16)
        # fill the queue without running the loop (never start it)
        await s.submit(list(range(4)), 4)
        with pytest.raises(OverloadedError) as ei:
            await s.submit(list(range(4)), 4)
        assert "SERVE_OVERLOADED" in str(ei.value)
        assert s.shed_total == 2
        assert s.queue_depth() == 1
        s.stop()
    asyncio.run(main())


def test_scheduler_kv_budget_holds_admission_until_frees():
    """A queued sequence that does not fit waits at the head and gets
    admitted once the running one frees its pages."""
    async def main():
        s = _sched(max_pages=4, page_tokens=4, max_running=4)
        a = await s.submit(list(range(8)), 4)   # 3 pages worst case
        b = await s.submit(list(range(8)), 4)   # needs 3 > 1 free: waits
        out_a = [t async for t in s.stream(a)]
        out_b = [t async for t in s.stream(b)]
        assert len(out_a) == 4 and len(out_b) == 4
        assert s.steps >= 8, "b cannot have run concurrently with a"
        s.stop()
    asyncio.run(main())


# ---------------------------------------------------------------------------
# affinity router units (directly on _RouterState)
# ---------------------------------------------------------------------------

def _router(replicas, reported, index, block_tokens=2, fresh=True):
    from ray_tpu.serve.handle import _RouterState

    st = _RouterState("app", "dep")
    st.replicas = [(n, None) for n in replicas]
    st.inflight = {n: 0 for n in replicas}
    st.reported = dict(reported)
    st.reported_age0 = 0.0
    st.reported_at = time.monotonic() if fresh else None
    st.report_max_age_s = 5.0
    st.prefix_index = {n: frozenset(v) for n, v in index.items()}
    st.prefix_block_tokens = block_tokens
    return st


def test_router_longest_prefix_wins():
    chains = ["c0", "c1", "c2"]
    st = _router(["r1", "r2"], {"r1": 0, "r2": 0},
                 {"r1": ["c0"], "r2": ["c0", "c1"]})
    assert st.pick(chains)[0] == "r2"
    # equal depth: lower score breaks the tie
    st = _router(["r1", "r2"], {"r1": 3, "r2": 1},
                 {"r1": ["c0", "c1"], "r2": ["c0", "c1"]})
    assert st.pick(chains)[0] == "r2"


def test_router_affinity_yields_to_load():
    """Cache warmth must not defeat load balancing: a drowning winner is
    skipped (p2c takes over)."""
    chains = ["c0", "c1"]
    st = _router(["r1", "r2"], {"r1": 0.0, "r2": 10.0},
                 {"r2": ["c0", "c1"]})
    assert st.affinity_pick(chains) is None
    assert st.pick(chains)[0] in ("r1", "r2")  # legacy p2c path


def test_router_stale_report_disables_affinity():
    chains = ["c0"]
    st = _router(["r1", "r2"], {}, {"r2": ["c0"]}, fresh=False)
    assert st.reported_stale()
    assert st.affinity_pick(chains) is None
    picked = {st.pick(chains)[0] for _ in range(40)}
    assert picked == {"r1", "r2"}, "stale digests must fall back to p2c"


def test_router_plain_deployment_untouched():
    """No digests reported => request_chains is [] and pick() is exactly
    the legacy p2c — the flags-off byte-identity of the router."""
    st = _router(["r1", "r2"], {"r1": 0, "r2": 5}, {}, block_tokens=0)
    assert st.request_chains((), {"prompt": "a b c"}) == []
    assert st.pick([])[0] in ("r1", "r2")


def test_router_request_chains_from_llm_call_shapes():
    st = _router(["r1"], {"r1": 0}, {"r1": ["x"]}, block_tokens=2)
    toks = prefix.tokenize("w0 w1 w2 w3")
    want = prefix.chain_hashes(toks, 2)
    assert st.request_chains((), {"prompt": "w0 w1 w2 w3"}) == want
    assert st.request_chains((), {"tokens": toks}) == want
    assert st.request_chains((7,), {}) == []  # not an LLM request


# ---------------------------------------------------------------------------
# satellite: in-graph psum wiring parity (chunked/quantized vs plain)
# ---------------------------------------------------------------------------

_PARITY_SCRIPT = """
import numpy as np
import jax, jax.numpy as jnp
from jax.sharding import Mesh
from ray_tpu.models.gpt2 import GPT2Config, build_train_step, \
    make_train_state

cfg = GPT2Config.small_test(dtype=jnp.float32)
model, params, tx, opt = make_train_state(cfg, jax.random.PRNGKey(0))
mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                         cfg.vocab_size)
batch = {"input_ids": ids, "labels": ids}

def run(mode):
    step = build_train_step(model, tx, donate=False, mesh=mesh,
                            ingraph_psum=mode, psum_chunks=2)
    p, _, l = step(jax.tree.map(jnp.copy, params),
                   jax.tree.map(jnp.copy, opt), batch)
    return jax.tree.leaves(jax.device_get(p)), float(l)

p0, l0 = run("")           # flags-off: the original jit path
p1, l1 = run("chunked")
p2, l2 = run("quantized")
d1 = max(float(np.max(np.abs(a - b))) for a, b in zip(p0, p1))
d2 = max(float(np.max(np.abs(a - b))) for a, b in zip(p0, p2))
assert abs(l0 - l1) < 1e-4 and d1 < 1e-4, \
    f"chunked psum diverged from plain: dloss={l0-l1} dparam={d1}"
assert abs(l0 - l2) < 5e-2 and d2 < 5e-2, \
    f"quantized psum outside int8 tolerance: dparam={d2}"
try:
    build_train_step(model, tx, ingraph_psum="chunked")  # no mesh
except ValueError:
    pass
else:
    raise AssertionError("mode without mesh must raise")
print("PARITY_OK", d1, d2)
"""


@pytest.mark.slow
def test_build_train_step_ingraph_psum_parity():
    """Subprocess: XLA_FLAGS must predate the jax import to get 4 host
    devices, and other tests in this process have already imported it."""
    import subprocess
    import sys

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    r = subprocess.run([sys.executable, "-c", _PARITY_SCRIPT], env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PARITY_OK" in r.stdout


def test_jax_config_carries_ingraph_psum():
    from ray_tpu.train.backend import JaxConfig, _set_ingraph_psum
    from ray_tpu._private.config import GLOBAL_CONFIG

    cfg = JaxConfig(ingraph_psum="chunked", ingraph_psum_chunks=8)
    assert cfg.ingraph_psum == "chunked"
    old = (GLOBAL_CONFIG.train_ingraph_psum,
           GLOBAL_CONFIG.train_ingraph_psum_chunks)
    try:
        _set_ingraph_psum("quantized", 2)  # what on_start fans out
        assert GLOBAL_CONFIG.train_ingraph_psum == "quantized"
        assert GLOBAL_CONFIG.train_ingraph_psum_chunks == 2
    finally:
        _set_ingraph_psum(*old)


# ---------------------------------------------------------------------------
# e2e: serve cluster
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def llm_cluster():
    ray_tpu.init(num_cpus=4)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _url(path):
    return f"http://127.0.0.1:{serve.http_port()}{path}"


def _stream_tokens(body, path="/llm", timeout=60):
    import requests

    toks = []
    with requests.post(_url(path), json=body, stream=True,
                       timeout=timeout) as r:
        assert r.status_code == 200, r.text
        for line in r.iter_lines():
            if line:
                toks.append(json.loads(line)["token"])
    return toks


def test_llm_http_stream_prefix_reuse_and_metrics(llm_cluster):
    dep = serve.deployment(LLMServer, name="llm").options(num_replicas=1)
    h = serve.run(dep.bind(page_tokens=4, max_pages=64,
                           prefix_cache_pages=16),
                  name="llm", route_prefix="/llm")
    body = {"prompt": "sess1 w1 w2 w3 w4 w5 w6 w7", "max_tokens": 6}
    out1 = _stream_tokens(body)
    out2 = _stream_tokens(body)
    assert len(out1) == 6 and out1 == out2, \
        "cached-prefix decode must be byte-identical"
    info = ray_tpu.get(h.options(method_name="debug_info").remote().ref)
    assert info["arena_backed"] is True, \
        "in-cluster KV pages must be slab-arena entries, not heap"
    assert info["hit_rate"] > 0, "second request must hit the prefix cache"
    assert info["counts"]["cached"] > 0
    assert info["tokens_decode"] >= 12
    assert {"kv_cache_hit_rate", "kv_cache_pages", "serve_llm_batch_size",
            "serve_llm_shed_total", "serve_llm_tokens_total"} \
        <= set(info["metric_names"])
    proof = ray_tpu.get(
        h.options(method_name="debug_zero_copy").remote().ref)
    assert proof == {"oid_prefix_ok": True, "shares_memory": True,
                     "roundtrip_ok": True}
    # controller load report carries the llm block + prefix digest the
    # affinity router indexes
    controller = ray_tpu.get_actor(SERVE_CONTROLLER_NAME,
                                   namespace=SERVE_NAMESPACE)
    deadline = time.time() + 15
    llm_state = {}
    while time.time() < deadline:
        st = ray_tpu.get(controller.get_replica_state.remote("llm", "llm"))
        llm_state = st.get("llm") or {}
        if any(r.get("prefix_digest") for r in llm_state.values()):
            break
        time.sleep(0.3)
    assert llm_state, "controller never picked up the llm load report"
    rep = next(iter(llm_state.values()))
    assert rep["block_tokens"] == 4 and rep["prefix_digest"]
    serve.delete("llm")


def test_llm_http_shed_returns_503(llm_cluster):
    dep = serve.deployment(LLMServer, name="tiny").options(num_replicas=1)
    serve.run(dep.bind(page_tokens=4, max_pages=4, max_queued=2),
              name="tiny", route_prefix="/tiny")
    import requests

    # worst-case pages exceed the whole pool: shed at submit, BEFORE any
    # stream bytes — the proxy must answer a real 503, not a 200 + error
    r = requests.post(_url("/tiny"),
                      json={"prompt": "a b c", "max_tokens": 500},
                      timeout=30)
    assert r.status_code == 503
    assert r.headers.get("Retry-After") == "1"
    serve.delete("tiny")


def test_llm_kill9_mid_decode_leaves_no_pages(llm_cluster):
    """kill -9 a replica while it streams: the raylet's death reclaim
    must erase every KVPG page (dead ranges, not adoption) — the store
    holds no KV rows and memview issues no leak verdicts for them."""
    import requests

    from ray_tpu.util import state

    dep = serve.deployment(LLMServer, name="victim").options(
        num_replicas=1)
    h = serve.run(dep.bind(page_tokens=4, max_pages=64,
                           step_delay_s=0.05),
                  name="victim", route_prefix="/victim")
    info = ray_tpu.get(h.options(method_name="debug_info").remote().ref)
    assert info["arena_backed"] is True
    r = requests.post(_url("/victim"),
                      json={"prompt": "k1 k2 k3 k4 k5", "max_tokens": 200},
                      stream=True, timeout=30)
    it = r.iter_lines()
    next(it)  # decode underway: live KV pages in the arena
    next(it)
    os.kill(info["pid"], signal.SIGKILL)
    r.close()
    deadline = time.time() + 20
    kv_rows = None
    while time.time() < deadline:
        merged = state.object_summary()
        kv_rows = [row for row in merged["objects"]
                   if row["object_id"].startswith(KV_HEX)]
        if not kv_rows:
            break
        time.sleep(0.5)
    assert kv_rows == [], f"KV pages survived replica death: {kv_rows}"
    assert not [v for v in merged["verdicts"]
                if v["kind"] == "leak"
                and v.get("object_id", "").startswith(KV_HEX)]
    serve.delete("victim")


def test_flags_off_plain_deployment_byte_identical(llm_cluster):
    """The pin: a non-LLM deployment's replica metrics, controller state
    and queue-depth source are exactly the legacy shapes — nothing in
    the LLM plumbing leaks into plain serve."""

    @serve.deployment
    class Plain:
        def __call__(self, request):
            return "ok"

    h = serve.run(Plain.bind(), name="plain", route_prefix="/plain")
    assert ray_tpu.get(h.remote(None).ref) == "ok"
    controller = ray_tpu.get_actor(SERVE_CONTROLLER_NAME,
                                   namespace=SERVE_NAMESPACE)
    st = ray_tpu.get(controller.get_replica_state.remote("plain", "Plain"))
    assert "llm" not in st, "plain deployments must not report llm state"
    assert st["names"]
    rep = ray_tpu.get_actor(st["names"][0], namespace=SERVE_NAMESPACE)
    m = ray_tpu.get(rep.get_metrics.remote())
    assert set(m) == {"ongoing", "total"}, \
        f"legacy get_metrics payload changed: {sorted(m)}"
    # router state for a plain deployment: no prefix index, pick == p2c
    state_obj = h._state
    state_obj.refresh(force=True)
    assert state_obj.prefix_index == {}
    assert state_obj.prefix_block_tokens == 0
    serve.delete("plain")
