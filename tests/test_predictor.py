"""Predictor + BatchPredictor (ray parity: train/predictor.py,
train/batch_predictor.py, per-framework *_predictor.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train.predictor import (
    BatchPredictor,
    JaxPredictor,
    SklearnPredictor,
    XGBoostPredictor,
)


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_jax_predictor_roundtrip():
    import jax.numpy as jnp

    w = np.array([[2.0], [3.0]], np.float32)

    def apply_fn(params, x):
        return x @ params["w"]

    ck = JaxPredictor.pack(apply_fn, {"w": w})
    pred = JaxPredictor.from_checkpoint(ck)
    out = pred.predict(np.array([[1.0, 1.0], [2.0, 0.0]], np.float32))
    np.testing.assert_allclose(out["predictions"][:, 0], [5.0, 4.0])
    # dict batches concatenate columns in order
    out2 = pred.predict({"a": np.array([1.0, 2.0], np.float32),
                         "b": np.array([1.0, 0.0], np.float32)})
    np.testing.assert_allclose(out2["predictions"][:, 0], [5.0, 4.0])


def test_sklearn_predictor_roundtrip():
    from sklearn.linear_model import LinearRegression

    X = np.array([[0.0], [1.0], [2.0]], np.float64)
    y = np.array([1.0, 3.0, 5.0])
    ck = SklearnPredictor.pack(LinearRegression().fit(X, y))
    pred = SklearnPredictor.from_checkpoint(ck)
    out = pred.predict(np.array([[3.0]]))
    assert out["predictions"][0] == pytest.approx(7.0)


def test_xgboost_predictor_roundtrip():
    xgboost = pytest.importorskip("xgboost")

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 3))
    y = (X[:, 0] > 0).astype(np.float64)
    booster = xgboost.train(
        {"objective": "binary:logistic", "seed": 0},
        xgboost.DMatrix(X, label=y), num_boost_round=10,
    )
    ck = XGBoostPredictor.pack(booster)
    pred = XGBoostPredictor.from_checkpoint(ck)
    out = pred.predict(X[:8])
    acc = ((out["predictions"] > 0.5) == y[:8]).mean()
    assert acc >= 0.75


def test_batch_predictor_over_dataset(ray_cluster):
    def apply_fn(params, x):
        return x * params["scale"]

    ck = JaxPredictor.pack(apply_fn, {"scale": np.float32(10.0)})
    bp = BatchPredictor.from_checkpoint(ck, JaxPredictor)
    ds = ray_tpu.data.range(64)
    scored = bp.predict(ds, batch_size=16, concurrency=2)
    rows = scored.take_all()
    got = sorted(float(np.ravel(r["predictions"])[0]) for r in rows)
    assert got == [float(i * 10) for i in range(64)]
