"""RL tests (analog of ray: rllib/tests + per-algorithm learning tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    CartPole,
    DQNConfig,
    IMPALAConfig,
    PPOConfig,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    SampleBatch,
    compute_gae,
    vtrace,
)


def test_cartpole_env_contract():
    env = CartPole({"seed": 0})
    obs, info = env.reset(seed=0)
    assert obs.shape == (4,)
    obs, r, term, trunc, _ = env.step(1)
    assert r == 1.0 and not term and not trunc


def test_gae_matches_manual():
    batch = SampleBatch({
        "rewards": np.array([1.0, 1.0, 1.0], np.float32),
        "values": np.array([0.5, 0.5, 0.5], np.float32),
        "dones": np.array([False, False, True]),
    })
    out = compute_gae(batch, last_value=9.9, gamma=1.0, lam=1.0)
    # terminal step: delta = 1 - 0.5 = 0.5
    assert np.isclose(out["advantages"][2], 0.5)
    # t=1: delta = 1 + 0.5 - 0.5 = 1.0; adv = 1.0 + 0.5
    assert np.isclose(out["advantages"][1], 1.5)
    assert np.allclose(out["value_targets"], out["advantages"] + 0.5)


def test_vtrace_on_policy_reduces_to_td():
    import jax.numpy as jnp

    n = 5
    logp = jnp.zeros(n)
    rewards = jnp.ones(n)
    values = jnp.zeros(n)
    next_values = jnp.zeros(n)  # V(s_{t+1}) per step; fragment end = 0
    dones = jnp.zeros(n, bool)
    truncs = jnp.zeros(n, bool)
    vs, pg = vtrace(logp, logp, rewards, values, next_values, dones,
                    truncs, 1.0)
    # on-policy, gamma=1, zero values: vs[t] = sum of remaining rewards
    assert np.allclose(np.asarray(vs), [5, 4, 3, 2, 1])


def test_vtrace_truncation_cuts_chain_keeps_bootstrap():
    import jax.numpy as jnp

    n = 4
    logp = jnp.zeros(n)
    rewards = jnp.ones(n)
    values = jnp.zeros(n)
    # truncation after t=1 bootstraps from V(final obs)=10, and the
    # correction chain must not leak t>=2 rewards into t<=1 targets
    next_values = jnp.array([0.0, 10.0, 0.0, 0.0])
    dones = jnp.zeros(n, bool)
    truncs = jnp.array([False, True, False, False])
    vs, _ = vtrace(logp, logp, rewards, values, next_values, dones,
                   truncs, 1.0)
    # t=1: delta = 1 + 10 - 0 = 11; t=0: 1 + vs[1] = 12 (within episode)
    assert np.allclose(np.asarray(vs), [12, 11, 2, 1])


def test_gae_truncation_bootstraps_final_obs():
    batch = SampleBatch({
        "rewards": np.array([1.0, 1.0, 1.0], np.float32),
        "values": np.array([0.0, 0.0, 0.0], np.float32),
        "dones": np.array([False, False, False]),
        "truncateds": np.array([False, True, False]),
        # V(s_{t+1}): t=1 truncates with V(final obs)=10; others chain
        "vf_next": np.array([0.0, 10.0, 7.0], np.float32),
    })
    out = compute_gae(batch, last_value=0.0, gamma=1.0, lam=1.0)
    # t=2 (new episode): 1 + 7 = 8; t=1: 1 + 10 = 11 (chain cut, no leak
    # of t=2 into t=1); t=0: 1 + adv[1] = 12
    assert np.allclose(out["advantages"], [12.0, 11.0, 8.0])


def test_replay_buffers():
    rb = ReplayBuffer(capacity=8, seed=0)
    b = SampleBatch({"obs": np.arange(12, dtype=np.float32)})
    rb.add(b)
    assert len(rb) == 8  # wrapped
    s = rb.sample(4)
    assert s.count == 4

    prb = PrioritizedReplayBuffer(capacity=16, seed=0)
    prb.add(SampleBatch({"obs": np.arange(10, dtype=np.float32)}))
    s = prb.sample(5)
    assert "weights" in s and "batch_indexes" in s
    prb.update_priorities(s["batch_indexes"], np.full(5, 10.0))


def test_ppo_learns_cartpole(ray_start_regular):
    algo = (
        PPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=5e-3, num_epochs=6, minibatch_size=128)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(25):
        result = algo.train()
        best = max(best, result.get("episode_return_mean", 0.0))
        if best >= 120:
            break
    algo.stop()
    assert best >= 100, f"PPO failed to learn CartPole (best={best})"


@pytest.mark.slow
def test_impala_improves(ray_start_regular):
    algo = (
        IMPALAConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .debugging(seed=0)
        .build()
    )
    first, best = None, 0.0
    for _ in range(30):
        result = algo.train()
        r = result.get("episode_return_mean")
        if r is not None:
            first = first if first is not None else r
            best = max(best, r)
    algo.stop()
    assert best > first + 10, (first, best)


def test_dqn_runs_and_losses_finite(ray_start_regular):
    algo = (
        DQNConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=1, rollout_fragment_length=200)
        .training(minibatch_size=64,
                  num_steps_sampled_before_learning=200)
        .build()
    )
    losses = []
    for _ in range(5):
        result = algo.train()
        if "loss" in result:
            losses.append(result["loss"])
    algo.stop()
    assert losses and all(np.isfinite(l) for l in losses)


def test_algorithm_checkpoint_roundtrip(ray_start_regular):
    algo = PPOConfig().environment("CartPole-native").env_runners(
        num_env_runners=1, rollout_fragment_length=64
    ).build()
    algo.train()
    ckpt = algo.save()
    w_before = algo.compute_single_action([0.1, 0.0, 0.02, 0.0])
    algo.stop()

    algo2 = PPOConfig().environment("CartPole-native").env_runners(
        num_env_runners=1, rollout_fragment_length=64
    ).build()
    algo2.restore(ckpt)
    assert algo2.compute_single_action([0.1, 0.0, 0.02, 0.0]) == w_before
    algo2.stop()


@pytest.mark.slow
def test_tune_over_algorithm(ray_start_regular):
    """rllib Algorithms are Tune trainables (ray parity: Tuner("PPO"))."""
    from ray_tpu import tune
    from ray_tpu.rllib import PPO

    grid = tune.Tuner(
        PPO,
        param_space={
            "env": "CartPole-native",
            "num_env_runners": 1,
            "rollout_fragment_length": 64,
            "lr": tune.grid_search([5e-3, 1e-3]),
        },
        run_config=ray_tpu.air.RunConfig(stop={"training_iteration": 2}),
        tune_config=tune.TuneConfig(metric="total_loss", mode="min"),
    ).fit()
    assert grid.num_errors == 0
    assert len(grid) == 2


def test_sac_improves(ray_start_regular):
    from ray_tpu.rllib import SACConfig

    algo = (
        SACConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=1, rollout_fragment_length=200)
        .training(minibatch_size=128,
                  num_steps_sampled_before_learning=400,
                  num_epochs=8)
        .build()
    )
    first = None
    best = -1.0
    for _ in range(12):
        result = algo.train()
        r = result.get("episode_return_mean")
        if r is not None:
            if first is None:
                first = r
            best = max(best, r)
        if "q_loss" in result:
            assert np.isfinite(result["q_loss"])
            assert np.isfinite(result["alpha"])
    algo.stop()
    assert first is not None
    assert best > max(first, 25.0), (first, best)


@pytest.mark.slow  # 23s learning-threshold test: slow lane (tier-1 budget)
def test_multi_agent_ppo_two_policies(ray_start_regular):
    """Two policies over four agents: both improve on multi-agent
    CartPole; per-policy batches stay separate."""
    from ray_tpu.rllib.multi_agent import MultiAgentCartPole, MultiAgentPPO

    algo = MultiAgentPPO(
        MultiAgentCartPole,
        env_config={"num_agents": 4, "max_episode_steps": 200},
        policies=["even", "odd"],
        policy_mapping_fn=lambda aid: "even" if int(aid[-1]) % 2 == 0
        else "odd",
        num_env_runners=1,
        rollout_fragment_length=256,
    )
    first = best = None
    for _ in range(10):
        m = algo.train()
        r = m.get("episode_return_mean")
        if r is not None:
            first = r if first is None else first
            best = r if best is None else max(best, r)
        for pid in ("even", "odd"):
            if pid in m:
                assert np.isfinite(m[pid]["total_loss"]), m
    algo.stop()
    assert first is not None and best is not None
    # both policies learned something: aggregate return improved
    assert best > first, (first, best)
    # distinct policies: weights differ
    w_even = algo.get_policy_state("even")
    w_odd = algo.get_policy_state("odd")
    leaves_e = [np.asarray(x).sum() for x in
                __import__("jax").tree.leaves(w_even)]
    leaves_o = [np.asarray(x).sum() for x in
                __import__("jax").tree.leaves(w_odd)]
    assert leaves_e != leaves_o


def test_bc_clones_expert(ray_start_regular, tmp_path):
    """Behavior cloning: train PPO briefly as the 'expert', record its
    rollouts, clone from the recording, and verify the clone outperforms
    a random policy (ray parity: rllib BC on offline data)."""
    from ray_tpu.rllib import PPOConfig
    from ray_tpu.rllib.offline import BCConfig, read_json, write_json

    expert = (
        PPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=1, rollout_fragment_length=512)
        .training(num_epochs=6, minibatch_size=128)
        .build()
    )
    for _ in range(8):
        expert.train()
    # record expert rollouts
    batches = [expert.runners[0].sample.remote(512) for _ in range(2)]
    import ray_tpu as rt

    recorded = rt.get(batches, timeout=300)
    path = write_json(recorded, str(tmp_path / "expert.jsonl"))
    expert.stop()
    assert read_json(path).count == 1024

    bc = (
        BCConfig()
        .environment("CartPole-native")
        .offline_data(input_=path)
        .training(num_epochs=20, minibatch_size=256, lr=3e-3)
        .build()
    )
    result = bc.train()
    assert np.isfinite(result["bc_loss"])
    score = bc.evaluate()["evaluation"]["episode_return_mean"]
    bc.stop()
    # random CartPole policy scores ~20; a clone of a trained expert
    # should be clearly better
    assert score > 50, score


@pytest.mark.slow
def test_appo_learns_cartpole(ray_start_regular):
    from ray_tpu.rllib import APPOConfig

    algo = (
        APPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(25):
        result = algo.train()
        best = max(best, result.get("episode_return_mean", 0.0))
        if best >= 120:
            break
    algo.stop()
    assert best >= 100, f"APPO failed to learn CartPole (best={best})"


@pytest.mark.slow
def test_runner_death_recovers(ray_start_regular):
    """Killing an env-runner actor mid-training is absorbed: the algorithm
    replaces it and keeps training (ray parity: FaultTolerantActorManager,
    rllib/utils/actor_manager.py:189)."""
    algo = (
        PPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=64)
        .training(num_epochs=2)
        .debugging(seed=0)
        .build()
    )
    algo.train()
    victim = algo.runners[0]
    ray_tpu.kill(victim)
    result = algo.train()  # must not raise; runner gets replaced
    assert result["num_env_steps_sampled_lifetime"] >= 2 * 2 * 64
    assert algo.runners[0] is not victim
    algo.stop()
