"""Runtime-env plugin framework tests.

Analog of ray: python/ray/tests/test_runtime_env_plugin.py — custom
plugins register via the class-path env var, validate at option time,
and materialize inside worker processes; built-in keys ride the same
registry; unsupported keys still fail fast.
"""

import os

import pytest

import ray_tpu

# cluster-state-mutating module: always gets (and leaves behind) a
# fresh cluster instead of joining the shared fast-lane one
RAY_REUSE_CLUSTER = False


def test_custom_plugin_materializes_in_worker(monkeypatch):
    monkeypatch.setenv(
        "RAY_TPU_RUNTIME_ENV_PLUGINS",
        "tests.runtime_env_plugin_mod:MarkerPlugin",
    )
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"marker": "hello-plugin"})
        def read_marker():
            return os.environ.get("RTPU_TEST_MARKER")

        assert ray_tpu.get(read_marker.remote(), timeout=60) == "hello-plugin"

        # a worker of a DIFFERENT env (no marker) must not see it
        @ray_tpu.remote
        def read_plain():
            return os.environ.get("RTPU_TEST_MARKER")

        assert ray_tpu.get(read_plain.remote(), timeout=60) is None
    finally:
        ray_tpu.shutdown()


def test_plugin_validate_fails_fast():
    from ray_tpu._private.runtime_env import (
        RuntimeEnvPlugin,
        prepare_runtime_env,
        register_runtime_env_plugin,
    )

    class Picky(RuntimeEnvPlugin):
        name = "picky"

        def validate(self, env):
            if env.get("picky") == "bad":
                raise ValueError("picky rejects bad")

    register_runtime_env_plugin(Picky())
    with pytest.raises(ValueError, match="picky rejects bad"):
        prepare_runtime_env(None, {"picky": "bad"})
    # good values pass through untouched
    assert prepare_runtime_env(None, {"picky": "good"})["picky"] == "good"


def test_malformed_container_still_raises_at_option_time():
    """container graduated from unsupported to a real plugin (worker
    wrapping); malformed specs must still fail at option time, not at
    spawn."""
    from ray_tpu._private.runtime_env import prepare_runtime_env

    with pytest.raises(ValueError, match="image"):
        prepare_runtime_env(None, {"container": ["anything"]})
    out = prepare_runtime_env(None, {"container": {"image": "img:v1"}})
    assert out["container"]["image"] == "img:v1"


def test_pip_without_wheelhouse_raises_documented_error(monkeypatch):
    """Offline path: pip with no wheelhouse fails EARLY with the
    pre-download instructions, not at task time."""
    monkeypatch.delenv("RAY_TPU_WHEELHOUSE", raising=False)
    from ray_tpu._private.runtime_env import prepare_runtime_env

    with pytest.raises(ValueError, match="wheelhouse"):
        prepare_runtime_env(None, {"pip": ["somepkg"]})
    with pytest.raises(ValueError, match="pip download"):
        prepare_runtime_env(None, {"pip": ["somepkg"]})
    # missing directory is also an early error
    with pytest.raises(ValueError, match="not a directory"):
        prepare_runtime_env(
            None, {"pip": {"packages": ["p"], "wheelhouse": "/nope"}}
        )


def test_non_json_value_rejected_at_option_time():
    from ray_tpu._private.runtime_env import prepare_runtime_env

    with pytest.raises(ValueError, match="JSON-serializable"):
        prepare_runtime_env(None, {"custom_blob": {1, 2}})


def test_env_vars_shape_validated():
    from ray_tpu._private.runtime_env import prepare_runtime_env

    with pytest.raises(ValueError, match="env_vars"):
        prepare_runtime_env(None, {"env_vars": ["not", "a", "dict"]})


def _make_wheel(wheelhouse, name="rtpu_testwheel", version="1.0",
                body="MAGIC = 42\n"):
    """Hand-craft a minimal pure-Python wheel (a wheel is just a zip with
    dist-info metadata) so the test needs no network or build tooling."""
    import os
    import zipfile

    os.makedirs(wheelhouse, exist_ok=True)
    whl = os.path.join(wheelhouse, f"{name}-{version}-py3-none-any.whl")
    di = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as zf:
        zf.writestr(f"{name}/__init__.py", body)
        zf.writestr(
            f"{di}/METADATA",
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n",
        )
        zf.writestr(
            f"{di}/WHEEL",
            "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
            "Tag: py3-none-any\n",
        )
        zf.writestr(
            f"{di}/RECORD",
            f"{name}/__init__.py,,\n{di}/METADATA,,\n{di}/WHEEL,,\n"
            f"{di}/RECORD,,\n",
        )
    return whl


def test_pip_wheelhouse_env_end_to_end(ray_start_regular_fn, tmp_path):
    """A task running under a pip runtime env imports a package that
    exists ONLY as a wheel in the local wheelhouse."""
    import ray_tpu

    wheelhouse = str(tmp_path / "wheels")
    _make_wheel(wheelhouse)

    @ray_tpu.remote(runtime_env={"pip": {"packages": ["rtpu_testwheel"],
                                         "wheelhouse": wheelhouse}})
    def use_wheel():
        import rtpu_testwheel

        return rtpu_testwheel.MAGIC

    assert ray_tpu.get(use_wheel.remote(), timeout=120) == 42

    # the driver itself must NOT see the package (it lives in the
    # worker's venv, not the shared interpreter)
    import importlib.util

    assert importlib.util.find_spec("rtpu_testwheel") is None


def test_conda_named_env_activates(monkeypatch, tmp_path):
    """{'conda': 'name'}: an existing env's site-packages join sys.path
    worker-side; a missing env fails EARLY at validate (no conda binary
    on this image)."""
    import sys

    from ray_tpu._private.runtime_env import (
        _CondaPlugin,
        prepare_runtime_env,
    )

    root = tmp_path / "miniconda"
    sp = root / "envs" / "myenv" / "lib" / "python3.12" / "site-packages"
    sp.mkdir(parents=True)
    (sp / "conda_shipped_mod.py").write_text("VALUE = 41\n")
    monkeypatch.setenv("CONDA_PREFIX", str(root))
    monkeypatch.delenv("CONDA_EXE", raising=False)

    env = prepare_runtime_env(None, {"conda": "myenv"})
    plugin = _CondaPlugin()
    try:
        plugin.materialize(None, env)
        import conda_shipped_mod

        assert conda_shipped_mod.VALUE == 41
    finally:
        sys.path[:] = [p for p in sys.path if str(sp) != p]
        sys.modules.pop("conda_shipped_mod", None)

    with pytest.raises(ValueError, match="no such .?env"):
        prepare_runtime_env(None, {"conda": "missing-env"})


def test_conda_spec_without_binary_raises(monkeypatch):
    monkeypatch.delenv("CONDA_EXE", raising=False)
    monkeypatch.setenv("PATH", "/usr/bin:/bin")
    from ray_tpu._private.runtime_env import prepare_runtime_env

    with pytest.raises(ValueError, match="conda binary"):
        prepare_runtime_env(None, {"conda": {"dependencies": ["numpy"]}})
