"""Runtime-env plugin framework tests.

Analog of ray: python/ray/tests/test_runtime_env_plugin.py — custom
plugins register via the class-path env var, validate at option time,
and materialize inside worker processes; built-in keys ride the same
registry; unsupported keys still fail fast.
"""

import os

import pytest

import ray_tpu


def test_custom_plugin_materializes_in_worker(monkeypatch):
    monkeypatch.setenv(
        "RAY_TPU_RUNTIME_ENV_PLUGINS",
        "tests.runtime_env_plugin_mod:MarkerPlugin",
    )
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"marker": "hello-plugin"})
        def read_marker():
            return os.environ.get("RTPU_TEST_MARKER")

        assert ray_tpu.get(read_marker.remote(), timeout=60) == "hello-plugin"

        # a worker of a DIFFERENT env (no marker) must not see it
        @ray_tpu.remote
        def read_plain():
            return os.environ.get("RTPU_TEST_MARKER")

        assert ray_tpu.get(read_plain.remote(), timeout=60) is None
    finally:
        ray_tpu.shutdown()


def test_plugin_validate_fails_fast():
    from ray_tpu._private.runtime_env import (
        RuntimeEnvPlugin,
        prepare_runtime_env,
        register_runtime_env_plugin,
    )

    class Picky(RuntimeEnvPlugin):
        name = "picky"

        def validate(self, env):
            if env.get("picky") == "bad":
                raise ValueError("picky rejects bad")

    register_runtime_env_plugin(Picky())
    with pytest.raises(ValueError, match="picky rejects bad"):
        prepare_runtime_env(None, {"picky": "bad"})
    # good values pass through untouched
    assert prepare_runtime_env(None, {"picky": "good"})["picky"] == "good"


def test_unsupported_keys_still_raise():
    from ray_tpu._private.runtime_env import prepare_runtime_env

    for key in ("pip", "conda", "container"):
        with pytest.raises(ValueError, match="not supported"):
            prepare_runtime_env(None, {key: ["anything"]})


def test_non_json_value_rejected_at_option_time():
    from ray_tpu._private.runtime_env import prepare_runtime_env

    with pytest.raises(ValueError, match="JSON-serializable"):
        prepare_runtime_env(None, {"custom_blob": {1, 2}})


def test_env_vars_shape_validated():
    from ray_tpu._private.runtime_env import prepare_runtime_env

    with pytest.raises(ValueError, match="env_vars"):
        prepare_runtime_env(None, {"env_vars": ["not", "a", "dict"]})
