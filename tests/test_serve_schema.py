"""Declarative Serve deploy (schema + deploy_config + CLI path).

ray parity: serve/schema.py ServeDeploySchema, `serve deploy`,
_private/application_state.py persisted configs.
"""

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.schema import ServeDeploySchema


def test_schema_validation():
    with pytest.raises(ValueError, match="applications"):
        ServeDeploySchema.from_dict({})
    with pytest.raises(ValueError, match="duplicate"):
        ServeDeploySchema.from_dict({"applications": [
            {"name": "a", "import_path": "m:x"},
            {"name": "a", "import_path": "m:y"},
        ]})
    with pytest.raises(ValueError, match="unknown deployment config"):
        ServeDeploySchema.from_dict({"applications": [
            {"name": "a", "import_path": "m:x",
             "deployments": [{"name": "d", "bogus": 1}]},
        ]})
    s = ServeDeploySchema.from_dict({"applications": [
        {"name": "a", "import_path": "m:x", "route_prefix": "/a",
         "deployments": [{"name": "d", "num_replicas": 3}]},
    ]})
    assert s.to_dict()["applications"][0]["deployments"][0]["num_replicas"] == 3


def test_deploy_config_and_status(ray_start_regular):
    config = {"applications": [{
        "name": "echo_app",
        "import_path": "tests.serve_test_app:app",
        "route_prefix": "/echo",
        "deployments": [{"name": "Echo", "num_replicas": 2}],
    }]}
    deployed = serve.deploy_config(config)
    assert deployed == ["echo_app"]

    # Overrides took effect: 2 replicas of Echo.
    status = serve.status()
    assert "echo_app" in status
    # persisted config readable from any client
    assert serve.get_deployed_config()["applications"][0]["name"] == "echo_app"

    # The app answers over HTTP on its route prefix.
    import urllib.request

    port = serve.http_port()
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/echo?m=hi", timeout=30
    ) as resp:
        import json

        assert json.loads(resp.read())["echo"] == "hi"

    # App-builder import path (module:function) also deploys.
    config2 = {"applications": [{
        "name": "built_app",
        "import_path": "tests.serve_test_app:app_builder",
        "route_prefix": "/built",
    }]}
    assert serve.deploy_config(config2) == ["built_app"]
    serve.shutdown()


def test_build_emits_config(ray_start_regular):
    from tests.serve_test_app import app

    cfg = serve.build(app, name="myapp")
    assert cfg["name"] == "myapp"
    assert cfg["deployments"][0]["name"] == "Echo"
    # emitted config round-trips through the schema with a real import_path
    cfg["import_path"] = "tests.serve_test_app:app"
    ServeDeploySchema.from_dict({"applications": [cfg]})
