"""Dask scheduler shim + Grafana factory tests.

Analog of ray: python/ray/util/dask tests (graphs execute on the cluster
with inter-task edges as objects) and the grafana_dashboard_factory
output-shape tests.
"""

import json

import pytest

import ray_tpu
from ray_tpu.util.dask import ray_dask_get


def _add(a, b):
    return a + b


def _inc(x):
    return x + 1


def test_dask_graph_executes(ray_start_regular):
    dsk = {
        "a": 1,
        "b": (_inc, "a"),          # 2
        "c": (_inc, "b"),          # 3
        "d": (_add, "b", "c"),     # 5
        "e": (_add, "d", 10),      # 15
    }
    assert ray_dask_get(dsk, "e") == 15
    assert ray_dask_get(dsk, ["b", "d", "e"]) == [2, 5, 15]


def test_dask_nested_lists_and_tasks(ray_start_regular):
    dsk = {
        "xs": [1, 2, 3],
        "sum": (sum, "xs"),
        "both": (_add, (_inc, 4), "sum"),  # inline nested task: 5 + 6
    }
    assert ray_dask_get(dsk, "both") == 11


def test_dask_nested_task_with_key_args(ray_start_regular):
    dsk = {
        "a": (_inc, 1),                    # 2
        "b": (_add, (_inc, "a"), 1),       # nested task referencing a key
        "lst": ["a"],                      # list-of-keys graph value
    }
    assert ray_dask_get(dsk, "b") == 4
    assert ray_dask_get(dsk, "lst") == [2]


def test_dask_cycle_detected(ray_start_regular):
    dsk = {"a": (_inc, "b"), "b": (_inc, "a")}
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get(dsk, "a")


def test_grafana_dashboard_shape(tmp_path):
    from ray_tpu.dashboard.grafana import generate_dashboard, write_dashboard

    dash = generate_dashboard(user_metrics=["my_app_requests_total"])
    assert dash["uid"] == "ray-tpu-cluster"
    exprs = [p["targets"][0]["expr"] for p in dash["panels"]]
    assert "ray_tpu_node_count" in exprs
    assert "my_app_requests_total" in exprs
    path = write_dashboard(str(tmp_path / "dash.json"))
    loaded = json.load(open(path))
    assert loaded["panels"]
