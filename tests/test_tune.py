"""Tune tests (analog of ray: python/ray/tune/tests/test_tune_*.py)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import BasicVariantGenerator, ConcurrencyLimiter


def test_sample_domains():
    assert 0.0 <= tune.uniform(0, 1).sample() <= 1.0
    assert 1 <= tune.loguniform(1, 100).sample() <= 100
    v = tune.quniform(0, 10, 0.5).sample()
    assert abs(v / 0.5 - round(v / 0.5)) < 1e-9
    assert tune.randint(3, 7).sample() in range(3, 7)
    assert tune.choice(["a", "b"]).sample() in ("a", "b")


def test_variant_generation():
    from ray_tpu.tune.search.variant_generator import (
        count_variants,
        generate_variants,
    )

    space = {
        "a": tune.grid_search([1, 2, 3]),
        "b": tune.grid_search(["x", "y"]),
        "c": tune.uniform(0, 1),
    }
    assert count_variants(space) == 6
    variants = list(generate_variants(space))
    assert len(variants) == 6
    configs = [cfg for _, cfg in variants]
    assert {(c["a"], c["b"]) for c in configs} == {
        (a, b) for a in (1, 2, 3) for b in ("x", "y")
    }
    assert all(0 <= c["c"] <= 1 for c in configs)


def test_tuner_function_trainable(ray_start_regular):
    def objective(config):
        score = (config["x"] - 3) ** 2
        tune.report({"score": score})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="min"),
    )
    grid = tuner.fit()
    assert len(grid) == 5
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert best.metrics["config"]["x"] == 3
    assert best.metrics["score"] == 0


def test_tuner_multiple_reports_and_stop(ray_start_regular):
    def objective(config):
        for i in range(20):
            tune.report({"iter": i, "loss": 1.0 / (i + 1)})

    tuner = tune.Tuner(
        objective,
        param_space={"lr": tune.uniform(0.1, 1.0)},
        tune_config=tune.TuneConfig(num_samples=2, metric="loss", mode="min"),
        run_config=ray_tpu.air.RunConfig(stop={"training_iteration": 5}),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    for res in grid:
        assert res.metrics["training_iteration"] == 5


def test_tuner_class_trainable(ray_start_regular):
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.x = config["x"]
            self.acc = 0.0

        def step(self):
            self.acc += self.x
            return {"acc": self.acc, "done": self.acc >= 10 * self.x}

        def save_checkpoint(self, checkpoint_dir=None):
            return {"acc": self.acc}

        def load_checkpoint(self, state):
            self.acc = state["acc"]

    tuner = tune.Tuner(
        MyTrainable,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="acc", mode="max"),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    best = grid.get_best_result()
    assert best.metrics["acc"] == 20.0


def test_asha_stops_bad_trials(ray_start_regular):
    def objective(config):
        for i in range(30):
            tune.report({"score": config["q"] * (i + 1)})

    tuner = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search([0.1, 0.2, 1.0, 2.0])},
        tune_config=tune.TuneConfig(
            metric="score",
            mode="max",
            scheduler=ASHAScheduler(max_t=30, grace_period=2, reduction_factor=2),
        ),
    )
    grid = tuner.fit()
    iters = {
        r.metrics["config"]["q"]: r.metrics["training_iteration"] for r in grid
    }
    # The best trial survives the full budget.
    assert iters[2.0] == 30
    assert grid.get_best_result().metrics["config"]["q"] == 2.0


def test_median_stopping(ray_start_regular):
    def objective(config):
        for i in range(15):
            tune.report({"score": config["lvl"]})

    tuner = tune.Tuner(
        objective,
        param_space={"lvl": tune.grid_search([1.0, 1.0, 1.0, 0.0])},
        tune_config=tune.TuneConfig(
            metric="score",
            mode="max",
            scheduler=MedianStoppingRule(grace_period=3, min_samples_required=2),
            max_concurrent_trials=4,
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 4


def test_pbt_exploits(ray_start_regular):
    def objective(config):
        score = tune.get_checkpoint()
        base = score.to_dict()["score"] if score else 0.0
        for i in range(12):
            base += config["rate"]
            tune.report(
                {"score": base},
                checkpoint=ray_tpu.air.Checkpoint.from_dict({"score": base}),
            )

    pbt = PopulationBasedTraining(
        metric="score",
        mode="max",
        perturbation_interval=3,
        hyperparam_mutations={"rate": tune.uniform(0.5, 2.0)},
        seed=0,
    )
    tuner = tune.Tuner(
        objective,
        param_space={"rate": tune.grid_search([0.01, 0.02, 1.0, 1.5])},
        tune_config=tune.TuneConfig(
            scheduler=pbt, max_concurrent_trials=4, metric="score", mode="max"
        ),
        run_config=ray_tpu.air.RunConfig(stop={"training_iteration": 12}),
    )
    grid = tuner.fit()
    assert pbt.num_perturbations > 0
    assert grid.get_best_result().metrics["score"] > 1.0


def test_with_resources_and_parameters(ray_start_regular):
    big = list(range(1000))

    def objective(config, data=None):
        tune.report({"n": len(data) + config["x"]})

    wrapped = tune.with_resources(
        tune.with_parameters(objective, data=big), {"CPU": 1}
    )
    grid = tune.Tuner(
        wrapped,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="n", mode="max"),
    ).fit()
    assert grid.get_best_result().metrics["n"] == 1002


def test_trial_failure_marks_error(ray_start_regular):
    def objective(config):
        if config["x"] == 1:
            raise ValueError("boom")
        tune.report({"ok": 1})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
    ).fit()
    assert grid.num_errors == 1
    assert grid.num_terminated == 1


def test_concurrency_limiter(ray_start_regular):
    searcher = ConcurrencyLimiter(BasicVariantGenerator(), max_concurrent=1)

    def objective(config):
        tune.report({"v": config["x"]})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=tune.TuneConfig(
            search_alg=searcher, metric="v", mode="max"
        ),
    ).fit()
    assert len(grid) == 3


def test_tune_run_legacy_api(ray_start_regular):
    def objective(config):
        tune.report({"m": config["x"] * 2})

    grid = tune.run(
        objective,
        config={"x": tune.grid_search([1, 2])},
        metric="m",
        mode="max",
        resources_per_trial={"cpu": 1},
    )
    assert grid.get_best_result().metrics["m"] == 4


def test_tuner_over_trainer(ray_start_regular):
    """Tuner(trainer) parity: sweep over train_loop_config."""
    from ray_tpu import train

    def loop(config):
        for i in range(3):
            train.report({"loss": config["lr"] * (3 - i)})

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=ray_tpu.air.ScalingConfig(num_workers=1),
    )
    tuner = tune.Tuner(
        trainer,
        param_space={
            "train_loop_config": {"lr": tune.grid_search([0.1, 0.01])}
        },
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    assert grid.num_errors == 0
    assert abs(grid.get_best_result().metrics["loss"] - 0.01) < 1e-9


def test_tuner_over_trainer_full_cluster(ray_start_regular):
    """Train workers may claim the ENTIRE cluster: the trial actor must not
    double-count worker bundles or the workers can never schedule
    (regression: trial claimed a worker bundle on top of the executor's)."""
    from ray_tpu import train

    def loop(config):
        train.report({"ok": 1.0})

    trainer = train.DataParallelTrainer(
        loop,
        # 4 workers x 1 CPU == the whole ray_start_regular cluster.
        scaling_config=ray_tpu.air.ScalingConfig(num_workers=4),
    )
    grid = tune.Tuner(trainer).fit()
    assert len(grid) == 1
    assert grid.num_errors == 0


def test_function_trainable_without_checkpoint_has_none(ray_start_regular):
    """A trial that never reports a checkpoint must yield Result.checkpoint
    None even when checkpoint_at_end forces a save (regression: wrapper dict
    leaked through as a truthy empty Checkpoint)."""

    def loop(config):
        tune.report({"x": 1.0})

    grid = tune.Tuner(
        loop,
        run_config=ray_tpu.air.RunConfig(
            checkpoint_config=ray_tpu.air.CheckpointConfig(checkpoint_at_end=True)
        ),
    ).fit()
    assert grid.num_errors == 0
    assert grid[0].checkpoint is None


def test_experiment_snapshot_and_restore(ray_start_regular, tmp_path):
    """Kill an experiment mid-flight, Tuner.restore, finish with trial
    checkpoints intact (ray parity: tune/execution/experiment_state.py)."""

    class Slow(tune.Trainable):
        def setup(self, config):
            self.x = config["x"]
            self.it = 0

        def step(self):
            self.it += 1
            return {"score": self.x * self.it, "done": self.it >= 6}

        def save_checkpoint(self, checkpoint_dir=None):
            return {"it": self.it}

        def load_checkpoint(self, state):
            self.it = state["it"]

    from ray_tpu.air.config import CheckpointConfig, RunConfig
    from ray_tpu.tune.execution.tune_controller import TuneController
    from ray_tpu.tune.logger import DEFAULT_CALLBACKS

    exp_dir = str(tmp_path / "exp")
    controller = TuneController(
        Slow,
        {"x": tune.grid_search([1.0, 2.0, 3.0, 4.0])},
        metric="score",
        mode="max",
        run_config=RunConfig(
            checkpoint_config=CheckpointConfig(checkpoint_frequency=1)
        ),
        callbacks=[cls() for cls in DEFAULT_CALLBACKS],
        experiment_dir=exp_dir,
        max_concurrent_trials=2,
    )
    # Step until some trials have made progress, then snapshot + abandon:
    # this is what a killed driver leaves behind.
    import time as _time

    deadline = _time.monotonic() + 60
    while _time.monotonic() < deadline:
        controller.step()
        if any(
            t.checkpoint is not None and not t.is_finished()
            for t in controller.trials
        ):
            break
    assert not controller.is_finished(), "interrupted too late to be useful"
    controller.save_experiment_state()
    progressed = {
        t.trial_id: t.checkpoint["state"]["it"]
        for t in controller.trials
        if t.checkpoint is not None
    }
    assert progressed, "no trial checkpointed before the interrupt"
    controller.cleanup()  # the "kill": actors die, state file remains

    assert tune.Tuner.can_restore(exp_dir)
    tuner = tune.Tuner.restore(exp_dir, Slow)
    grid = tuner.fit()
    assert len(grid) == 4
    assert grid.num_errors == 0
    scores = sorted(r.metrics["score"] for r in grid)
    assert scores == [6.0, 12.0, 18.0, 24.0]
