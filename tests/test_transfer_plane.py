"""Arena-to-arena transfer plane: receive-side slab assembly, pipelined
fetch, and hole-punch reclamation.

Cross-node object movement lands directly in arena memory: the raylet
reserves an UNSEALED slab entry when a transfer's size is known, chunks
pwrite straight into the segment at their offsets (out-of-order safe),
and the atomic state-word seal fires only when every byte has arrived —
a receiver killed mid-transfer leaves exactly the torn tail the crash
rescan already discards. A periodic pass hole-punches the page-aligned
interior of dead entry ranges (fallocate PUNCH_HOLE|KEEP_SIZE) so
long-lived, partially-dead segments return memory without waiting for
whole-segment emptiness — live zero-copy readers keep their views
because KEEP_SIZE preserves the mapping, and flock-pinned segments are
skipped entirely.
"""

import multiprocessing
import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import memview, object_store, slab_arena
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import LocalObjectStore

pytestmark = pytest.mark.objectplane


# ----------------------------------------------------------------------
# punch page-alignment math (pure)
# ----------------------------------------------------------------------

def test_punch_span_preserves_header_and_page_aligns():
    page = slab_arena.PAGE
    # a multi-page range starting at 0: the header's page survives
    span = slab_arena.punch_span(0, page * 3, page=page)
    assert span == (page, page * 2)
    # header bytes never inside the hole: start >= off + HDR, page-aligned
    for off in (0, 64, page - 64, page, page * 2 + 192):
        for length in (page, page * 2, page * 4 + 128, 64, 128):
            span = slab_arena.punch_span(off, length, page=page)
            if span is None:
                continue
            start, nbytes = span
            assert start % page == 0 and nbytes % page == 0
            assert start >= off + slab_arena.HDR
            assert start + nbytes <= off + length
    # sub-page ranges punch nothing
    assert slab_arena.punch_span(100, 200) is None
    assert slab_arena.punch_span(0, slab_arena.PAGE) is None or \
        slab_arena.punch_span(0, slab_arena.PAGE)[1] == 0


def test_dead_tombstone_covers_whole_range(tmp_path):
    """The covering DEAD tombstone written before a punch makes the scan
    hop the zeroed interior in ONE step — sealed entries BEHIND a
    punched range must stay reachable."""
    store_dir = str(tmp_path)
    seg_path = slab_arena.create_segment(store_dir, 0, 1 << 20)
    fd = os.open(seg_path, os.O_RDWR)
    try:
        import mmap as _mmap

        with open(seg_path, "r+b") as f:
            mm = _mmap.mmap(f.fileno(), 0)
            mv = memoryview(mm)
            oid_a, oid_b = os.urandom(28), os.urandom(28)
            total_a = slab_arena.write_entry(mv, 0, oid_a, b"", [b"x" * 300])
            total_b = slab_arena.write_entry(mv, total_a, oid_b, b"",
                                             [b"y" * 300])
            mv.release()
            mm.close()
        # tombstone entry A's range as one covering DEAD header, then
        # zero its interior the way a punch would
        assert slab_arena.write_dead_tombstone(fd, 0, total_a)
        entries = list(slab_arena.scan_segment(seg_path))
        assert [(e[0], e[5]) for e in entries] == [
            (b"\0" * 28, True), (oid_b, False)
        ], "scan must hop the tombstone and still reach the live entry"
    finally:
        os.close(fd)


# ----------------------------------------------------------------------
# receive-side slab reservations (store level)
# ----------------------------------------------------------------------

@pytest.fixture
def store(tmp_path):
    st = LocalObjectStore(str(tmp_path / "store"), 128 << 20)
    yield st


def test_reservation_out_of_order_writes_then_seal(store):
    """The fetch pipeline lands chunks out of order at their offsets;
    the entry must read back exact after the seal and be INVISIBLE
    before it (the seal is the only publication)."""
    oid = ObjectID(os.urandom(28))
    payload = np.arange(3 << 20, dtype=np.uint8).tobytes()
    res = store.reserve(oid, b"meta!", len(payload))
    assert res is not None
    chunk = 1 << 20
    for off in (2 << 20, 0, 1 << 20):  # out of order
        res.write(off, payload[off:off + chunk])
    assert not store.contains(oid), "unsealed entry must be invisible"
    assert res.seal()
    assert store.contains(oid)
    buf = store.get(oid)
    assert buf.metadata == b"meta!" and bytes(buf.data) == payload
    assert buf.seg_id is not None, "assembled object must be slab-backed"
    buf.release()


def test_reservation_write_bounds_checked(store):
    res = store.reserve(ObjectID(os.urandom(28)), b"", 1024)
    assert res is not None
    with pytest.raises(ValueError):
        res.write(1000, b"x" * 100)  # would overflow the reserved region
    res.abandon()


def test_reservation_abandon_goes_dead_and_scan_hops_it(store):
    """An abandoned session's reservation is tombstoned DEAD (satellite:
    no leaked unsealed entries eroding capacity) and later sealed
    entries in the same segment stay reachable across a rescan."""
    dead0 = store.arena_dead_bytes()
    oid = ObjectID(os.urandom(28))
    res = store.reserve(oid, b"m", 1 << 20)
    res.write(0, b"z" * 1000)  # partial
    res.abandon()
    assert store.arena_dead_bytes() >= dead0 + (1 << 20)
    assert not store.contains(oid)
    # a later put in the same segment...
    oid2 = ObjectID(os.urandom(28))
    store.put(oid2, b"", [b"q" * 500_000], 500_000)
    assert store._slab_objs[oid2][0] == res.seg_id, \
        "put should land in the same (still leased) segment"
    # ...survives a restart rescan (the tombstone is traversable)
    st2 = LocalObjectStore(store.store_dir, 128 << 20)
    buf = st2.get(oid2)
    assert buf is not None and bytes(buf.data[:3]) == b"qqq"


def test_duplicate_seal_loser_goes_dead(store):
    """Two concurrent sessions assembling the SAME object (e.g. two
    senders pushing it): the first seal wins the ledger; the loser's
    sealed bytes must flip DEAD (reclaimable), not leak as an
    unreachable sealed entry until the segment dies."""
    oid = ObjectID(os.urandom(28))
    payload = b"r" * 500_000
    res_a = store.reserve(oid, b"", len(payload))
    res_b = store.reserve(oid, b"", len(payload))
    assert res_a is not None and res_b is not None
    res_a.write(0, payload)
    res_b.write(0, payload)
    assert res_a.seal()
    dead0 = store.arena_dead_bytes()
    assert not res_b.seal(), "second seal must not claim the ledger"
    assert store.arena_dead_bytes() >= dead0 + len(payload)
    assert store._slab_objs[oid][:2] == (res_a.seg_id, res_a.off)
    buf = store.get(oid)
    assert buf is not None and bytes(buf.data) == payload


def test_reservation_keeps_segment_alive(store):
    """A segment holding only an in-flight reservation must not be
    unlinked by the seal path — the assembly is pwriting into it."""
    big = 7 << 20  # most of an 8MB (capacity//8... cap'd) local slab
    oid = ObjectID(os.urandom(28))
    res = store.reserve(oid, b"", big)
    # force a seal/lease cycle: a put too big for the current slab
    oid2 = ObjectID(os.urandom(28))
    store.put(oid2, b"", [b"w" * (2 << 20)], 2 << 20)
    seg = store._segments.get(res.seg_id)
    assert seg is not None and seg.reserved == 1, \
        "reserved segment must survive the seal with its file intact"
    assert os.path.exists(
        slab_arena.segment_path(store.store_dir, res.seg_id))
    res.write(0, np.full(big, 3, np.uint8))
    assert res.seal()
    buf = store.get(oid)
    assert buf is not None and buf.data.nbytes == big
    assert store._segments[res.seg_id].reserved == 0


# ----------------------------------------------------------------------
# hole-punch reclamation (store level)
# ----------------------------------------------------------------------

def _fill_segments(store, n=40, size=1 << 20):
    oids = [ObjectID(os.urandom(28)) for _ in range(n)]
    for o in oids:
        store.put(o, b"", [np.full(size, 9, np.uint8)], size)
    by_seg = {}
    for o in oids:
        by_seg.setdefault(store._slab_objs[o][0], []).append(o)
    return by_seg


@pytest.mark.skipif(not LocalObjectStore("/tmp/_punch_probe_dir",
                                         1 << 20).punch_supported(),
                    reason="filesystem cannot PUNCH_HOLE")
def test_punch_reduces_dead_bytes_while_live_view_stays_valid(store):
    """Acceptance criterion: the punch pass drives slab_arena_dead_bytes
    down while a live reader's zero-copy view (np.shares_memory against
    the segment mapping) stays valid and correct — its flock-pinned
    segment is skipped, fragmented unpinned segments are punched."""
    by_seg = _fill_segments(store)
    sealed = [s for s, seg in
              ((sid, store._segments[sid]) for sid in by_seg)
              if seg.leased_to is None]
    assert len(sealed) >= 2, by_seg.keys()
    keepers = {s: objs[0] for s, objs in by_seg.items()}
    pinned_seg = sealed[0]
    kb = store.get(keepers[pinned_seg])
    view = np.frombuffer(kb.data, dtype=np.uint8)
    mm, _sz = slab_arena.view(store.store_dir).segment(pinned_seg)
    assert np.shares_memory(
        np.frombuffer(memoryview(mm), dtype=np.uint8), view)
    for o in [o for objs in by_seg.values() for o in objs
              if o not in keepers.values()]:
        store.delete(o)
    dead_before = store.arena_dead_bytes()
    out = store.punch_holes(min_fragmentation=0.1, min_bytes=1)
    assert out["dead_bytes_retired"] > 0, out
    assert out["skipped_pinned"] >= 1, \
        "the live reader's segment must be SKIPPED, not punched"
    assert store.arena_dead_bytes() < dead_before
    assert store.arena_punched_bytes() == out["dead_bytes_retired"]
    # the live view is byte-for-byte intact (KEEP_SIZE + skip)
    assert int(view[0]) == 9 and int(view[-1]) == 9
    assert np.all(view[:: max(1, view.nbytes // 64)] == 9)
    # every keeper (including ones in punched segments) still reads
    for s, o in keepers.items():
        b = store.get(o)
        assert b is not None and bytes(b.data[:2]) == b"\x09\x09", s
    # introspection reports the punched ranges
    intro = store.arena_introspect()
    assert intro["punched_bytes"] == out["dead_bytes_retired"]
    assert any(s["punched_bytes"] for s in intro["segments"])


@pytest.mark.skipif(not LocalObjectStore("/tmp/_punch_probe_dir",
                                         1 << 20).punch_supported(),
                    reason="filesystem cannot PUNCH_HOLE")
def test_punch_skips_leased_reserved_and_pooled_segments(store):
    """Leased slabs (writer mid-put), segments with in-flight
    reservations, and recycling-pool files are off limits to the punch
    pass — only sealed, unpinned, fragmented segments are touched."""
    by_seg = _fill_segments(store, n=24)
    leased = [sid for sid, s in store._segments.items() if s.leased_to]
    assert leased, "the active local slab must be leased"
    # park a reservation in one sealed segment
    sealed = [sid for sid in by_seg if store._segments[sid].leased_to
              is None]
    res = None
    for o in [o for objs in by_seg.values() for o in objs]:
        store.delete(o)  # everything dead -> max fragmentation
    # reserve AFTER the deletes so the reservation segment survives
    res = store.reserve(ObjectID(os.urandom(28)), b"", 1 << 20)
    out = store.punch_holes(min_fragmentation=0.0, min_bytes=1)
    touched = {sid for sid, s in store._segments.items() if s.punched}
    assert res.seg_id not in touched, "reserved segment must be skipped"
    assert not (touched & set(leased)), "leased segments must be skipped"
    # pooled files (whole-segment reclamation got there first) are
    # untouched by construction: they are not in _segments at all
    for pooled in store._pool:
        assert os.path.exists(pooled)
    res.abandon()


@pytest.mark.skipif(not LocalObjectStore("/tmp/_punch_probe_dir",
                                         1 << 20).punch_supported(),
                    reason="filesystem cannot PUNCH_HOLE")
def test_punch_merges_across_previously_punched_neighbors(store):
    """A dead range freed NEXT to an already-punched range must merge
    with it on the next pass (coalesce over dead + punched) instead of
    being stranded sub-page forever; the merged covering tombstone
    keeps later sealed entries reachable."""
    by_seg = _fill_segments(store, n=40)
    sealed = [s for s in by_seg if store._segments[s].leased_to is None]
    target = sealed[0]
    objs = by_seg[target]
    # free the MIDDLE objects, punch, then free the first one (adjacent
    # to the punched range) and punch again
    for o in objs[1:-1]:
        store.delete(o)
    out1 = store.punch_holes(min_fragmentation=0.0, min_bytes=1)
    assert out1["dead_bytes_retired"] > 0
    seg = store._segments[target]
    assert seg.punched, "first pass must leave a punched range"
    store.delete(objs[0])
    out2 = store.punch_holes(min_fragmentation=0.0, min_bytes=1)
    assert out2["dead_bytes_retired"] > 0, \
        "the newly dead neighbor must merge with the punched range"
    assert len(seg.punched) == 1, seg.punched
    # the survivor (last object, behind the merged punched range) reads
    b = store.get(objs[-1])
    assert b is not None and bytes(b.data[:2]) == b"\x09\x09"
    # and survives a restart rescan across the merged tombstone
    st2 = LocalObjectStore(store.store_dir, 128 << 20)
    b2 = st2.get(objs[-1])
    assert b2 is not None and bytes(b2.data[:2]) == b"\x09\x09"


def test_punch_disabled_when_unsupported(store, monkeypatch):
    monkeypatch.setattr(store, "_punch_probe", False)
    by_seg = _fill_segments(store, n=10)
    for o in [o for objs in by_seg.values() for o in objs]:
        store.delete(o)
    out = store.punch_holes(min_fragmentation=0.0, min_bytes=1)
    assert out == {"punched_ranges": 0, "punched_bytes": 0,
                   "dead_bytes_retired": 0, "skipped_pinned": 0,
                   "segments": 0}


# ----------------------------------------------------------------------
# kill -9 of the receiver mid-transfer (chaos): rescan discards the
# unsealed entry, a sender retry lands the object
# ----------------------------------------------------------------------

def _receiver_then_die(store_dir, oid_b, payload_len):
    """Child 'receiver raylet': seal one good object, start assembling
    another (reserve + partial chunks, NO seal), seal a SECOND good
    object BEHIND the in-flight assembly, die mid-transfer."""
    st = LocalObjectStore(store_dir, 128 << 20)
    good = ObjectID(b"G" * 28)
    st.put(good, b"", [b"g" * 100_000], 100_000)
    res = st.reserve(ObjectID(oid_b), b"meta", payload_len)
    assert res is not None
    res.write(0, b"p" * (payload_len // 3))          # partial,
    res.write(payload_len // 2, b"q" * 1000)          # out of order
    # a local put sealing AFTER the reservation (same segment, higher
    # offset): the reserve-time DEAD header lets the crash rescan hop
    # the in-flight assembly and still adopt this one
    after = ObjectID(b"A" * 28)
    st.put(after, b"", [b"a" * 100_000], 100_000)
    assert st._slab_objs[after][0] == res.seg_id, \
        "test setup: the later put must share the reservation's segment"
    os.kill(os.getpid(), signal.SIGKILL)


@pytest.mark.chaos
def test_kill9_receiver_midtransfer_rescan_discards_and_retry_lands(
        tmp_path):
    store_dir = str(tmp_path / "store")
    oid_b = os.urandom(28)
    payload_len = 2 << 20
    ctx = multiprocessing.get_context("fork")
    p = ctx.Process(target=_receiver_then_die,
                    args=(store_dir, oid_b, payload_len))
    p.start()
    p.join(30)
    assert p.exitcode == -signal.SIGKILL
    # the 'restarted raylet' rescans: BOTH sealed objects survive —
    # including the one sealed BEHIND the in-flight assembly (its
    # reserve-time DEAD header keeps the scan walking) — while the
    # unsealed assembly itself is discarded (reads as dead)
    st = LocalObjectStore(store_dir, 128 << 20)
    assert st.contains(ObjectID(b"G" * 28))
    assert st.contains(ObjectID(b"A" * 28)), \
        "entries sealed after a crashed assembly must stay adoptable"
    oid = ObjectID(oid_b)
    assert not st.contains(oid), "unsealed assembly must be discarded"
    # sender retry: the SAME oid assembles again and lands
    payload = np.arange(payload_len, dtype=np.uint8).tobytes()
    res = st.reserve(oid, b"meta", payload_len)
    assert res is not None
    half = payload_len // 2
    res.write(half, payload[half:])
    res.write(0, payload[:half])
    assert res.seal()
    buf = st.get(oid)
    assert buf is not None and bytes(buf.data) == payload


# ----------------------------------------------------------------------
# cluster-level: abandoned push sessions + ledger callsites + pipeline
# ----------------------------------------------------------------------

RAY_REUSE_CLUSTER = False


def test_expired_push_session_discards_reservation(monkeypatch):
    """Satellite: _expire_push_rx must discard the partially-written
    slab reservation of an abandoned inbound push — the bytes flip to
    dead (reclaimable) instead of leaking an unsealed entry that erodes
    capacity until restart."""
    monkeypatch.setenv("RAY_TPU_push_rx_expiry_s", "1.0")
    ray_tpu.init(num_cpus=2)
    try:
        from ray_tpu._private.worker import global_worker
        from ray_tpu.util import state

        cw = global_worker.core_worker
        total = 4 << 20

        def dead_bytes():
            arenas = state.arena_summary()
            return sum(a.get("dead_bytes") or 0 for a in arenas)

        d0 = dead_bytes()
        # half a push session straight at our raylet: metadata chunk
        # arrives, reservation is made, the rest never comes
        reply = cw.io.run(cw.raylet.request("push_chunks", {
            "object_id": os.urandom(28), "offset": 0, "total": total,
            "data": b"x" * (1 << 20), "metadata": b"m",
            "push_id": "test:abandoned",
        }))
        assert reply.get("ok") and not reply.get("assembled")
        # the heartbeat loop sweeps expired sessions (~0.5s cadence)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if dead_bytes() >= d0 + total:
                break
            time.sleep(0.5)
        assert dead_bytes() >= d0 + total, \
            "abandoned session's reservation must be tombstoned dead"
    finally:
        ray_tpu.shutdown()


def test_put_callsite_persisted_in_store_ledger():
    """Satellite (PR 12 known gap): the creation callsite rides the slab
    report into the STORE-side ledger row, so a dead owner's leak
    verdict still names the line that made the object."""
    ray_tpu.init(num_cpus=2)
    try:
        from ray_tpu._private.worker import global_worker

        cw = global_worker.core_worker
        ref = ray_tpu.put(np.zeros(300_000, np.uint8))  # CALLSITE LINE
        deadline = time.monotonic() + 15
        row = None
        while time.monotonic() < deadline:
            out = cw.io.run(cw.raylet.request("memview_node", {}))
            for proc in out["processes"]:
                for r in (proc.get("store") or {}).get("objects", ()):
                    if r["object_id"] == ref.hex():
                        row = r
            if row is not None and row.get("callsite"):
                break
            time.sleep(0.2)
        assert row is not None, "ledger row must exist"
        assert "test_transfer_plane.py" in (row.get("callsite") or ""), row
    finally:
        ray_tpu.shutdown()


def test_pipelined_fetch_out_of_order_chunks_land_exact(monkeypatch):
    """Fetch pipeline e2e: a small chunk size forces many concurrent
    in-flight chunks whose responses land out of order at their offsets
    in the reserved entry — the assembled object must be byte-exact and
    the flow row must report path="arena"."""
    monkeypatch.setenv("RAY_TPU_object_transfer_chunk_bytes", "65536")
    monkeypatch.setenv("RAY_TPU_fetch_head_chunk_bytes", "65536")
    monkeypatch.setenv("RAY_TPU_fetch_pipeline_depth", "6")
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.util import state

        me = ray_tpu.get_runtime_context().get_node_id()
        peer = next(n["node_id"] for n in ray_tpu.nodes()
                    if n["alive"] and n["node_id"] != me)

        @ray_tpu.remote
        def digest(r):
            import zlib

            return r.nbytes, zlib.crc32(bytes(r))

        arr = np.frombuffer(np.random.default_rng(7).bytes(3 << 20),
                            dtype=np.uint8)  # 48 chunks at 64KB
        import zlib

        want = (arr.nbytes, zlib.crc32(arr.tobytes()))
        ref = ray_tpu.put(arr)
        got = ray_tpu.get(digest.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(peer)
        ).remote(ref), timeout=60)
        assert tuple(got) == want, "out-of-order assembly must be exact"
        time.sleep(1.0)
        flows = state.object_summary().get("flows") or []
        fetches = [f for f in flows if f.get("kind") == "fetch"]
        assert fetches and all(f["path"] == "arena" for f in fetches), \
            fetches
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
