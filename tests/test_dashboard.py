"""Dashboard JSON API + state CLI (ray parity: dashboard HTTP routes,
`ray list` CLI)."""

import json
import subprocess
import sys
import time
import urllib.request

import ray_tpu


@ray_tpu.remote
def ping(x):
    return x


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as resp:
        return json.loads(resp.read())


def test_dashboard_json_api(ray_start_regular):
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    ray_tpu.get([ping.remote(i) for i in range(3)], timeout=60)
    port = start_dashboard()
    try:
        assert _get(port, "/api/v0/healthz")["status"] == "ok"
        nodes = _get(port, "/api/v0/nodes")
        assert nodes and nodes[0]["alive"]
        res = _get(port, "/api/v0/cluster_resources")
        assert res["total"].get("CPU", 0) > 0

        # task events flush to the GCS in batches: wait for ALL three pings
        # to be reported finished, not just the first batch
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            tasks = _get(port, "/api/v0/tasks")
            if sum(t["name"] == "ping" and t["state"] == "FINISHED"
                   for t in tasks) >= 3:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("3 finished ping tasks never appeared")
        summary = _get(port, "/api/v0/tasks/summarize")
        assert summary["ping"]["FINISHED"] >= 3
        assert isinstance(_get(port, "/api/v0/timeline"), list)
        assert isinstance(_get(port, "/api/v0/actors"), list)
    finally:
        stop_dashboard()


def test_cli_list_and_summary(ray_start_regular):
    from ray_tpu._private.worker import global_worker

    ray_tpu.get([ping.remote(i) for i in range(2)], timeout=60)
    time.sleep(3)  # task events flush
    host, port = global_worker.core_worker.gcs_addr

    from ray_tpu._private.node import package_env

    env = package_env()
    env["RAY_TPU_GCS_ADDR"] = f"{host}:{port}"
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "list", "nodes"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)[0]["alive"] is True

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "list", "tasks",
         "--filter", "state=FINISHED"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    assert all(r["state"] == "FINISHED" for r in rows)

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "summary"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert "ping" in out.stdout


def test_dashboard_profile_endpoints(ray_start_regular):
    """/api/profile/* (ray parity: the dashboard's py-spy attach button):
    cluster CPU profile in json + speedscope + collapsed formats, memory
    diff, and the SPA's Profile tab wiring."""
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def burn(seconds):
        deadline = time.monotonic() + seconds
        x = 0
        while time.monotonic() < deadline:
            x += 1
        return x

    ref = burn.remote(4.0)
    port = start_dashboard()
    try:
        prof = _get(port, "/api/profile/cpu?duration=1.0&hz=100")
        assert prof["kind"] == "cpu"
        assert prof["samples"] > 0
        assert {p["role"] for p in prof["processes"]} >= {"worker", "raylet"}
        ss = _get(port, "/api/profile/cpu?duration=0.5&format=speedscope")
        assert ss["$schema"].startswith("https://www.speedscope.app/")
        assert ss["profiles"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profile/cpu"
            "?duration=0.5&format=collapsed", timeout=60
        ) as resp:
            assert "text/plain" in resp.headers["Content-Type"]
            collapsed = resp.read().decode()
        assert all(line.rsplit(" ", 1)[1].isdigit()
                   for line in collapsed.strip().splitlines() if line)
        mem = _get(port, "/api/profile/memory?duration=0.5")
        assert mem["kind"] == "mem"
        assert isinstance(mem["sites"], list)
        # the SPA ships the Profile tab and its fetch wiring
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10
        ) as resp:
            body = resp.read().decode()
        assert "runProfile" in body and '"profile"' in body
    finally:
        stop_dashboard()
    ray_tpu.get(ref)


def test_dashboard_spa_and_full_api_surface(ray_start_regular):
    """Browser-level smoke without a browser: the SPA page serves with
    its tab structure, and EVERY endpoint the SPA fetches responds with
    valid JSON describing live cluster state."""
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    ray_tpu.get([ping.remote(i) for i in range(3)], timeout=60)
    port = start_dashboard()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10
        ) as resp:
            body = resp.read().decode()
            assert "text/html" in resp.headers["Content-Type"]
        # SPA skeleton: tab nav + the client-side pieces the pages use
        assert "ray_tpu" in body and 'id="nav"' in body
        for marker in ("overview", "timeline", "metrics", "filterBar",
                       "drawTimeline", "spark", "straggler", '"memory"',
                       "fmtBytes", '"serve"', "serve_requests",
                       "dominant phase"):
            assert marker in body, f"SPA missing {marker}"
        # every endpoint the SPA's want-map fetches must answer
        for ep in ("nodes", "actors", "tasks?limit=1000", "objects?limit=500",
                   "memory", "placement_groups", "jobs", "events?limit=200",
                   "metrics", "metrics_history", "timeline", "train",
                   "train_timeline", "serve_requests", "serve_timeline",
                   "tasks/summarize", "cluster_resources"):
            out = _get(port, f"/api/v0/{ep}")
            assert out is not None, ep
        nodes = _get(port, "/api/v0/nodes")
        assert nodes and nodes[0]["alive"]
    finally:
        stop_dashboard()
