"""Native (C++) object store tests: round-trips, Python interop, eviction.

Analog of ray: src/ray/object_manager/plasma/test/ — exercised through the
ctypes boundary instead of gtest.
"""

import os

import numpy as np
import pytest

from ray_tpu._private import native_store, object_store
from ray_tpu._private.ids import ObjectID

pytestmark = pytest.mark.skipif(
    not native_store.available(), reason="native store not built"
)


def _oid(i: int) -> ObjectID:
    return ObjectID(bytes([i]) * ObjectID.SIZE)


def test_native_write_python_read(tmp_path):
    d = str(tmp_path)
    payload = np.arange(1000, dtype=np.int64)
    native_store.write_object(
        d, _oid(1).hex(), b"meta", [payload.tobytes()], payload.nbytes
    )
    buf = object_store.read_object(d, _oid(1))
    assert buf is not None
    assert buf.metadata == b"meta"
    assert np.frombuffer(buf.data, np.int64).tolist() == payload.tolist()
    buf.release()


def test_python_write_native_read(tmp_path):
    d = str(tmp_path)
    object_store.write_object(d, _oid(2), b"m2", [b"hello", b"world"], 10)
    out = native_store.open_object(d, _oid(2).hex())
    assert out is not None
    handle, metadata, data = out
    assert metadata == b"m2"
    assert bytes(data) == b"helloworld"
    del data
    native_store.release(handle)
    assert native_store.object_exists(d, _oid(2).hex())


def test_native_store_eviction_and_pinning(tmp_path):
    d = str(tmp_path)
    store = native_store.NativeLocalObjectStore(d, capacity_bytes=4096)
    blob = b"x" * 1000
    for i in range(3):
        store.put(_oid(i + 1), b"", [blob], len(blob))
    assert store.used_bytes() <= 4096
    store.pin(_oid(3))
    # two more puts force eviction of the oldest unpinned objects
    store.put(_oid(4), b"", [blob], len(blob))
    store.put(_oid(5), b"", [blob], len(blob))
    assert store.contains(_oid(3))  # pinned survived
    assert store.used_bytes() <= 4096
    ids = {o.hex() for o in store.object_ids()}
    assert _oid(3).hex() in ids

    # everything pinned and full -> ObjectStoreFullError
    for oid in store.object_ids():
        store.pin(oid)
    with pytest.raises(object_store.ObjectStoreFullError):
        store.put(_oid(9), b"", [b"y" * 4000], 4000)


def test_native_store_zero_copy_writable_buffer(tmp_path):
    d = str(tmp_path)
    arr = np.arange(256, dtype=np.uint8)
    native_store.write_object(d, _oid(7).hex(), b"", [memoryview(arr)],
                              arr.nbytes)
    buf = object_store.read_object(d, _oid(7))
    assert bytes(buf.data) == arr.tobytes()
    buf.release()


def test_cluster_uses_native_store(tmp_path):
    """End-to-end: put/get through the runtime rides the native store."""
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    try:
        big = np.random.default_rng(0).standard_normal(100_000)
        ref = ray_tpu.put(big)
        out = ray_tpu.get(ref, timeout=30)
        np.testing.assert_array_equal(out, big)
    finally:
        ray_tpu.shutdown()


def test_native_log_store_roundtrip(tmp_path):
    """C++ append-log KV store: put/tombstone/replay/compaction across
    reopen (the GCS persistence backend; src/log_store.cpp)."""
    import pytest

    from ray_tpu._private import native_store
    from ray_tpu._private.gcs_store import NativeLogStore

    if not native_store.available():
        pytest.skip("native library unavailable")
    path = str(tmp_path / "gcs.log")
    s = NativeLogStore(path)
    for i in range(100):
        s.put("kv", ("ns", f"k{i}".encode()), f"v{i}".encode())
    for i in range(0, 100, 2):
        s.put("kv", ("ns", f"k{i}".encode()), None)  # delete evens
    s.put("actor", b"aid", {"state": "ALIVE"})
    s.close()

    s2 = NativeLogStore(path)
    tables = s2.load()
    assert len(tables["kv"]) == 50
    assert tables["kv"][("ns", b"k1")] == b"v1"
    assert ("ns", b"k0") not in tables["kv"]
    assert tables["actor"][b"aid"]["state"] == "ALIVE"
    s2.close()

    # torn tail: truncate mid-record; replay keeps the intact prefix
    import os

    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 3)
    s3 = NativeLogStore(path)
    tables = s3.load()
    assert len(tables.get("kv", {})) in (49, 50)
    s3.close()
