"""Learning tests for the round-4 algorithm additions: PG, A2C, ES, ARS,
MARWIL, CQL (ray parity: the per-algo learning tests under
rllib/algorithms/*/tests/)."""

import os

import numpy as np
import pytest

from tests.conftest import *  # noqa: F401,F403

# ES/ARS population search and CQL offline evaluation hit fixed return
# thresholds that are seed-sensitive at CPU-CI iteration budgets: the same
# commit passes or fails on rerun with no code change (observed flaking
# from the seed onward). Gate, don't fake — the deterministic loss/shape
# assertions for these algos still run unconditionally above/below; the
# threshold climbs run when explicitly requested (nightly lane).
_stochastic_learning = pytest.mark.skipif(
    os.environ.get("RAY_TPU_RUN_STOCHASTIC_LEARNING") != "1",
    reason="seed-sensitive learning threshold (flaky at CPU-CI budgets); "
    "set RAY_TPU_RUN_STOCHASTIC_LEARNING=1 to run",
)


def _train_until(algo, key, threshold, iters):
    best = -np.inf
    for _ in range(iters):
        m = algo.train()
        best = max(best, m.get(key, -np.inf))
        if best >= threshold:
            break
    return best


@pytest.mark.slow
def test_pg_learns_cartpole(ray_start_regular):
    from ray_tpu.rllib import PGConfig

    algo = (
        PGConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=0.01)
        .build()
    )
    best = _train_until(algo, "episode_return_mean", 60.0, 25)
    algo.stop()
    assert best >= 60.0, best


@pytest.mark.slow
def test_a2c_learns_cartpole(ray_start_regular):
    from ray_tpu.rllib import A2CConfig

    algo = (
        A2CConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=0.01)
        .build()
    )
    best = _train_until(algo, "episode_return_mean", 80.0, 30)
    algo.stop()
    assert best >= 80.0, best


@_stochastic_learning
def test_es_improves_cartpole(ray_start_regular):
    from ray_tpu.rllib import ESConfig

    cfg = ESConfig().environment("CartPole-native")
    cfg.population = 16
    cfg.num_env_runners = 2
    cfg.model = {"hiddens": (16,)}  # small theta: ES scales with dim
    algo = cfg.build()
    first = algo.train()["episode_return_mean"]
    best = _train_until(algo, "episode_return_mean", first + 30.0, 12)
    algo.stop()
    assert best >= first + 30.0, (first, best)


@_stochastic_learning
def test_ars_improves_cartpole(ray_start_regular):
    from ray_tpu.rllib import ARSConfig

    cfg = ARSConfig().environment("CartPole-native")
    cfg.population = 16
    cfg.ars_top_k = 4
    cfg.num_env_runners = 2
    cfg.model = {"hiddens": (16,)}
    algo = cfg.build()
    first = algo.train()["episode_return_mean"]
    best = _train_until(algo, "episode_return_mean", first + 30.0, 12)
    algo.stop()
    assert best >= first + 30.0, (first, best)


def test_es_checkpoint_restores_theta(ray_start_regular):
    """ES's flat theta is the search state: after load_checkpoint the next
    training_step must perturb the RESTORED policy, not the fresh init."""
    import jax
    from jax.flatten_util import ravel_pytree

    from ray_tpu.rllib import ESConfig

    def cfg():
        c = ESConfig().environment("CartPole-native")
        c.population = 8
        c.num_env_runners = 1
        c.model = {"hiddens": (8,)}
        return c

    a = cfg().build()
    a.train()
    ckpt = a.save_checkpoint()
    trained_theta = np.asarray(ravel_pytree(a.module.params)[0])
    a.stop()

    b = cfg().build()
    b.load_checkpoint(ckpt)
    np.testing.assert_allclose(b._theta, trained_theta, rtol=1e-6)
    b.train()  # must not explode and must evolve FROM the restored theta
    assert not np.allclose(b._theta, trained_theta)
    b.stop()


@pytest.fixture(scope="module")
def expert_dataset(ray_start_regular, tmp_path_factory):
    """Shared offline dataset: a briefly-trained PPO expert's rollouts
    (with rewards/dones/next_obs, so all offline algos can feed on it)."""
    import ray_tpu as rt
    from ray_tpu.rllib import PPOConfig
    from ray_tpu.rllib.offline import write_json

    expert = (
        PPOConfig()
        .environment("CartPole-native")
        .env_runners(num_env_runners=1, rollout_fragment_length=512)
        .training(num_epochs=6, minibatch_size=128)
        .build()
    )
    for _ in range(8):
        expert.train()
    recorded = rt.get(
        [expert.runners[0].sample.remote(512) for _ in range(2)],
        timeout=300,
    )
    path = write_json(
        recorded, str(tmp_path_factory.mktemp("offline") / "expert.jsonl")
    )
    expert.stop()
    return path


def test_marwil_beats_random(ray_start_regular, expert_dataset):
    from ray_tpu.rllib import MARWILConfig

    algo = (
        MARWILConfig()
        .environment("CartPole-native")
        .offline_data(input_=expert_dataset)
        .training(num_epochs=20, minibatch_size=256, lr=3e-3)
        .build()
    )
    for _ in range(3):
        m = algo.train()
    assert np.isfinite(m["policy_loss"])
    score = algo.evaluate()["evaluation"]["episode_return_mean"]
    algo.stop()
    assert score > 50, score


def test_marwil_beta_zero_is_bc(ray_start_regular, expert_dataset):
    """beta=0 must reduce MARWIL's policy loss to plain BC (uniform
    weights) — the documented contract of the beta knob."""
    from ray_tpu.rllib import MARWILConfig

    cfg = (
        MARWILConfig()
        .environment("CartPole-native")
        .offline_data(input_=expert_dataset)
        .training(num_epochs=1, minibatch_size=256)
    )
    cfg.beta = 0.0
    algo = cfg.build()
    m = algo.train()
    algo.stop()
    assert np.isfinite(m["policy_loss"])


@_stochastic_learning
def test_cql_beats_random(ray_start_regular, expert_dataset):
    from ray_tpu.rllib import CQLConfig

    algo = (
        CQLConfig()
        .environment("CartPole-native")
        .offline_data(input_=expert_dataset)
        .build()
    )
    for _ in range(6):
        m = algo.train()
    assert np.isfinite(m["td_loss"])
    score = algo.evaluate()["evaluation"]["episode_return_mean"]
    algo.stop()
    assert score > 50, score


@pytest.mark.slow
def test_cql_regularizer_lowers_unseen_q(ray_start_regular, expert_dataset):
    """The CQL term must push logsumexp(Q) toward the logged action's Q —
    with alpha>0 the gap shrinks vs alpha=0 over the same updates."""
    from ray_tpu.rllib import CQLConfig

    gaps = {}
    for alpha in (0.0, 2.0):
        cfg = (
            CQLConfig()
            .environment("CartPole-native")
            .offline_data(input_=expert_dataset)
        )
        cfg.cql_alpha = alpha
        cfg.num_epochs = 30
        algo = cfg.build()
        for _ in range(3):
            m = algo.train()
        gaps[alpha] = m["cql_loss"]
        algo.stop()
    assert gaps[2.0] < gaps[0.0], gaps
