"""BOHB searcher + external-searcher adapter (ray parity:
tune/search/bohb/ TuneBOHB and the tune/search/ wrapper family)."""

import math
import random
import statistics

from ray_tpu import tune
from ray_tpu.tune.search import BOHBSearcher, ExternalSearcherAdapter

import pytest


def _multi_fidelity_objective(cfg, budget):
    """Score improves with budget; the config's quality dominates at high
    budget (the BOHB setting: low fidelities are biased estimators)."""
    quality = (cfg["x"] - 1.2) ** 2 + (cfg["y"] + 2.0) ** 2
    return quality + 4.0 / budget


def _run_bohb(searcher, n_trials, max_budget=9, rf=3.0, cohort=8,
              seed=0):
    """Drive the searcher through actual successive halving (a compact
    stand-in for HyperBandForBOHB's bracket mechanics): trials run in
    cohorts; at each rung only the better 1/rf fraction advances to the
    next budget, and every stop is reported to the searcher."""
    searcher.set_search_properties(
        "loss", "min", {"x": tune.uniform(-5, 5), "y": tune.uniform(-5, 5)}
    )
    rng = random.Random(seed)
    best = float("inf")
    consumed = [0]  # total training iterations spent (the compute budget)
    tid_counter = [0]

    def new_trial():
        tid = f"t{tid_counter[0]}"
        tid_counter[0] += 1
        return tid, searcher.suggest(tid)

    remaining = n_trials
    while remaining > 0:
        size = min(cohort, remaining)
        remaining -= size
        live = [new_trial() for _ in range(size)]
        budget, prev_budget = 1, 0
        while live:
            consumed[0] += (budget - prev_budget) * len(live)
            scored = []
            for tid, cfg in live:
                loss = _multi_fidelity_objective(cfg, budget)
                searcher.on_trial_result(
                    tid, {"loss": loss, "training_iteration": budget}
                )
                scored.append((loss, rng.random(), tid, cfg))
            scored.sort()
            if budget >= max_budget:
                for loss, _r, tid, cfg in scored:
                    best = min(best, loss)
                    searcher.on_trial_complete(
                        tid,
                        result={"loss": loss, "training_iteration": budget},
                    )
                break
            keep = max(1, int(len(scored) / rf))
            for loss, _r, tid, cfg in scored[keep:]:  # stopped at the rung
                best = min(best, loss)
                searcher.on_trial_complete(
                    tid, result={"loss": loss, "training_iteration": budget}
                )
            live = [(tid, cfg) for _l, _r, tid, cfg in scored[:keep]]
            prev_budget = budget
            budget = int(budget * rf)
    return best, consumed[0]


def test_bohb_beats_random_at_equal_budget():
    """Equal TOTAL compute: random search gets consumed/max_budget full-
    fidelity evaluations — exactly the iterations BOHB spent across its
    rungs (this is the BOHB paper's comparison, and what halving buys)."""
    bohb_bests, rand_bests = [], []
    for seed in range(8):
        bohb = BOHBSearcher(n_initial_points=8, seed=seed)
        best, consumed = _run_bohb(bohb, 50, seed=seed)
        bohb_bests.append(best)

        rng = random.Random(seed + 500)
        best = float("inf")
        for _ in range(max(1, consumed // 9)):
            cfg = {"x": rng.uniform(-5, 5), "y": rng.uniform(-5, 5)}
            best = min(best, _multi_fidelity_objective(cfg, 9))
        rand_bests.append(best)
    assert statistics.fmean(bohb_bests) < statistics.fmean(rand_bests), (
        bohb_bests, rand_bests,
    )


def test_bohb_models_highest_qualified_budget():
    """The KDE model must come from the largest budget with enough data,
    never pooled across fidelities."""
    bohb = BOHBSearcher(n_initial_points=4, seed=0)
    bohb.set_search_properties("loss", "min", {"x": tune.uniform(0, 1)})
    for i in range(6):
        tid = f"t{i}"
        bohb.suggest(tid)
        bohb.on_trial_result(tid, {"loss": 1.0, "training_iteration": 1})
        if i < 3:  # only 3 trials reached budget 3
            bohb.on_trial_result(tid, {"loss": 0.5, "training_iteration": 3})
        bohb.on_trial_complete(tid, {"loss": 1.0, "training_iteration": 1})
    obs = bohb._model_obs()
    assert obs is not None
    # 6 observations at budget 1 qualify (need = max(1+2, 4) = 4);
    # budget 3 has only 3 and must not be chosen
    assert len(obs) == 6
    assert all(v == 1.0 for _c, v in obs)


def test_external_adapter_worked_example():
    """The docstring's simulated-annealing example, end to end."""

    class Annealer:
        def __init__(self, lo, hi, seed=0):
            self.rng = random.Random(seed)
            self.lo, self.hi = lo, hi
            self.best_x, self.best_v, self.temp = None, math.inf, 1.0

        def ask(self):
            if self.best_x is None:
                return {"x": self.rng.uniform(self.lo, self.hi)}
            span = (self.hi - self.lo) * self.temp
            x = min(max(self.best_x + self.rng.gauss(0, span), self.lo),
                    self.hi)
            return {"x": x}

        def tell(self, config, value, error=False):
            self.temp *= 0.9
            if not error and value < self.best_v:
                self.best_x, self.best_v = config["x"], value

    ann = Annealer(lo=-5.0, hi=5.0, seed=3)
    adapter = ExternalSearcherAdapter(ann, metric="loss", mode="min")
    best = float("inf")
    for i in range(40):
        tid = f"t{i}"
        cfg = adapter.suggest(tid)
        loss = (cfg["x"] - 2.5) ** 2
        best = min(best, loss)
        adapter.on_trial_complete(tid, result={"loss": loss})
    assert best < 0.5  # annealing actually informed by tells
    assert ann.best_x is not None

    # exhaustion: ask() returning None finishes the search
    adapter2 = ExternalSearcherAdapter(ask=lambda: None, metric="loss",
                                       mode="min")
    from ray_tpu.tune.search.searcher import Searcher

    assert adapter2.suggest("t0") == Searcher.FINISHED


@pytest.mark.slow
def test_bohb_with_tuner_and_hb_scheduler(ray_start_regular):
    """End-to-end: Tuner + HyperBandForBOHB + BOHBSearcher converge on a
    seeded objective."""
    from ray_tpu.tune import TuneConfig, Tuner
    from ray_tpu.tune.schedulers import HyperBandForBOHB

    def objective(config):
        for it in range(1, 10):
            loss = (config["x"] - 0.6) ** 2 + 2.0 / it
            tune.report({"loss": loss, "training_iteration": it})

    tuner = Tuner(
        objective,
        param_space={"x": tune.uniform(-3, 3)},
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=16,
            search_alg=BOHBSearcher(n_initial_points=6, seed=11),
            scheduler=HyperBandForBOHB(
                time_attr="training_iteration", max_t=9,
                reduction_factor=3,
            ),
        ),
    )
    results = tuner.fit()
    best = results.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 1.5, best.metrics
