"""Tracing: spans, driver->worker propagation, timeline integration.

ray parity: python/ray/tests/test_tracing.py (opt-in OTel tracing with
span context injected into task calls).
"""

import time

import ray_tpu
from ray_tpu.util import tracing


def _wait_for(fn, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.3)
    raise TimeoutError("condition not met")


def test_spans_nest_and_propagate(ray_start_regular):
    tracing.enable()
    try:
        @ray_tpu.remote
        def traced_work(x):
            return x + 1

        with tracing.span("driver-root", phase="test") as root:
            assert ray_tpu.get(traced_work.remote(1), timeout=60) == 2
            with tracing.span("inner"):
                pass
            trace_id = root["trace_id"]
        tracing.flush()

        spans = _wait_for(
            lambda: [s for s in tracing.get_spans(trace_id)
                     if s["name"] == "task::traced_work"] or None
        )
        # the worker-side execution span parents into the driver's root
        all_spans = tracing.get_spans(trace_id)
        by_name = {s["name"]: s for s in all_spans}
        assert "driver-root" in by_name and "inner" in by_name
        root_span = by_name["driver-root"]
        # span records carry span_id as task_id; inner must parent to root
        assert by_name["inner"]["parent_span_id"] == root_span["task_id"]
        task_span = spans[0]
        assert task_span["trace_id"] == trace_id
        assert task_span["parent_span_id"] is not None
        assert task_span["duration"] >= 0
    finally:
        tracing.disable()


def test_multihop_propagation(ray_start_regular):
    """A traced task's nested .remote() call stays in the same trace
    (ray: span context injected hop by hop)."""
    tracing.enable()
    try:
        @ray_tpu.remote
        def leaf():
            return "leaf"

        @ray_tpu.remote
        def mid():
            import ray_tpu as rt

            return rt.get(leaf.remote(), timeout=60)

        with tracing.span("hop-root") as root:
            assert ray_tpu.get(mid.remote(), timeout=120) == "leaf"
            trace_id = root["trace_id"]
        tracing.flush()

        spans = _wait_for(
            lambda: (lambda ss: ss if {"task::mid", "task::leaf"} <=
                     {s["name"] for s in ss} else None)(
                tracing.get_spans(trace_id))
        )
        by_name = {s["name"]: s for s in spans}
        # leaf's span parents into mid's span: same trace, chained hops
        assert by_name["task::leaf"]["parent_span_id"] == \
            by_name["task::mid"]["task_id"]
    finally:
        tracing.disable()


def test_driver_span_parents_worker_span_in_timeline(ray_start_regular):
    """e2e for the cross-process propagation path (tracing.py
    record_remote_span): a span opened on the DRIVER parents the
    worker-side execution span, and BOTH render in ray_tpu.timeline()
    output as complete slices."""
    tracing.enable()
    try:
        @ray_tpu.remote
        def traced_leaf():
            return 7

        with tracing.span("timeline-root") as root:
            assert ray_tpu.get(traced_leaf.remote(), timeout=60) == 7
            trace_id = root["trace_id"]
        tracing.flush()

        spans = _wait_for(
            lambda: [s for s in tracing.get_spans(trace_id)
                     if s["name"] == "task::traced_leaf"] or None
        )
        root_span = next(s for s in tracing.get_spans(trace_id)
                         if s["name"] == "timeline-root")
        # parentage: the worker-side execution span chains to the driver's
        assert spans[0]["parent_span_id"] == root_span["task_id"]

        trace = ray_tpu.timeline()
        by_name = {e["name"]: e for e in trace if e.get("cat") == "span"}
        assert "timeline-root" in by_name, "driver span missing in timeline"
        assert "task::traced_leaf" in by_name, "worker span missing"
        child, parent = by_name["task::traced_leaf"], by_name["timeline-root"]
        # same trace, linked parent, and the child interval nests inside
        assert child["args"]["trace_id"] == parent["args"]["trace_id"]
        assert child["args"]["parent_span_id"] == parent["args"]["span_id"]
        assert child["ts"] >= parent["ts"] - 1e3  # clock skew slack (us)
        # the limit= knob caps the raw event fetch without breaking shape
        assert isinstance(ray_tpu.timeline(limit=5), list)
    finally:
        tracing.disable()


def test_disabled_tracing_is_noop(ray_start_regular):
    tracing.disable()
    with tracing.span("nope") as rec:
        assert rec is None
    assert tracing.current_context() is None
