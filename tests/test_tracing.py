"""Tracing: spans, driver->worker propagation, timeline integration.

ray parity: python/ray/tests/test_tracing.py (opt-in OTel tracing with
span context injected into task calls).
"""

import time

import ray_tpu
from ray_tpu.util import tracing


def _wait_for(fn, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.3)
    raise TimeoutError("condition not met")


def test_spans_nest_and_propagate(ray_start_regular):
    tracing.enable()
    try:
        @ray_tpu.remote
        def traced_work(x):
            return x + 1

        with tracing.span("driver-root", phase="test") as root:
            assert ray_tpu.get(traced_work.remote(1), timeout=60) == 2
            with tracing.span("inner"):
                pass
            trace_id = root["trace_id"]
        tracing.flush()

        spans = _wait_for(
            lambda: [s for s in tracing.get_spans(trace_id)
                     if s["name"] == "task::traced_work"] or None
        )
        # the worker-side execution span parents into the driver's root
        all_spans = tracing.get_spans(trace_id)
        by_name = {s["name"]: s for s in all_spans}
        assert "driver-root" in by_name and "inner" in by_name
        root_span = by_name["driver-root"]
        # span records carry span_id as task_id; inner must parent to root
        assert by_name["inner"]["parent_span_id"] == root_span["task_id"]
        task_span = spans[0]
        assert task_span["trace_id"] == trace_id
        assert task_span["parent_span_id"] is not None
        assert task_span["duration"] >= 0
    finally:
        tracing.disable()


def test_multihop_propagation(ray_start_regular):
    """A traced task's nested .remote() call stays in the same trace
    (ray: span context injected hop by hop)."""
    tracing.enable()
    try:
        @ray_tpu.remote
        def leaf():
            return "leaf"

        @ray_tpu.remote
        def mid():
            import ray_tpu as rt

            return rt.get(leaf.remote(), timeout=60)

        with tracing.span("hop-root") as root:
            assert ray_tpu.get(mid.remote(), timeout=120) == "leaf"
            trace_id = root["trace_id"]
        tracing.flush()

        spans = _wait_for(
            lambda: (lambda ss: ss if {"task::mid", "task::leaf"} <=
                     {s["name"] for s in ss} else None)(
                tracing.get_spans(trace_id))
        )
        by_name = {s["name"]: s for s in spans}
        # leaf's span parents into mid's span: same trace, chained hops
        assert by_name["task::leaf"]["parent_span_id"] == \
            by_name["task::mid"]["task_id"]
    finally:
        tracing.disable()


def test_disabled_tracing_is_noop(ray_start_regular):
    tracing.disable()
    with tracing.span("nope") as rec:
        assert rec is None
    assert tracing.current_context() is None
