"""Remote GCS KV persistence (ray parity:
src/ray/gcs/store_client/redis_store_client.h): cluster metadata lives
on an EXTERNAL KV server (kv_server.py, the redis-analog), so losing the
head's local disk loses nothing — a restarted GCS replays its snapshot
over the wire."""

import os
import shutil
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu


@pytest.fixture
def kv_server(tmp_path):
    port_file = str(tmp_path / "kv_port")
    env = dict(os.environ)
    env["RAY_TPU_CLUSTER_TOKEN"] = "kv-secret"  # the server's own secret
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.kv_server",
         "--port", "0", "--port-file", port_file,
         "--path", str(tmp_path / "kv.log")],
        env=env,
    )
    deadline = time.time() + 20
    while not os.path.exists(port_file) and time.time() < deadline:
        time.sleep(0.1)
    assert os.path.exists(port_file), "kv server did not start"
    with open(port_file) as f:
        port = int(f.read())
    yield f":kv-secret@127.0.0.1:{port}"
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=10)


def test_remote_kv_store_roundtrip(kv_server):
    from ray_tpu._private.gcs_store import RemoteKvStore

    a = RemoteKvStore(kv_server, cluster_id="clusterA")
    b = RemoteKvStore(kv_server, cluster_id="clusterB")
    a.put("actors", "k1", {"state": "ALIVE"})
    a.put("kv", "key", b"value")
    a.put("kv", "gone", b"x")
    a.put("kv", "gone", None)  # tombstone deletes
    a.close()

    a2 = RemoteKvStore(kv_server, cluster_id="clusterA")
    snap = a2.load()
    assert snap["actors"]["k1"] == {"state": "ALIVE"}
    assert snap["kv"]["key"] == b"value"
    assert "gone" not in snap["kv"]
    # namespacing: cluster B sees nothing of A's state
    assert b.load() == {}
    a2.close()
    b.close()


def test_put_is_async_and_ordered(kv_server):
    """ADVICE medium: put() runs on the GCS event loop — it must enqueue
    and return immediately (the kv io thread drains FIFO), not pay a KV
    round trip per mutation. Ordering: a tombstone queued after a write
    must land as a tombstone."""
    from ray_tpu._private.gcs_store import RemoteKvStore

    st = RemoteKvStore(kv_server, cluster_id="async")
    t0 = time.perf_counter()
    for i in range(500):
        st.put("kv", f"k{i}", i)
    st.put("kv", "k0", None)  # tombstone AFTER the write
    enqueue_s = time.perf_counter() - t0
    # 500 synchronous round trips would take far longer than this
    assert enqueue_s < 1.0, f"put() blocked the caller: {enqueue_s:.2f}s"
    st.close()  # drains the queue

    st2 = RemoteKvStore(kv_server, cluster_id="async")
    snap = st2.load()
    assert snap["kv"]["k499"] == 499
    assert "k0" not in snap["kv"]  # FIFO: tombstone applied last
    st2.close()


def test_put_never_blocks_on_dead_server(kv_server):
    """Circuit breaker: with the KV server gone, puts keep returning
    instantly (degraded no-persist posture) and close() stays bounded —
    the GCS control plane must never stall behind persistence."""
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg
    from ray_tpu._private.gcs_store import RemoteKvStore

    st = RemoteKvStore(kv_server, cluster_id="dead")
    st.put("kv", "before", 1)
    # the fixture's proc object isn't exposed; sever the connection
    # instead — a closed conn fails requests exactly like a dead server
    time.sleep(0.2)  # let the first put flush
    st._io.run(st._conn.close(), timeout=5)

    t0 = time.perf_counter()
    for i in range(200):
        st.put("kv", f"x{i}", i)
    assert time.perf_counter() - t0 < 1.0, "puts blocked on a dead server"
    # give the drain task a beat to trip the breaker, then close bounded
    time.sleep(0.3)
    t0 = time.perf_counter()
    st.close()
    assert time.perf_counter() - t0 < cfg.gcs_kv_put_timeout_s + 2.0


@pytest.fixture
def ray_kv_cluster(kv_server, monkeypatch):
    monkeypatch.setenv("RAY_TPU_GCS_STORAGE", f"kv://{kv_server}")
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n


def test_gcs_replays_from_remote_kv_after_disk_loss(ray_kv_cluster):
    """Chaos: kill -9 the GCS, DESTROY its local session persistence
    (the simulated head-disk loss), restart — named actors and KV come
    back from the remote store."""
    cluster = ray_kv_cluster
    ray_tpu.init(address=cluster.address)

    counter = Counter.options(name="kv-survivor",
                              lifetime="detached").remote()
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1
    from ray_tpu.util.collective import collective as col

    col._kv_put(b"kv-key", b"kv-value")

    cluster.head.kill_gcs()  # SIGKILL: no flush opportunity
    # head-disk loss: every local GCS persistence artifact is gone
    session = cluster.head.session_dir
    for name in os.listdir(session):
        if "gcs" in name and os.path.isfile(os.path.join(session, name)):
            os.unlink(os.path.join(session, name))
    cluster.head.restart_gcs()

    deadline = time.monotonic() + 30
    val = None
    while time.monotonic() < deadline:
        try:
            val = col._kv_get(b"kv-key")
            if val is not None:
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert val == b"kv-value"
    handle = ray_tpu.get_actor("kv-survivor")
    assert ray_tpu.get(handle.incr.remote(), timeout=60) == 2
