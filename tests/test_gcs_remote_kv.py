"""Remote GCS KV persistence (ray parity:
src/ray/gcs/store_client/redis_store_client.h): cluster metadata lives
on an EXTERNAL KV server (kv_server.py, the redis-analog), so losing the
head's local disk loses nothing — a restarted GCS replays its snapshot
over the wire."""

import os
import shutil
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu


@pytest.fixture
def kv_server(tmp_path):
    port_file = str(tmp_path / "kv_port")
    env = dict(os.environ)
    env["RAY_TPU_CLUSTER_TOKEN"] = "kv-secret"  # the server's own secret
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.kv_server",
         "--port", "0", "--port-file", port_file,
         "--path", str(tmp_path / "kv.log")],
        env=env,
    )
    deadline = time.time() + 20
    while not os.path.exists(port_file) and time.time() < deadline:
        time.sleep(0.1)
    assert os.path.exists(port_file), "kv server did not start"
    with open(port_file) as f:
        port = int(f.read())
    yield f":kv-secret@127.0.0.1:{port}"
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=10)


def test_remote_kv_store_roundtrip(kv_server):
    from ray_tpu._private.gcs_store import RemoteKvStore

    a = RemoteKvStore(kv_server, cluster_id="clusterA")
    b = RemoteKvStore(kv_server, cluster_id="clusterB")
    a.put("actors", "k1", {"state": "ALIVE"})
    a.put("kv", "key", b"value")
    a.put("kv", "gone", b"x")
    a.put("kv", "gone", None)  # tombstone deletes
    a.close()

    a2 = RemoteKvStore(kv_server, cluster_id="clusterA")
    snap = a2.load()
    assert snap["actors"]["k1"] == {"state": "ALIVE"}
    assert snap["kv"]["key"] == b"value"
    assert "gone" not in snap["kv"]
    # namespacing: cluster B sees nothing of A's state
    assert b.load() == {}
    a2.close()
    b.close()


@pytest.fixture
def ray_kv_cluster(kv_server, monkeypatch):
    monkeypatch.setenv("RAY_TPU_GCS_STORAGE", f"kv://{kv_server}")
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n


def test_gcs_replays_from_remote_kv_after_disk_loss(ray_kv_cluster):
    """Chaos: kill -9 the GCS, DESTROY its local session persistence
    (the simulated head-disk loss), restart — named actors and KV come
    back from the remote store."""
    cluster = ray_kv_cluster
    ray_tpu.init(address=cluster.address)

    counter = Counter.options(name="kv-survivor",
                              lifetime="detached").remote()
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1
    from ray_tpu.util.collective import collective as col

    col._kv_put(b"kv-key", b"kv-value")

    cluster.head.kill_gcs()  # SIGKILL: no flush opportunity
    # head-disk loss: every local GCS persistence artifact is gone
    session = cluster.head.session_dir
    for name in os.listdir(session):
        if "gcs" in name and os.path.isfile(os.path.join(session, name)):
            os.unlink(os.path.join(session, name))
    cluster.head.restart_gcs()

    deadline = time.monotonic() + 30
    val = None
    while time.monotonic() < deadline:
        try:
            val = col._kv_get(b"kv-key")
            if val is not None:
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert val == b"kv-value"
    handle = ray_tpu.get_actor("kv-survivor")
    assert ray_tpu.get(handle.incr.remote(), timeout=60) == 2
