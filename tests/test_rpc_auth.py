"""RPC auth: pickle frames are only read from authenticated peers
(rpcio preamble; plays the reference's cluster-auth-token role)."""

import pickle
import socket

import ray_tpu

# cluster-state-mutating module: always gets (and leaves behind) a
# fresh cluster instead of joining the shared fast-lane one
RAY_REUSE_CLUSTER = False


def test_unauthenticated_peer_rejected(ray_start_regular):
    """A raw TCP client that skips the auth preamble must be disconnected
    without its pickle frame ever being dispatched."""
    import os

    from ray_tpu._private.worker import global_worker

    assert os.environ.get("RAY_TPU_CLUSTER_TOKEN"), (
        "head start must have generated a cluster token"
    )
    host, port = global_worker.core_worker.gcs_addr

    # The server holds a garbage (non-preamble) connection until
    # rpc_auth_timeout_s (10s) elapses before closing; the client must
    # wait comfortably PAST that or this test is a 10s-vs-10s coin flip
    # on a loaded box.
    s = socket.create_connection((host, port), timeout=25)
    s.settimeout(25)
    try:
        payload = pickle.dumps((1, 0, "kv_keys", {"prefix": ""}), protocol=5)
        s.sendall(len(payload).to_bytes(4, "little") + payload)
        # server must close without replying (the frame is not a preamble)
        got = b""
        try:
            while True:
                chunk = s.recv(4096)
                if not chunk:
                    break
                got += chunk
        except socket.timeout:
            raise AssertionError(
                "server kept an unauthenticated connection open"
            )
        assert got == b"", f"server answered an unauthenticated peer: {got!r}"
    finally:
        s.close()


def test_authenticated_cluster_still_works(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get(f.remote(21), timeout=60) == 42
