"""Per-node proxy fleet + gRPC ingress (analog of ray:
serve/_private/proxy_state.py tests + test_grpc proxy tests)."""

import time

import pytest
import requests

import ray_tpu
from ray_tpu import serve


def _controller():
    return ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")


def test_proxy_fleet_multi_node_and_grpc(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)  # becomes the head node
    cluster.add_node(num_cpus=2, resources={"nodeB": 1.0})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    serve.start()
    try:
        @serve.deployment(num_replicas=1)
        class Echo:
            def __call__(self, arg):
                if isinstance(arg, serve.Request):
                    return {"path": arg.path}
                return {"echo": arg}

        serve.run(Echo.bind(), name="default", route_prefix="/")

        # One proxy per alive node.
        ctrl = _controller()
        deadline = time.monotonic() + 60
        proxies = {}
        while time.monotonic() < deadline and len(proxies) < 2:
            proxies = ray_tpu.get(ctrl.get_proxies.remote(), timeout=30)
            time.sleep(1.0)
        assert len(proxies) == 2, proxies

        # Requests through EVERY node's proxy reach the app.
        for nid, info in proxies.items():
            r = requests.get(f"http://127.0.0.1:{info['port']}/ping",
                             timeout=30)
            assert r.status_code == 200, (nid, r.text)
            assert r.json()["path"] == "/ping"

        # gRPC ingress on each proxy: pickled (args, kwargs) in, pickled
        # result out, routed by "application" metadata.
        import pickle

        import grpc

        info = next(iter(proxies.values()))
        assert info["grpc_port"], info
        channel = grpc.insecure_channel(f"127.0.0.1:{info['grpc_port']}")
        call = channel.unary_unary(
            "/ray_tpu.serve.Ingress/Call",
            request_serializer=None, response_deserializer=None,
        )
        reply = call(pickle.dumps((("hello-grpc",), {})),
                     metadata=(("application", "default"),), timeout=60)
        assert pickle.loads(reply) == {"echo": "hello-grpc"}
        channel.close()

        # Kill one proxy: the app stays reachable through the OTHER
        # proxy, and the controller restarts the dead one.
        victim_nid, victim = next(iter(proxies.items()))
        other = [v for k, v in proxies.items() if k != victim_nid][0]
        ray_tpu.kill(ray_tpu.get_actor(victim["name"], namespace="serve"))
        r = requests.get(f"http://127.0.0.1:{other['port']}/alive",
                         timeout=30)
        assert r.status_code == 200

        deadline = time.monotonic() + 90
        revived = None
        while time.monotonic() < deadline:
            cur = ray_tpu.get(ctrl.get_proxies.remote(), timeout=30)
            ent = cur.get(victim_nid)
            if ent is not None:
                try:
                    r = requests.get(
                        f"http://127.0.0.1:{ent['port']}/back", timeout=10
                    )
                    if r.status_code == 200:
                        revived = ent
                        break
                except Exception:
                    pass
            time.sleep(1.5)
        assert revived is not None, "killed proxy was not restarted"
    finally:
        serve.shutdown()


def test_grpc_streaming(ray_start_cluster):
    """Server-streaming gRPC: a generator deployment's chunks arrive as
    individual messages (not one drained blob)."""
    import pickle

    import grpc

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    serve.start()
    try:
        @serve.deployment(num_replicas=1)
        class Tokens:
            def __call__(self, n):
                for i in range(int(n)):
                    yield f"tok{i}"

        serve.run(Tokens.bind(), name="default", route_prefix="/")
        ctrl = _controller()
        info = next(iter(
            ray_tpu.get(ctrl.get_proxies.remote(), timeout=30).values()
        ))
        channel = grpc.insecure_channel(f"127.0.0.1:{info['grpc_port']}")
        stream = channel.unary_stream(
            "/ray_tpu.serve.Ingress/Stream",
            request_serializer=None, response_deserializer=None,
        )
        chunks = [pickle.loads(m) for m in stream(
            pickle.dumps(((4,), {})),
            metadata=(("application", "default"),), timeout=60,
        )]
        assert chunks == ["tok0", "tok1", "tok2", "tok3"]

        # non-generator target: single message
        @serve.deployment(num_replicas=1)
        class One:
            def __call__(self, x):
                return {"v": x}

        serve.run(One.bind(), name="one", route_prefix="/one")
        chunks = [pickle.loads(m) for m in stream(
            pickle.dumps((("a",), {})),
            metadata=(("application", "one"),), timeout=60,
        )]
        assert chunks == [{"v": "a"}]
        channel.close()
    finally:
        serve.shutdown()
