"""Model-family tests: Llama (train/decode/TP), ViT, ResNet.

Parity model: the reference trains/serves these families through torch
integrations (ray: release/air_tests/air_benchmarks/workloads/,
python/ray/serve release LLM tests); here they are native flax modules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama, vision


def test_llama_train_step_loss_decreases():
    cfg = llama.LlamaConfig.small_test()
    model, params = llama.init_params(cfg, jax.random.PRNGKey(0))
    import optax

    tx = optax.adamw(1e-2)
    opt_state = tx.init(params)
    step = llama.build_train_step(model, tx, donate=False)
    batch = llama.synthetic_batch(jax.random.PRNGKey(1), 4, 32, cfg.vocab_size)
    first = None
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_llama_decode_matches_full_pass():
    """KV-cache decode must produce the same logits as the full causal
    pass — the correctness contract for the serving path."""
    cfg = llama.LlamaConfig.small_test()
    model, params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    full_logits, _ = model.apply({"params": params}, ids)

    caches = llama.init_kv_caches(cfg, 2, max_len=16)
    decode = llama.build_decode_step(model)
    for t in range(ids.shape[1]):
        logits, caches = decode(params, ids[:, t:t + 1], jnp.int32(t), caches)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full_logits[:, -1, :]),
        rtol=0.05, atol=0.05,  # bf16 compute
    )


def test_llama_generate_greedy():
    cfg = llama.LlamaConfig.small_test()
    model, params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    out = llama.generate(model, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 10)
    assert (np.asarray(out[:, :4]) == np.asarray(prompt)).all()
    # prefill correctness: the first generated token must equal the argmax
    # of the FULL causal pass over the prompt (regression: the cache-branch
    # mask once let prefill queries attend only to position 0)
    full_logits, _ = model.apply({"params": params}, prompt)
    expect = np.asarray(jnp.argmax(full_logits[:, -1, :], axis=-1))
    assert (np.asarray(out[:, 4]) == expect).all()
    # temperature>0 without an rng is a usage error, not a crash deep in jax
    with pytest.raises(ValueError):
        llama.generate(model, params, prompt, 2, temperature=0.5)


def test_llama_gqa_heads():
    """n_kv_head < n_head (grouped-query) must broadcast correctly."""
    cfg = llama.LlamaConfig.small_test(n_head=4, n_kv_head=1)
    model, params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.zeros((1, 8), dtype=jnp.int32)
    logits, _ = model.apply({"params": params}, ids)
    assert logits.shape == (1, 8, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits, dtype=np.float32)).all()


def test_llama_tp_sharding_specs():
    from ray_tpu.parallel.mesh_utils import create_mesh

    mesh = create_mesh({"model": 2})
    cfg = llama.LlamaConfig.small_test()
    model, params = llama.init_params(cfg, jax.random.PRNGKey(0))
    shardings = llama.shard_params_tp(params, mesh)
    qspec = shardings["h_0"]["attn"]["q_proj"]["kernel"].spec
    ospec = shardings["h_0"]["attn"]["o_proj"]["kernel"].spec
    assert qspec == jax.sharding.PartitionSpec(None, "model")
    assert ospec == jax.sharding.PartitionSpec("model", None)
    # placed forward pass still agrees with the unsharded one
    placed = jax.tree.map(jax.device_put, params, shardings)
    ids = jnp.zeros((1, 8), dtype=jnp.int32)
    a, _ = model.apply({"params": params}, ids)
    b, _ = model.apply({"params": placed}, ids)
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32), rtol=2e-2,
                               atol=2e-2)


def test_vit_forward_and_train():
    cfg = vision.ViTConfig.small_test()
    model = vision.ViT(cfg)
    params, tx, opt_state = vision.make_train_state(
        model, cfg, jax.random.PRNGKey(0), learning_rate=1e-2
    )
    step = vision.build_train_step(model, tx, donate=False)
    batch = vision.synthetic_image_batch(jax.random.PRNGKey(1), 8,
                                         cfg.image_size, cfg.num_classes)
    first = None
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_resnet_forward_and_train():
    cfg = vision.ResNetConfig.small_test()
    model = vision.ResNet(cfg)
    params, tx, opt_state = vision.make_train_state(
        model, cfg, jax.random.PRNGKey(0), learning_rate=1e-2
    )
    step = vision.build_train_step(model, tx, donate=False)
    batch = vision.synthetic_image_batch(jax.random.PRNGKey(1), 8, 32,
                                         cfg.num_classes)
    first = None
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_resnet50_config_shapes():
    cfg = vision.ResNetConfig.resnet50_cifar()
    assert cfg.stage_sizes == (3, 4, 6, 3)
    assert cfg.num_classes == 10


def test_gpt2_chunked_loss_matches_fused():
    """The bench's default loss path (loss_chunks>0) must agree with the
    fused [B,T,V] loss in value AND gradients, masked and unmasked."""
    from ray_tpu.models import gpt2

    cfg = gpt2.GPT2Config.small_test()
    cfgc = gpt2.GPT2Config.small_test(loss_chunks=4)
    model, params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    modelc = gpt2.GPT2(cfgc)
    batch = gpt2.synthetic_batch(jax.random.PRNGKey(1), 2, 64, cfg.vocab_size)
    for mask in (None, (jnp.arange(64)[None, :] < 48).astype(jnp.float32)
                 * jnp.ones((2, 1))):
        b = dict(batch)
        if mask is not None:
            b["mask"] = mask
        l1, g1 = jax.value_and_grad(gpt2.loss_fn)(params, model, b)
        l2, g2 = jax.value_and_grad(gpt2.loss_fn)(params, modelc, b)
        assert abs(float(l1) - float(l2)) < 1e-3
        diffs = jax.tree.map(lambda a, c: float(jnp.abs(a - c).max()), g1, g2)
        assert max(jax.tree.leaves(diffs)) < 1e-2


def test_flash_pallas_interpret_tiny_seq():
    """Regression for the TPU blockspec failure at trace-time shapes: the
    lane-broadcast lse layout must lower for q_len < 128 (model init traces
    with a seq-8 dummy) and for b*h not a multiple of 8."""
    from ray_tpu.ops import attention as A

    q, k, v = (
        jax.random.normal(kk, (1, 12, 8, 64), jnp.float32)
        for kk in jax.random.split(jax.random.PRNGKey(0), 3)
    )
    out = A.flash_attention(q, k, v, causal=True, impl="pallas_interpret")
    ref = A.attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_llama_7b_param_count():
    cfg = llama.LlamaConfig.llama2_7b()
    n = cfg.num_params()
    assert 6.0e9 < n < 7.5e9, n
