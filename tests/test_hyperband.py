"""Cohort-synchronous HyperBand semantics (ray parity:
python/ray/tune/tests/test_trial_scheduler.py HyperBand cases)."""

import numpy as np
import pytest

from tests.conftest import *  # noqa: F401,F403
from ray_tpu.tune.schedulers import HyperBandScheduler
from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _FakeTrial:
    def __init__(self, tid):
        self.trial_id = tid
        self.last_result = {}
        self.status = "RUNNING"


class _FakeController:
    def __init__(self, trials):
        self._trials = {t.trial_id: t for t in trials}
        self.stopped = []

    def get_trial(self, tid):
        return self._trials.get(tid)

    def stop_trial(self, trial, result=None):
        self.stopped.append(trial.trial_id)


def _result(it, score):
    return {"training_iteration": it, "score": score}


def test_bracket_geometry():
    hb = HyperBandScheduler(metric="score", mode="max", max_t=9.0,
                            reduction_factor=3.0)
    trials = [_FakeTrial(f"t{i}") for i in range(12)]
    ctl = _FakeController(trials)
    for t in trials:
        hb.on_trial_add(ctl, t)
    # s_max=2: bracket 0 (s=2) n=9 r0=1; bracket 1 (s=1) n=5 r0=3 (ceil(1.5*3))
    b0, b1 = hb._brackets[0], hb._brackets[1]
    assert b0.capacity == 9 and b0.milestones == [1.0, 3.0, 9.0]
    assert b1.capacity == 5 and b1.milestones == [3.0, 9.0]
    assert len(hb._brackets) == 2  # 12 trials: 9 + 3 of 5


def test_synchronous_promotion_waits_for_cohort():
    """No trial advances past a rung until EVERY live member reported —
    the defining difference from ASHA."""
    hb = HyperBandScheduler(metric="score", mode="max", max_t=9.0,
                            reduction_factor=3.0)
    trials = [_FakeTrial(f"t{i}") for i in range(9)]
    ctl = _FakeController(trials)
    for t in trials:
        hb.on_trial_add(ctl, t)

    # first 8 report at the rung-0 milestone: all must PAUSE (cohort open)
    for i in range(8):
        d = hb.on_trial_result(ctl, trials[i], _result(1, score=i))
        assert d == TrialScheduler.PAUSE, (i, d)
        assert not hb.may_resume(trials[i])
    assert ctl.stopped == []

    # the 9th (best) report completes the cohort: top ceil(9/3)=3 promoted
    d = hb.on_trial_result(ctl, trials[8], _result(1, score=100))
    assert d == TrialScheduler.CONTINUE  # last reporter won: stays hot
    # losers t0..t5 stopped; winners t6, t7 now resumable
    assert sorted(ctl.stopped) == [f"t{i}" for i in range(6)]
    assert hb.may_resume(trials[6]) and hb.may_resume(trials[7])


def test_dead_member_does_not_block_cohort():
    hb = HyperBandScheduler(metric="score", mode="max", max_t=9.0,
                            reduction_factor=3.0)
    trials = [_FakeTrial(f"t{i}") for i in range(9)]
    ctl = _FakeController(trials)
    for t in trials:
        hb.on_trial_add(ctl, t)
    for i in range(8):
        hb.on_trial_result(ctl, trials[i], _result(1, score=i))
    # the 9th member dies before reporting: the cohort must settle anyway
    hb.on_trial_error(ctl, trials[8])
    assert sorted(ctl.stopped) == [f"t{i}" for i in range(5)]  # keep top 3 of 8
    assert hb.may_resume(trials[5]) or not any(
        hb.may_resume(trials[i]) for i in range(5)
    )
    assert hb.may_resume(trials[6]) and hb.may_resume(trials[7])


def test_straggler_join_does_not_corrupt_settled_rung():
    """A trial joining a non-full bracket after its rung-0 cohort settled
    must be ranked on its own cohort — never demote or re-promote trials
    already moved to higher rungs (regression: promote() once re-ranked
    ALL recorded scores)."""
    hb = HyperBandScheduler(metric="score", mode="max", max_t=9.0,
                            reduction_factor=3.0)
    a, b_ = _FakeTrial("a"), _FakeTrial("b")
    ctl = _FakeController([a, b_])
    hb.on_trial_add(ctl, a)
    hb.on_trial_add(ctl, b_)
    bracket = hb._brackets[0]
    # both report rung 0: cohort of 2 settles, a (best) promoted, b stopped
    hb.on_trial_result(ctl, b_, _result(1, score=1.0))
    d = hb.on_trial_result(ctl, a, _result(1, score=5.0))
    assert d == TrialScheduler.CONTINUE
    assert bracket.rung_of["a"] == 1 and "b" not in bracket.live

    # straggler c joins the same (non-full) bracket and reports rung 0
    c = _FakeTrial("c")
    ctl._trials["c"] = c
    hb.on_trial_add(ctl, c)
    assert hb._bracket_of["c"] is bracket
    hb.on_trial_result(ctl, c, _result(1, score=99.0))
    # a must still be at rung 1, not demoted, and never stopped
    assert bracket.rung_of["a"] == 1
    assert "a" not in ctl.stopped


def test_hyperband_e2e_tuner(ray_start_regular):
    """End-to-end through Tuner: separable objective, HyperBand finds a
    near-optimal x while stopping most trials early."""
    from ray_tpu import tune
    from ray_tpu.tune import TuneConfig, Tuner

    def objective(config):
        for it in range(1, 10):
            tune.report({"loss": (config["x"] - 0.7) ** 2 + 1.0 / it,
                         "training_iteration": it})

    tuner = Tuner(
        objective,
        param_space={"x": tune.uniform(-2, 2)},
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=14,
            search_alg=None,
            scheduler=HyperBandScheduler(
                time_attr="training_iteration", max_t=9, reduction_factor=3
            ),
        ),
    )
    results = tuner.fit()
    best = results.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 1.6, best.metrics
    # early stopping actually happened: some trials ran < max_t iterations
    iters = [r.metrics.get("training_iteration", 0) for r in results]
    assert min(iters) < 9, iters
