"""Usage stats (reference parity: _private/usage/usage_lib.py — inverted
default: local-only, never phones home)."""

import json
import os

# cluster-state-mutating module: always gets (and leaves behind) a
# fresh cluster instead of joining the shared fast-lane one
RAY_REUSE_CLUSTER = False


def test_usage_snapshot_written_on_head_init(ray_start_regular):
    import ray_tpu
    from ray_tpu._private.worker import global_worker

    path = os.path.join(global_worker.node.session_dir, "usage_stats.json")
    assert os.path.exists(path)
    payload = json.load(open(path))
    assert payload["source"] == "ray_tpu"
    assert payload["total_num_nodes"] >= 1
    assert payload["total_num_cpus"] >= 1
    assert "python_version" in payload


def test_usage_stats_opt_out(monkeypatch):
    from ray_tpu._private import usage_lib

    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
    assert not usage_lib.usage_stats_enabled()
    monkeypatch.delenv("RAY_TPU_USAGE_STATS_ENABLED")
    monkeypatch.setenv("RAY_USAGE_STATS_ENABLED", "false")
    assert not usage_lib.usage_stats_enabled()


def test_no_report_without_operator_url(monkeypatch):
    from ray_tpu._private import usage_lib

    monkeypatch.delenv("RAY_TPU_USAGE_STATS_REPORT_URL", raising=False)
    assert usage_lib.maybe_report({"x": 1}) is False
