"""Continuous control: TD3/DDPG learning regression on Reacher1D-native
(ray parity: rllib/algorithms/td3, /ddpg — tuned_examples-style check)."""

import numpy as np
import pytest

from ray_tpu.rllib import DDPGConfig, TD3Config


def _train(config_cls, iters, **training):
    cfg = (
        config_cls()
        .environment("Reacher1D-native")
        .env_runners(num_env_runners=1, rollout_fragment_length=240)
        .training(**training)
        .debugging(seed=1)
    )
    algo = cfg.build()
    last = {}
    returns = []
    for _ in range(iters):
        last = algo.train()
        if "episode_return_mean" in last:
            returns.append(last["episode_return_mean"])
    score = algo.evaluate()["evaluation"]["episode_return_mean"]
    ckpt = algo.save_checkpoint()
    return score, returns, ckpt, algo


def test_td3_learns_reacher(ray_start_regular):
    score, returns, ckpt, algo = _train(
        TD3Config, iters=8, warmup_steps=300,
        num_steps_sampled_before_learning=300, num_epochs=30,
    )
    try:
        # Random policy averages ~ -20 per 60-step episode; a trained actor
        # that homes in on the target stays above -8.
        assert score > -8.0, (score, returns)
        # checkpoint roundtrip keeps the trained actor (runners still live:
        # load_checkpoint re-syncs weights to them)
        algo.load_checkpoint(ckpt)
        a = algo.compute_single_action(np.array([0.5, -0.5], np.float32))
        assert a.shape == (1,) and -1.0 <= float(a[0]) <= 1.0
    finally:
        algo.cleanup()


def test_ddpg_runs_and_improves(ray_start_regular):
    score, returns, _, algo = _train(
        DDPGConfig, iters=6, warmup_steps=300,
        num_steps_sampled_before_learning=300, num_epochs=25,
    )
    algo.cleanup()
    assert score > -12.0, (score, returns)


def test_td3_rejects_discrete_env(ray_start_regular):
    cfg = TD3Config().environment("CartPole-native")
    with pytest.raises(ValueError, match="continuous"):
        cfg.build().train()
