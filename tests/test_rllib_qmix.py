"""QMIX: monotonic value-mixing MARL (ray parity: rllib/algorithms/qmix),
validated on the paper's two-step coordination game — the canonical case
where per-agent greedy values pick the wrong branch without a
state-conditioned mixer."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import QMIXConfig, TwoStepCoopGame


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_two_step_game_payoffs():
    env = TwoStepCoopGame({})
    obs, _ = env.reset()
    assert set(obs) == {"agent_0", "agent_1"}
    # branch B + joint (1,1) pays the optimum 8
    env.reset()
    env.step({"agent_0": 1, "agent_1": 0})
    _, rew, term, _, _ = env.step({"agent_0": 1, "agent_1": 1})
    assert rew["agent_0"] == 8.0 and term["__all__"]
    # branch A pays a flat 7
    env.reset()
    env.step({"agent_0": 0, "agent_1": 0})
    _, rew, _, _, _ = env.step({"agent_0": 0, "agent_1": 1})
    assert rew["agent_0"] == 7.0


def test_mixer_monotonic_in_agent_qs():
    from ray_tpu.rllib.qmix import QMixModule

    m = QMixModule(obs_dim=3, n_agents=2, num_actions=2, state_dim=3, seed=0)
    state = np.eye(3, dtype=np.float32)[:1]
    base = np.array([[1.0, 1.0]], np.float32)
    import jax.numpy as jnp

    q0 = m.mixer.apply({"params": m.params["mixer"]},
                       jnp.asarray(base), jnp.asarray(state))
    for i in range(2):
        bumped = base.copy()
        bumped[0, i] += 1.0
        qi = m.mixer.apply({"params": m.params["mixer"]},
                           jnp.asarray(bumped), jnp.asarray(state))
        assert float(qi[0]) >= float(q0[0]) - 1e-6  # dQtot/dq_a >= 0


@pytest.mark.slow
def test_qmix_solves_two_step_game(ray_cluster):
    cfg = (
        QMIXConfig()
        .environment(TwoStepCoopGame)
        .env_runners(num_env_runners=1, rollout_fragment_length=64)
        .training(lr=5e-3, minibatch_size=64, num_epochs=8,
                  num_steps_sampled_before_learning=128,
                  target_network_update_freq=128)
        .debugging(seed=3)
    )
    algo = cfg.build()
    try:
        solved = False
        for _ in range(40):
            algo.train()
            # greedy rollout: must pick branch B then coordinate on (1,1)
            env = TwoStepCoopGame({})
            obs, _ = env.reset()
            acts = algo.compute_actions(obs)
            obs, _, _, _, _ = env.step(acts)
            acts2 = algo.compute_actions(obs)
            _, rew, _, _, _ = env.step(acts2)
            if rew["agent_0"] == 8.0:
                solved = True
                break
        assert solved, "QMIX failed to find the coordinated optimum (8)"
    finally:
        algo.stop()
