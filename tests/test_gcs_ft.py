"""GCS fault-tolerance chaos tests.

Analog of ray: python/ray/tests/test_gcs_fault_tolerance.py — kill the GCS
mid-job, restart it, and assert the cluster resumes: the replayed store
restores actors/KV/jobs, raylets reconnect and reclaim their running
actors, and new work schedules normally.
"""

import time

import pytest

import ray_tpu


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n

    def getpid(self):
        import os

        return os.getpid()


@ray_tpu.remote
def add(a, b):
    return a + b


def _gcs_alive(port, timeout=30.0):
    from ray_tpu._private.rpcio import EventLoopThread, connect

    io = EventLoopThread("gcs-probe")
    try:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                conn = io.run(connect("127.0.0.1", port, retries=1))
                io.run(conn.request("get_nodes", {}))
                io.run(conn.close())
                return True
            except Exception:
                time.sleep(0.2)
        return False
    finally:
        io.stop()


def test_gcs_restart_resumes_cluster(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    # Pre-outage state: a named actor with counter state, and KV content.
    counter = Counter.options(name="survivor", lifetime="detached").remote()
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1
    from ray_tpu.util.collective import collective as col

    col._kv_put(b"ft-key", b"ft-value")

    # Kill the GCS mid-job; actor calls go worker->worker directly and must
    # keep working during the outage.
    cluster.head.kill_gcs()
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 2

    cluster.head.restart_gcs()
    assert _gcs_alive(cluster.head.gcs_port)

    # KV replayed from the persist log.
    deadline = time.monotonic() + 30
    val = None
    while time.monotonic() < deadline:
        try:
            val = col._kv_get(b"ft-key")
            if val is not None:
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert val == b"ft-value"

    # The raylet reconnected and reclaimed the running actor: the replayed
    # record must come back ALIVE (not restarted — state intact).
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 3
    handle = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(handle.incr.remote(), timeout=60) == 4

    # New tasks and new actors schedule normally after failover.
    assert ray_tpu.get(add.remote(2, 3), timeout=60) == 5
    c2 = Counter.remote()
    assert ray_tpu.get(c2.incr.remote(), timeout=60) == 1


def test_gcs_restart_restarts_lost_actor(ray_start_cluster, monkeypatch):
    """An actor whose worker died DURING the GCS outage is failed over by
    the restarted GCS once the reconnect window closes."""
    # The flag must reach the restarted GCS subprocess via its env.
    monkeypatch.setenv("RAY_TPU_gcs_failover_reconnect_timeout_s", "2.0")
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    actor = Counter.options(max_restarts=1, name="phoenix").remote()
    assert ray_tpu.get(actor.incr.remote(), timeout=60) == 1
    pid = ray_tpu.get(actor.getpid.remote(), timeout=60)

    cluster.head.kill_gcs()
    # Kill the actor's worker process while the GCS is down: nobody can
    # observe the death until the GCS is back and the raylet re-reports.
    import os
    import signal

    os.kill(pid, signal.SIGKILL)

    cluster.head.restart_gcs()
    assert _gcs_alive(cluster.head.gcs_port)

    # After failover the actor restarts (max_restarts=1) and serves calls;
    # its in-memory counter reset.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            val = ray_tpu.get(actor.incr.remote(), timeout=10)
            assert val >= 1
            return
        except Exception:
            time.sleep(0.5)
    pytest.fail("actor was not restarted after GCS failover")


def test_object_transfer_survives_gcs_outage(ray_start_cluster):
    """Ownership-based object directory (ray:
    ownership_based_object_directory.h): the owner — not the GCS — is the
    authority on object locations, so a cross-node pull must succeed while
    the GCS is down, and a GCS restart mid-transfer needs no location
    replay before pulls resume."""
    import numpy as np

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"nodeB": 2.0})
    ray_tpu.init(address=cluster.address)

    # A plasma object owned by this driver, stored on the head node.
    arr = np.arange(500_000, dtype=np.int64)
    ref = ray_tpu.put(arr)

    @ray_tpu.remote(resources={"nodeB": 0.1})
    def consume(x):
        return int(x.sum())

    # Warm nodeB's worker pool and the peer conns while the GCS is still
    # up (a cold worker spawn blocks on GCS registration until it's back).
    assert ray_tpu.get(consume.remote(ray_tpu.put(np.int64(3))), timeout=60) == 3
    assert ray_tpu.get(add.remote(1, 1), timeout=60) == 2

    cluster.head.kill_gcs()
    # nodeB's raylet has never seen `ref`; resolving it requires a
    # location lookup, which must be served by the owner (this driver).
    assert ray_tpu.get(consume.remote(ref), timeout=90) == int(arr.sum())

    cluster.head.restart_gcs()
    assert _gcs_alive(cluster.head.gcs_port)

    # Driver's GCS conn AND the raylet's re-registration are both
    # asynchronous after the restart: wait for a live node to show up, not
    # merely for the first successful (possibly still-empty) response.
    deadline = time.monotonic() + 30
    stats = None
    while time.monotonic() < deadline:
        try:
            stats = ray_tpu.nodes()
            if stats and any(n["alive"] for n in stats):
                break
        except Exception:
            pass
        time.sleep(0.3)
    assert stats and any(n["alive"] for n in stats)
    ref2 = ray_tpu.put(np.arange(200_000, dtype=np.int64))
    assert ray_tpu.get(consume.remote(ref2), timeout=90) == int(
        np.arange(200_000, dtype=np.int64).sum()
    )


def test_sqlite_store_roundtrip(tmp_path, monkeypatch):
    """SqliteStore: upserts, tombstones, reopen-and-load, and cluster
    ownership (a NEW cluster must not resurrect the old one's state)."""
    monkeypatch.delenv("RAY_TPU_GCS_STORAGE", raising=False)
    from ray_tpu._private.gcs_store import SqliteStore, make_store

    path = str(tmp_path / "sub" / "gcs.sqlite")
    st = make_store(f"sqlite://{path}")
    assert isinstance(st, SqliteStore)
    st.put("actors", b"a1", {"state": "ALIVE"})
    st.put("kv", ("ns", b"k"), b"v")
    st.put("actors", b"a2", {"state": "DEAD"})
    st.put("actors", b"a2", None)  # tombstone
    st.close()

    st2 = SqliteStore(path)
    tables = st2.load()
    assert tables["actors"] == {b"a1": {"state": "ALIVE"}}
    assert tables["kv"][("ns", b"k")] == b"v"
    st2.close()

    # same cluster id: state replays; different cluster id: wiped.
    st3 = SqliteStore(path, cluster_id="cluster-A")
    assert st3.load()["actors"] == {b"a1": {"state": "ALIVE"}}
    st3.close()
    st4 = SqliteStore(path, cluster_id="cluster-A")
    assert st4.load()["actors"] == {b"a1": {"state": "ALIVE"}}
    st4.close()
    st5 = SqliteStore(path, cluster_id="cluster-B")
    assert st5.load() == {}
    st5.close()


def test_gcs_kill9_restart_against_sqlite(ray_start_cluster, monkeypatch,
                                          tmp_path):
    """kill -9 the GCS and restart it against the EXTERNAL sqlite store:
    jobs/actors/KV/PGs come back intact even though the session-dir log
    was never written (reference analog: RedisStoreClient failover)."""
    monkeypatch.setenv(
        "RAY_TPU_GCS_STORAGE", f"sqlite://{tmp_path}/external_gcs.sqlite"
    )
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    counter = Counter.options(name="sq-survivor",
                              lifetime="detached").remote()
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1
    from ray_tpu.util.collective import collective as col

    col._kv_put(b"sq-key", b"sq-value")
    # the external store is the one being written, not the session log
    import os

    assert os.path.exists(f"{tmp_path}/external_gcs.sqlite")

    cluster.head.kill_gcs()  # SIGKILL, no flush opportunity
    cluster.head.restart_gcs()
    assert _gcs_alive(cluster.head.gcs_port)

    deadline = time.monotonic() + 30
    val = None
    while time.monotonic() < deadline:
        try:
            val = col._kv_get(b"sq-key")
            if val is not None:
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert val == b"sq-value"
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 2
    handle = ray_tpu.get_actor("sq-survivor")
    assert ray_tpu.get(handle.incr.remote(), timeout=60) == 3
