"""MLflow / W&B logger callbacks (ray parity: air/integrations/) —
tested against stub client libraries injected into sys.modules, since
the real ones are not installed in this image."""

import sys
import types
from unittest import mock

import pytest


class _Trial:
    def __init__(self, tid="t1", config=None):
        self.trial_id = tid
        self.config = config or {"lr": 0.1}

    def __str__(self):
        return f"trial_{self.trial_id}"


def _stub_mlflow():
    mlflow = types.ModuleType("mlflow")
    tracking = types.ModuleType("mlflow.tracking")
    client = mock.MagicMock()
    client.get_experiment_by_name.return_value = None
    client.create_experiment.return_value = "exp1"
    run = mock.MagicMock()
    run.info.run_id = "run1"
    client.create_run.return_value = run

    class MlflowClient:
        def __new__(cls, *a, **k):
            return client

    tracking.MlflowClient = MlflowClient
    mlflow.set_tracking_uri = mock.MagicMock()
    mlflow.tracking = tracking
    return mlflow, tracking, client


def test_mlflow_callback_lifecycle(monkeypatch):
    mlflow, tracking, client = _stub_mlflow()
    monkeypatch.setitem(sys.modules, "mlflow", mlflow)
    monkeypatch.setitem(sys.modules, "mlflow.tracking", tracking)
    from ray_tpu.air.integrations import MLflowLoggerCallback

    cb = MLflowLoggerCallback(experiment_name="e2e")
    trial = _Trial()
    cb.on_trial_start(trial)
    client.create_run.assert_called_once()
    client.log_param.assert_any_call("run1", "lr", 0.1)
    cb.on_trial_result(trial, {"score": 1.5, "training_iteration": 3,
                               "note": "text-skipped"})
    client.log_metric.assert_any_call("run1", "score", 1.5, step=3)
    # non-numeric values never reach the tracker
    for call in client.log_metric.call_args_list:
        assert call.args[1] != "note"
    cb.on_trial_complete(trial)
    client.set_terminated.assert_called_once_with("run1", status="FINISHED")


def test_mlflow_missing_library_fails_at_construction(monkeypatch):
    monkeypatch.setitem(sys.modules, "mlflow", None)
    from ray_tpu.air.integrations import MLflowLoggerCallback

    with pytest.raises(ImportError):
        MLflowLoggerCallback()


def test_wandb_callback_lifecycle(monkeypatch):
    wandb = types.ModuleType("wandb")
    run = mock.MagicMock()
    wandb.init = mock.MagicMock(return_value=run)
    monkeypatch.setitem(sys.modules, "wandb", wandb)
    from ray_tpu.air.integrations import WandbLoggerCallback

    cb = WandbLoggerCallback(project="p")
    trial = _Trial()
    cb.on_trial_start(trial)
    wandb.init.assert_called_once()
    cb.on_trial_result(trial, {"score": 2.0})
    run.log.assert_called_once_with({"score": 2.0})
    cb.on_trial_error(trial)
    run.finish.assert_called_once_with(exit_code=1)
