"""Container runtime-env plugin (ray parity:
_private/runtime_env/container.py): the raylet wraps worker commands in
a container-engine invocation. Docker/podman aren't in this image, so a
FAKE engine (a script that records its argv, then execs the inner worker
command) proves the wrapping end to end through a real cluster."""

import json
import os
import stat
import sys

import pytest

import ray_tpu
from ray_tpu._private.runtime_env import build_container_command

# spawns per-container workers with custom cfg: needs its own cluster
RAY_REUSE_CLUSTER = False


def test_validate_rejects_malformed_container():
    from ray_tpu._private.runtime_env import _ContainerPlugin

    p = _ContainerPlugin()
    p.validate({})  # absent: fine
    with pytest.raises(ValueError, match="image"):
        p.validate({"container": {"run_options": []}})
    with pytest.raises(ValueError, match="image"):
        p.validate({"container": "myimage"})
    with pytest.raises(ValueError, match="run_options"):
        p.validate({"container": {"image": "x", "run_options": "-it"}})
    p.validate({"container": {"image": "x",
                              "run_options": ["--gpus", "all"]}})


def test_build_container_command_shape():
    env = {"RAY_TPU_GCS_ADDR": "127.0.0.1:1234",
           "RAY_TPU_SESSION_DIR": "/dev/shm/ray_tpu/session_x",
           "JAX_PLATFORMS": "cpu", "HOME": "/root",
           "MY_APP_FLAG": "on"}
    cmd = build_container_command(
        {"image": "myimg:v1", "engine": "podman",
         "run_options": ["--cap-drop", "ALL"]},
        env, ["python", "-m", "ray_tpu._private.worker_main"],
        extra_env_keys=("MY_APP_FLAG",), cidfile="/tmp/x.cid",
    )
    assert cmd[0] == "podman" and cmd[1] == "run"
    assert "--network=host" in cmd and "--ipc=host" in cmd
    assert "--pid=host" in cmd
    # shm + session dir shared: data plane unchanged inside the container
    assert "/dev/shm:/dev/shm" in cmd
    assert "/dev/shm/ray_tpu/session_x:/dev/shm/ray_tpu/session_x" in cmd
    # cluster env rides in; unrelated host env does not
    assert "RAY_TPU_GCS_ADDR=127.0.0.1:1234" in cmd
    assert not any(c.startswith("HOME=") for c in cmd)
    # runtime_env env_vars forward explicitly (prefix filter can't know them)
    assert "MY_APP_FLAG=on" in cmd
    assert cmd[cmd.index("--cidfile") + 1] == "/tmp/x.cid"
    # run_options precede the image; the worker command is the tail
    assert cmd[cmd.index("--cap-drop"):][:2] == ["--cap-drop", "ALL"]
    assert cmd.index("myimg:v1") > cmd.index("ALL")
    assert cmd[-3:] == ["python", "-m", "ray_tpu._private.worker_main"]


@pytest.fixture
def fake_engine(tmp_path):
    record = tmp_path / "engine_calls.jsonl"
    script = tmp_path / "fake_engine.py"
    script.write_text(f"""#!{sys.executable}
import json, os, sys
args = sys.argv[1:]
with open({str(record)!r}, "a") as f:
    f.write(json.dumps(args) + "\\n")
# exec the inner worker command (everything after the image token —
# located as the arg before the trailing 'python')
i = args.index("python")
os.execv({sys.executable!r}, [{sys.executable!r}] + args[i + 1:])
""")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return str(script), str(record)


@pytest.fixture
def forking_engine(tmp_path):
    """Fake engine that FORKS the worker (subprocess) instead of exec'ing
    it in place — the real podman/docker shape, where the worker's
    os.getpid() differs from the engine client's pid the raylet spawned.
    Registration must therefore resolve via the spawn key, not the pid."""
    record = tmp_path / "fork_engine_calls.jsonl"
    script = tmp_path / "forking_engine.py"
    script.write_text(f"""#!{sys.executable}
import json, os, subprocess, sys
args = sys.argv[1:]
with open({str(record)!r}, "a") as f:
    f.write(json.dumps(args) + "\\n")
# env rides -e K=V flags, exactly like a real engine invocation
env = dict(os.environ)
i = 0
while i < len(args):
    if args[i] == "-e" and "=" in args[i + 1]:
        k, v = args[i + 1].split("=", 1)
        env[k] = v
        i += 2
    else:
        i += 1
# run the inner worker command as a CHILD (pid != our pid), like conmon
j = args.index("python")
rc = subprocess.run([sys.executable] + args[j + 1:], env=env).returncode
sys.exit(rc)
""")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return str(script), str(record)


def test_worker_registers_through_forking_engine(forking_engine, monkeypatch):
    """ADVICE high: with a forking engine the worker's reported pid never
    matches the raylet's engine-client pid — before the spawn-id fix,
    registration timed out and the raylet looped spawning containers."""
    engine, record = forking_engine
    monkeypatch.setenv("RAY_TPU_container_runtime", engine)
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"container": {"image": "fake:latest"}})
        def whoami():
            return os.getpid(), os.getppid(), \
                os.environ.get("RAY_TPU_WORKER_SPAWN_ID")

        pid, ppid, spawn_id = ray_tpu.get(whoami.remote(), timeout=120)
        assert spawn_id, "spawn key did not reach the containerized worker"
        with open(record) as f:
            calls = [json.loads(line) for line in f]
        assert calls, "worker never went through the engine"
        # the pid mismatch was actually exercised: the worker is a CHILD
        # of the engine client, so its pid differs from what the raylet
        # keyed all_workers by
        assert any(f"RAY_TPU_WORKER_SPAWN_ID={spawn_id}" in arg
                   for call in calls for arg in call)
        assert pid != ppid
        # and the registered worker serves follow-up tasks normally
        @ray_tpu.remote(runtime_env={"container": {"image": "fake:latest"}})
        def again():
            return "ok"

        assert ray_tpu.get(again.remote(), timeout=60) == "ok"
    finally:
        ray_tpu.shutdown()


def test_worker_runs_through_engine_end_to_end(fake_engine, monkeypatch):
    engine, record = fake_engine
    monkeypatch.setenv("RAY_TPU_container_runtime", engine)
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"container": {"image": "fake:latest"}})
        def whoami():
            return os.getpid()

        pid = ray_tpu.get(whoami.remote(), timeout=120)
        assert pid > 0
        with open(record) as f:
            calls = [json.loads(line) for line in f]
        assert calls, "worker never went through the engine"
        argv = calls[-1]
        assert argv[0] == "run" and "--network=host" in argv
        assert "fake:latest" in argv
        # the containerized worker is its own pool: a plain task must NOT
        # reuse it (env-hash keyed pools)
        @ray_tpu.remote
        def plain():
            return "ok"

        assert ray_tpu.get(plain.remote(), timeout=60) == "ok"
        assert len([json.loads(line) for line in open(record)]) == \
            len(calls), "plain task wrongly spawned through the engine"
    finally:
        ray_tpu.shutdown()
